"""Layer unit tests — forward shape/value checks against numpy golden
computations (the KerasRunner-style golden strategy, SURVEY.md §4.1,
with numpy as the reference implementation instead of a Keras
subprocess)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.pipeline.api.keras import Input, Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import (
    Activation, BatchNormalization, Dense, Dropout, Embedding, Flatten,
    Highway, LayerNorm, Masking, MaxoutDense, Merge, Permute, RepeatVector,
    Reshape, merge,
)

RNG = jax.random.PRNGKey(0)


def apply_layer(layer, x, input_shape=None, training=False, rng=None):
    variables = layer.init(RNG, input_shape or x.shape[1:])
    out, _ = layer.apply(variables["params"], x,
                         state=variables["state"], training=training,
                         rng=rng)
    return variables, out


class TestDense:
    def test_forward_matches_numpy(self):
        x = np.random.RandomState(0).randn(4, 7).astype(np.float32)
        layer = Dense(5)
        variables, out = apply_layer(layer, x)
        w = np.asarray(variables["params"]["kernel"])
        b = np.asarray(variables["params"]["bias"])
        np.testing.assert_allclose(np.asarray(out), x @ w + b,
                                   rtol=2e-2, atol=2e-2)
        assert out.shape == (4, 5)

    def test_3d_input(self):
        x = np.ones((2, 3, 7), np.float32)
        layer = Dense(4, activation="relu")
        _, out = apply_layer(layer, x)
        assert out.shape == (2, 3, 4)
        assert layer.compute_output_shape((None, 3, 7)) == (None, 3, 4)

    def test_no_bias(self):
        x = np.ones((2, 3), np.float32)
        layer = Dense(4, bias=False)
        variables, _ = apply_layer(layer, x)
        assert "bias" not in variables["params"]


class TestShapeOps:
    def test_flatten(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        _, out = apply_layer(Flatten(), x)
        assert out.shape == (2, 12)

    def test_reshape_with_minus_one(self):
        layer = Reshape((4, -1))
        x = np.zeros((2, 3, 8), np.float32)
        _, out = apply_layer(layer, x)
        assert out.shape == (2, 4, 6)
        assert layer.compute_output_shape((None, 3, 8)) == (None, 4, 6)

    def test_permute(self):
        layer = Permute((2, 1))
        x = np.zeros((2, 3, 5), np.float32)
        _, out = apply_layer(layer, x)
        assert out.shape == (2, 5, 3)

    def test_repeat_vector(self):
        x = np.ones((2, 6), np.float32)
        _, out = apply_layer(RepeatVector(4), x)
        assert out.shape == (2, 4, 6)

    def test_masking(self):
        x = np.array([[[0.0, 0.0], [1.0, 2.0]]], np.float32)
        _, out = apply_layer(Masking(0.0), x)
        np.testing.assert_array_equal(np.asarray(out)[0, 0], [0.0, 0.0])
        np.testing.assert_array_equal(np.asarray(out)[0, 1], [1.0, 2.0])


class TestDropout:
    def test_identity_at_inference(self):
        x = np.random.randn(8, 16).astype(np.float32)
        _, out = apply_layer(Dropout(0.5), x, training=False)
        np.testing.assert_array_equal(np.asarray(out), x)

    def test_scales_when_training(self):
        x = np.ones((64, 128), np.float32)
        _, out = apply_layer(Dropout(0.5), x, training=True,
                             rng=jax.random.PRNGKey(1))
        arr = np.asarray(out)
        assert set(np.unique(arr)).issubset({0.0, 2.0})
        assert abs(arr.mean() - 1.0) < 0.1

    def test_requires_rng_when_training(self):
        x = np.ones((2, 2), np.float32)
        with pytest.raises(ValueError):
            apply_layer(Dropout(0.5), x, training=True)


class TestEmbedding:
    def test_lookup(self):
        layer = Embedding(10, 4)
        ids = np.array([[1, 2], [3, 4]], np.int32)
        variables, out = apply_layer(layer, ids, input_shape=(2,))
        assert out.shape == (2, 2, 4)
        table = np.asarray(variables["params"]["embeddings"])
        np.testing.assert_allclose(np.asarray(out)[0, 0], table[1])

    def test_mask_zero(self):
        layer = Embedding(10, 4, mask_zero=True)
        ids = np.array([[0, 2]], np.int32)
        _, out = apply_layer(layer, ids, input_shape=(2,))
        np.testing.assert_array_equal(np.asarray(out)[0, 0], np.zeros(4))


class TestNormalization:
    def test_batchnorm_train_and_infer(self):
        x = np.random.RandomState(0).randn(32, 6).astype(np.float32) * 3 + 1
        layer = BatchNormalization()
        variables = layer.init(RNG, (6,))
        out, new_state = layer.apply(
            variables["params"], x, state=variables["state"], training=True)
        arr = np.asarray(out)
        np.testing.assert_allclose(arr.mean(axis=0), 0.0, atol=1e-4)
        np.testing.assert_allclose(arr.std(axis=0), 1.0, atol=1e-2)
        # moving stats moved toward batch stats
        assert not np.allclose(np.asarray(new_state["moving_mean"]), 0.0)
        # inference path uses moving stats, returns state unchanged
        out2, state2 = layer.apply(
            variables["params"], x, state=new_state, training=False)
        assert state2 is new_state

    def test_layernorm(self):
        x = np.random.RandomState(0).randn(4, 9).astype(np.float32)
        _, out = apply_layer(LayerNorm(), x)
        arr = np.asarray(out)
        np.testing.assert_allclose(arr.mean(axis=-1), 0.0, atol=1e-5)


class TestMergeAndGraph:
    def test_merge_modes(self):
        a = np.ones((2, 3), np.float32)
        b = 2 * np.ones((2, 3), np.float32)
        for mode, expect in [("sum", 3.0), ("mul", 2.0), ("max", 2.0),
                             ("min", 1.0), ("ave", 1.5)]:
            layer = Merge(mode=mode)
            out, _ = layer.apply({}, [a, b])
            assert np.allclose(np.asarray(out), expect), mode
        out, _ = Merge(mode="concat").apply({}, [a, b])
        assert out.shape == (2, 6)

    def test_graph_model_two_branches(self):
        left = Input(shape=(4,))
        right = Input(shape=(4,))
        la = Dense(8, activation="relu")(left)
        rb = Dense(8, activation="relu")(right)
        joined = merge([la, rb], mode="concat")
        out = Dense(2)(joined)
        model = Model([left, right], out)
        model.init(RNG)
        x1 = np.ones((3, 4), np.float32)
        x2 = np.zeros((3, 4), np.float32)
        variables = model.get_variables()
        y, _ = model.apply(variables["params"], [x1, x2],
                           state=variables["state"])
        assert y.shape == (3, 2)

    def test_shared_layer(self):
        shared = Dense(5)
        i1, i2 = Input(shape=(3,)), Input(shape=(3,))
        o = merge([shared(i1), shared(i2)], mode="sum")
        model = Model([i1, i2], o)
        variables = model.init(RNG)
        # one params entry for the shared layer
        assert sum(1 for k in variables["params"] if "dense" in k) == 1
        x = np.ones((2, 3), np.float32)
        y, _ = model.apply(variables["params"], [x, x],
                           state=variables["state"])
        y1, _ = shared.apply(variables["params"][shared.name], x)
        np.testing.assert_allclose(np.asarray(y), 2 * np.asarray(y1),
                                   rtol=1e-5)


class TestSequential:
    def test_stack_and_shapes(self):
        model = Sequential()
        model.add(Dense(16, activation="relu", input_shape=(8,)))
        model.add(Dropout(0.2))
        model.add(Dense(4))
        model.add(Activation("softmax"))
        assert model.get_output_shape() == (None, 4)
        variables = model.init(RNG)
        x = np.random.randn(5, 8).astype(np.float32)
        y, _ = model.apply(variables["params"], x,
                           state=variables["state"])
        arr = np.asarray(y)
        assert arr.shape == (5, 4)
        np.testing.assert_allclose(arr.sum(axis=-1), 1.0, rtol=1e-5)

    def test_first_layer_needs_shape(self):
        model = Sequential()
        with pytest.raises(ValueError):
            model.add(Dense(4))

    def test_nested_sequential(self):
        inner = Sequential()
        inner.add(Dense(6, input_shape=(8,)))
        outer = Sequential()
        outer.add(inner)
        outer.add(Dense(3))
        variables = outer.init(RNG)
        x = np.ones((2, 8), np.float32)
        y, _ = outer.apply(variables["params"], x,
                           state=variables["state"])
        assert y.shape == (2, 3)


class TestMisc:
    def test_highway_and_maxout(self):
        x = np.random.randn(4, 6).astype(np.float32)
        _, out = apply_layer(Highway(), x)
        assert out.shape == (4, 6)
        _, out = apply_layer(MaxoutDense(3, nb_feature=2), x)
        assert out.shape == (4, 3)

    def test_jit_composes(self):
        model = Sequential()
        model.add(Dense(4, input_shape=(3,)))
        variables = model.init(RNG)

        @jax.jit
        def fwd(params, x):
            y, _ = model.apply(params, x, state={})
            return y

        out = fwd(variables["params"], jnp.ones((2, 3)))
        assert out.shape == (2, 4)


class TestRegularizersModule:
    def test_creators_and_penalty(self):
        """keras.regularizers (ref pyzoo keras/regularizers.py): the
        (l1,l2) pairs wire into Layer.regularization_loss."""
        import jax
        import jax.numpy as jnp
        from analytics_zoo_tpu.pipeline.api.keras import regularizers
        from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

        assert regularizers.l1(0.3) == (0.3, 0.0)
        assert regularizers.l2(0.2) == (0.0, 0.2)
        assert regularizers.l1l2(0.3, 0.2) == (0.3, 0.2)

        layer = Dense(4, input_shape=(3,),
                      W_regularizer=regularizers.l1l2(0.5, 0.25))
        v = layer.init(jax.random.PRNGKey(0))
        w = v["params"]["kernel"]
        expect = 0.5 * float(jnp.sum(jnp.abs(w))) \
            + 0.25 * float(jnp.sum(jnp.square(w)))
        got = float(layer.regularization_loss(v["params"]))
        assert abs(got - expect) < 1e-5
