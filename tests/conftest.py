"""Test harness: simulate an 8-device TPU pod on CPU.

Mirrors the reference's test strategy (SURVEY.md §4): distributed paths
are exercised on a multi-partition local backend — here an 8-device
virtual CPU mesh via XLA_FLAGS, the analogue of `local[N]` Spark specs.
Env vars must be set before jax initialises.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon site hook forces jax_platforms="axon,cpu"; tests must run on
# the virtual 8-device CPU mesh, so override before backend init.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_context():
    """Reset global state between tests: context and layer naming (so
    param init rng streams don't depend on test execution order)."""
    from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
    Layer.reset_name_counters()
    yield
    from analytics_zoo_tpu.common.config import reset_config
    from analytics_zoo_tpu.common.zoo_context import reset_zoo_context
    reset_zoo_context()
    # also drop the config: programmatic sets now survive context
    # re-init by design, which across TESTS would leak one test's
    # knobs into the next
    reset_config()


@pytest.fixture
def f32_policy():
    """Full-f32 dtype policy for golden-oracle comparisons (default
    policy is bf16 compute, which would swamp 1e-4 tolerances)."""
    from analytics_zoo_tpu.ops import dtypes
    old = dtypes.get_policy()
    dtypes.set_policy(param_dtype="float32", compute_dtype="float32")
    yield
    dtypes.restore_policy(old)
