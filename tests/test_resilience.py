"""Resilience-layer unit tests: deterministic chaos injection, failure
classification, the time-windowed retry budget, the recovery policy
engine, topology math, launcher death forensics, and serving
result-write backpressure.

Estimator-level fault-injection acceptance (mesh re-formation,
bit-exact elastic resume, degraded exit) lives in
tests/test_elastic_recovery.py; together the two files are the CI
``chaos`` shard (dev/run-tests chaos)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from analytics_zoo_tpu.resilience import chaos as chaos_lib
from analytics_zoo_tpu.resilience.chaos import (
    ChaosPlan, FaultSpec, LostHost, PoisonedState, TransientFault,
    active_chaos, clear_chaos, install_chaos)
from analytics_zoo_tpu.resilience.detector import (
    FailureClass, HostHeartbeat, classify_exit, classify_failure,
    is_preemption_like, read_heartbeats, stale_hosts)
from analytics_zoo_tpu.resilience.policy import (
    RecoveryAction, RecoveryPolicy, RetryBudget)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leaked_chaos():
    clear_chaos()
    yield
    clear_chaos()


# ------------------------------------------------------------- chaos
class TestChaosPlan:
    def test_raising_kinds(self):
        for kind, exc_type in (("raise", TransientFault),
                               ("poison", PoisonedState),
                               ("lose_host", LostHost)):
            plan = ChaosPlan([FaultSpec(site="s", at_step=2, kind=kind)])
            plan.trip("s", 0)
            plan.trip("s", 1)
            with pytest.raises(exc_type):
                plan.trip("s", 2)

    def test_fires_once_then_disarmed(self):
        """A recovery that restarts a step counter must not re-trip
        the same fault (that would livelock the retry machinery)."""
        plan = ChaosPlan([FaultSpec(site="s", at_step=3)])
        with pytest.raises(TransientFault):
            plan.trip("s", 3)
        # replay from 0 passes step 3 cleanly this time
        for step in range(10):
            plan.trip("s", step)

    def test_times_fires_consecutive_steps(self):
        plan = ChaosPlan([FaultSpec(site="s", at_step=1, times=2)])
        plan.trip("s", 0)
        with pytest.raises(TransientFault):
            plan.trip("s", 1)
        with pytest.raises(TransientFault):
            plan.trip("s", 2)
        plan.trip("s", 3)

    def test_site_and_process_filtering(self, monkeypatch):
        plan = ChaosPlan([FaultSpec(site="a", at_step=0,
                                    process_index=1)])
        plan.trip("b", 0)                       # other site: no fire
        monkeypatch.setenv("ZOO_TPU_PROCESS_ID", "0")
        plan.trip("a", 0)                       # other process: no fire
        monkeypatch.setenv("ZOO_TPU_PROCESS_ID", "1")
        with pytest.raises(TransientFault):
            plan.trip("a", 0)

    def test_slow_kind_delays_not_raises(self):
        plan = ChaosPlan([FaultSpec(site="s", at_step=0, kind="slow",
                                    sleep_s=0.05)])
        t0 = time.perf_counter()
        plan.trip("s", 0)
        assert time.perf_counter() - t0 >= 0.05

    def test_lose_host_carries_survivors(self):
        plan = ChaosPlan([FaultSpec(site="s", at_step=0,
                                    kind="lose_host",
                                    survivors=[0, 1, 2])])
        with pytest.raises(LostHost) as ei:
            plan.trip("s", 0)
        assert ei.value.survivors == [0, 1, 2]

    def test_env_round_trip(self, monkeypatch):
        plan = ChaosPlan([FaultSpec(site="worker.step", at_step=4,
                                    kind="kill", exit_code=137,
                                    process_index=0)])
        monkeypatch.setenv(chaos_lib.ENV_CHAOS, plan.to_json())
        clear_chaos()                # force the env re-read
        loaded = active_chaos()
        assert loaded is not None
        (f,) = loaded.faults
        assert (f.site, f.at_step, f.kind, f.exit_code,
                f.process_index) == ("worker.step", 4, "kill", 137, 0)

    def test_unparseable_env_is_ignored(self, monkeypatch):
        monkeypatch.setenv(chaos_lib.ENV_CHAOS, "{not json")
        clear_chaos()
        assert active_chaos() is None

    def test_install_overrides_env(self, monkeypatch):
        monkeypatch.setenv(chaos_lib.ENV_CHAOS,
                           ChaosPlan([FaultSpec("s", 0)]).to_json())
        clear_chaos()
        install_chaos(None)
        assert active_chaos() is None

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultSpec(site="s", at_step=0, kind="meteor")


# ---------------------------------------------------- classification
class TestFailureClassification:
    @pytest.mark.parametrize("exc,expected", [
        (TransientFault("x"), FailureClass.TRANSIENT),
        (LostHost("x"), FailureClass.LOST_HOST),
        (PoisonedState("x"), FailureClass.POISONED_STATE),
        (RuntimeError("DEADLINE_EXCEEDED: rpc to worker timed out "
                      "after 60s"), FailureClass.TRANSIENT),
        (OSError("Connection reset by peer"), FailureClass.TRANSIENT),
        (RuntimeError("RESOURCE_EXHAUSTED: out of memory while "
                      "allocating"), FailureClass.TRANSIENT),
        (RuntimeError("coordination service: process 3 disconnected"),
         FailureClass.LOST_HOST),
        (RuntimeError("host tpu-worker-2 unreachable: deadline "
                      "exceeded"), FailureClass.LOST_HOST),
        (RuntimeError("worker preempted by scheduler"),
         FailureClass.LOST_HOST),
        (RuntimeError("heartbeat missed for 30s"),
         FailureClass.LOST_HOST),
        (FloatingPointError("loss became NaN at step 12"),
         FailureClass.POISONED_STATE),
        (ValueError("shapes (3,4) and (5,6) not aligned"),
         FailureClass.UNKNOWN),
    ])
    def test_table(self, exc, expected):
        assert classify_failure(exc) is expected

    def test_lost_host_outranks_transient(self):
        # a dead host's symptom usually INCLUDES a timeout; retrying
        # onto the dead topology would hang, so lost_host must win
        exc = RuntimeError("worker 5 unreachable (connection reset)")
        assert classify_failure(exc) is FailureClass.LOST_HOST

    def test_watchdog_types_unrecoverable_by_name(self):
        from analytics_zoo_tpu.observability.watchdog import (
            TrainingHalted)
        from analytics_zoo_tpu.pipeline.estimator.estimator import (
            _UnrecoverableTraining)
        assert classify_failure(TrainingHalted("halt")) is \
            FailureClass.UNRECOVERABLE
        assert classify_failure(_UnrecoverableTraining("gone")) is \
            FailureClass.UNRECOVERABLE

    def test_exit_codes(self):
        assert classify_exit(None) == "running"
        assert classify_exit(0) == "ok"
        assert classify_exit(3) == "error(3)"
        assert classify_exit(-9) == "signal(SIGKILL)"
        assert classify_exit(137) == "signal(SIGKILL)"   # 128+9
        assert classify_exit(143) == "signal(SIGTERM)"
        assert is_preemption_like(classify_exit(137))
        assert is_preemption_like(classify_exit(-15))
        assert not is_preemption_like(classify_exit(3))
        assert not is_preemption_like(classify_exit(0))


# -------------------------------------------------------- heartbeats
class TestHeartbeats:
    def test_beat_is_throttled_by_interval(self, tmp_path):
        clk = [0.0]
        hb = HostHeartbeat(str(tmp_path / "host-0"), interval_s=5.0,
                           clock=lambda: clk[0])
        assert hb.beat(step=1) is True
        assert hb.beat(step=2) is False           # within interval
        clk[0] = 6.0
        assert hb.beat(step=3) is True
        assert hb.beat(step=4, force=True) is True

    def test_read_and_stale(self, tmp_path):
        run = tmp_path / "run"
        hb = HostHeartbeat(str(run / "host-0"), interval_s=0.0)
        hb.beat(step=7)
        beats = read_heartbeats(str(run))
        assert beats[0]["step"] == 7
        assert beats[0]["pid"] == os.getpid()
        now = beats[0]["time"]
        # fresh within timeout; host 1 never beat at all
        assert stale_hosts(str(run), 30.0, expected=2, now=now) == [1]
        # everyone stale far in the future
        assert stale_hosts(str(run), 30.0, expected=2,
                           now=now + 100.0) == [0, 1]
        # without `expected`, only known slots are judged
        assert stale_hosts(str(run), 30.0, now=now) == []


# ------------------------------------------------------ retry budget
class TestRetryBudget:
    def test_consume_and_exhaust(self):
        clk = [0.0]
        b = RetryBudget(2, 10.0, clock=lambda: clk[0])
        assert b.consume() is True
        assert b.consume() is True
        assert b.consume() is False          # 3rd failure in window

    def test_refills_past_window_boundary(self):
        clk = [0.0]
        b = RetryBudget(1, 10.0, clock=lambda: clk[0])
        assert b.consume() is True
        clk[0] = 10.0                         # exactly the boundary:
        assert b.consume() is False           # NOT yet refilled (>)
        clk[0] = 20.1                         # past the boundary
        assert b.consume() is True

    def test_window_measures_between_failures(self):
        # parity with the reference: the interval is since the LAST
        # failure, not since the refill — a slow drip of failures
        # (one per window) never exhausts the budget
        clk = [0.0]
        b = RetryBudget(1, 10.0, clock=lambda: clk[0])
        for t in (0.0, 11.0, 22.0, 33.0):
            clk[0] = t
            assert b.consume() is True


# ----------------------------------------------------- policy engine
class TestRecoveryPolicy:
    def _policy(self, retries=3, elastic=True, max_reformations=2):
        return RecoveryPolicy(RetryBudget(retries, 100.0),
                              elastic=elastic,
                              max_reformations=max_reformations)

    def test_poisoned_always_raises(self):
        d = self._policy().decide(PoisonedState("nan"),
                                  have_checkpoint=True)
        assert d.action is RecoveryAction.RAISE
        assert d.failure_class is FailureClass.POISONED_STATE

    def test_unrecoverable_always_raises(self):
        from analytics_zoo_tpu.observability.watchdog import (
            TrainingHalted)
        d = self._policy().decide(TrainingHalted("halt"),
                                  have_checkpoint=True)
        assert d.action is RecoveryAction.RAISE

    def test_lost_host_reforms_then_degrades(self):
        p = self._policy(max_reformations=1)
        d1 = p.decide(LostHost("gone"), have_checkpoint=True)
        assert d1.action is RecoveryAction.REFORM_MESH
        d2 = p.decide(LostHost("gone again"), have_checkpoint=True)
        assert d2.action is RecoveryAction.DEGRADE

    def test_lost_host_without_elastic_uses_retry_budget(self):
        p = self._policy(retries=1, elastic=False)
        d1 = p.decide(LostHost("gone"), have_checkpoint=True)
        assert d1.action is RecoveryAction.RETRY
        d2 = p.decide(LostHost("gone"), have_checkpoint=True)
        assert d2.action is RecoveryAction.RAISE

    def test_transient_needs_checkpoint(self):
        d = self._policy().decide(TransientFault("flake"),
                                  have_checkpoint=False)
        assert d.action is RecoveryAction.RAISE
        assert "model_dir" in d.reason

    def test_transient_budget_exhaustion(self):
        p = self._policy(retries=1)
        assert p.decide(TransientFault("a"), True).action \
            is RecoveryAction.RETRY
        d = p.decide(TransientFault("b"), True)
        assert d.action is RecoveryAction.RAISE
        assert "exhausted" in d.reason

    def test_unknown_treated_like_transient(self):
        d = self._policy().decide(ValueError("???"),
                                  have_checkpoint=True)
        assert d.action is RecoveryAction.RETRY
        assert d.failure_class is FailureClass.UNKNOWN


# ----------------------------------------------------- topology math
class TestTopology:
    def test_viable_data_degree(self):
        from analytics_zoo_tpu.resilience.recovery import (
            viable_data_degree)
        assert viable_data_degree(8, 32) == 8
        assert viable_data_degree(6, 32) == 4    # idle 2 survivors
        assert viable_data_degree(3, 32) == 2
        assert viable_data_degree(1, 32) == 1
        assert viable_data_degree(0, 32) == 0
        assert viable_data_degree(8, 0) == 0
        assert viable_data_degree(16, 6) == 6    # capped by batch

    def test_surviving_devices_filters_by_id(self):
        import jax

        from analytics_zoo_tpu.resilience.recovery import (
            surviving_devices)
        ids = [d.id for d in jax.devices()[:3]]
        got = surviving_devices(LostHost("x", survivors=ids))
        assert [d.id for d in got] == ids
        # no explicit survivors: ask the backend
        assert len(surviving_devices(LostHost("x"))) == \
            len(jax.devices())

    def test_reform_mesh_and_no_viable(self):
        import jax

        from analytics_zoo_tpu.common.zoo_context import get_zoo_context
        from analytics_zoo_tpu.observability import get_registry
        from analytics_zoo_tpu.resilience.recovery import (
            NoViableTopology, reform_mesh)
        before = get_registry().counter(
            "mesh_reformations_total", "").value
        mesh = reform_mesh(jax.devices()[:6], batch_size=32)
        assert mesh.shape["data"] == 4           # largest divisor of 32
        assert mesh.devices.size == 4
        # the live context now runs on the surviving topology
        assert get_zoo_context().mesh is mesh
        assert get_registry().counter(
            "mesh_reformations_total", "").value == before + 1
        with pytest.raises(NoViableTopology):
            reform_mesh([], batch_size=32)


# ------------------------------------------- launcher death forensics
def _write(path, body):
    path.write_text(textwrap.dedent(body))
    return str(path)


class TestLauncherForensics:
    def test_wait_reports_first_failure_not_just_codes(self, tmp_path):
        from analytics_zoo_tpu.parallel.launcher import ZooCluster
        script = _write(tmp_path / "w.py", """
            import os, sys, time
            pid = int(os.environ["ZOO_TPU_PROCESS_ID"])
            # worker 1 dies FIRST (and worst); 0 and 2 exit clean
            # later (margin generous: interpreter startup under a
            # loaded CI host can add hundreds of ms of skew)
            time.sleep(0.2 if pid == 1 else 3.0)
            sys.exit(7 if pid == 1 else 0)
        """)
        cluster = ZooCluster(num_processes=3)
        cluster.start(script)
        codes = cluster.wait(timeout=30)
        assert list(codes) == [0, 7, 0]          # old contract intact
        assert codes.first_failure == {
            "process_index": 1, "code": 7,
            "classification": "error(7)"}
        assert codes.exit_order[0][0] == 1       # died first

    def test_stop_all_escalates_term_to_kill_and_reaps(self, tmp_path):
        from analytics_zoo_tpu.parallel.launcher import ProcessMonitor
        script = _write(tmp_path / "stubborn.py", """
            import signal, time
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
            print("armed", flush=True)
            time.sleep(600)
        """)
        mon = ProcessMonitor()
        procs = [subprocess.Popen([sys.executable, script],
                                  stdout=subprocess.PIPE)
                 for _ in range(2)]
        for i, p in enumerate(procs):
            p.stdout.readline()       # SIGTERM handler installed
            mon.register(p, index=i)
        codes = mon.stop_all(timeout=1.0, kill_grace=10.0)
        # TERM was ignored; the per-process KILL escalation reaped both
        assert codes == {0: -signal.SIGKILL, 1: -signal.SIGKILL}
        assert mon.alive() == 0
        assert all(p.poll() is not None for p in procs)   # no zombies

    def test_chaos_kill_through_cluster_env(self, tmp_path):
        """A scripted kill fault rides the ZOO_TPU_CHAOS env into a
        launched worker and fires at the scripted step in the right
        process — the launcher-level half of fault injection.  The
        worker loads chaos by FILE PATH (its stdlib-only contract), so
        this needs no jax import in the children."""
        from analytics_zoo_tpu.parallel.launcher import ZooCluster
        script = _write(tmp_path / "w.py", f"""
            import importlib.util, sys
            spec = importlib.util.spec_from_file_location(
                "chaos", {chaos_lib.__file__!r})
            chaos = importlib.util.module_from_spec(spec)
            sys.modules["chaos"] = chaos   # @dataclass needs the entry
            spec.loader.exec_module(chaos)
            plan = chaos.active_chaos()
            assert plan is not None, "chaos env missing"
            for step in range(50):
                plan.trip(chaos.SITE_WORKER_STEP, step)
            sys.exit(0)
        """)
        plan = ChaosPlan([FaultSpec(site=chaos_lib.SITE_WORKER_STEP,
                                    at_step=7, kind="kill",
                                    exit_code=137, process_index=1)])
        cluster = ZooCluster(num_processes=3, chaos=plan)
        cluster.start(script)
        codes = cluster.wait(timeout=30)
        assert list(codes) == [0, 137, 0]
        assert codes.first_failure["process_index"] == 1
        assert is_preemption_like(codes.first_failure["classification"])

    def test_check_health_flags_dead_worker(self, tmp_path):
        from analytics_zoo_tpu.observability import get_registry
        from analytics_zoo_tpu.parallel.launcher import ZooCluster
        script = _write(tmp_path / "w.py", """
            import os, sys, time
            if int(os.environ["ZOO_TPU_PROCESS_ID"]) == 1:
                sys.exit(3)
            time.sleep(600)
        """)
        cluster = ZooCluster(num_processes=3)
        cluster.start(script)
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                health = cluster.check_health()
                if health.missing:
                    break
                time.sleep(0.05)
            assert health.missing == [1]
            assert health.alive == 2
            assert not health.ok
            assert health.first_death["process_index"] == 1
            assert health.first_death["classification"] == "error(3)"
            reg = get_registry()
            assert reg.gauge("cluster_hosts_expected", "").value == 3.0
            assert reg.gauge("cluster_hosts_missing", "").value == 1.0
        finally:
            cluster.stop()

    def test_degraded_worker_exits_17_and_launcher_honors_it(
            self, tmp_path):
        """The shipped DegradedTraining -> DEGRADED_EXIT_CODE mapping
        (resilience.degraded_exit) speaks the launcher protocol end to
        end: the degraded worker prints its structured result and
        exits 17, which wait() surfaces distinctly from a crash."""
        from analytics_zoo_tpu.parallel.launcher import ZooCluster
        from analytics_zoo_tpu.resilience.policy import (
            DEGRADED_EXIT_CODE)
        script = _write(tmp_path / "w.py", """
            import os
            from analytics_zoo_tpu.resilience import (
                DegradedTraining, degraded_exit)
            with degraded_exit():
                if os.environ["ZOO_TPU_PROCESS_ID"] == "0":
                    raise DegradedTraining(
                        "no viable topology",
                        result={"status": "degraded",
                                "failure_class": "lost_host"})
        """)
        cluster = ZooCluster(num_processes=2,
                             env={"PYTHONPATH": REPO_ROOT})
        procs = []
        for pid in range(2):
            proc = subprocess.Popen(
                [sys.executable, script],
                env=cluster.worker_env(pid),
                stdout=subprocess.PIPE, text=True)
            procs.append(proc)
            cluster.monitor.register(proc, index=pid)
        codes = cluster.wait(timeout=60)
        assert list(codes) == [DEGRADED_EXIT_CODE, 0]
        # an orderly degraded ending is NOT a failure/death: it must
        # never be named the root cause, or counted missing
        assert codes.first_failure is None
        health = cluster.check_health()
        assert health.degraded == [0]
        assert health.missing == []
        assert health.first_death is None
        # the structured result rode the degraded worker's stdout
        result = json.loads(procs[0].stdout.read().strip())
        assert result == {"status": "degraded",
                          "failure_class": "lost_host"}

    def test_reused_run_dir_drops_stale_heartbeats(self, tmp_path):
        """A run_dir reused across runs must not carry the previous
        run's heartbeats — check_health would flag a live,
        still-initializing worker as stale."""
        from analytics_zoo_tpu.parallel.launcher import ZooCluster
        run_dir = tmp_path / "run"
        slot = run_dir / "host-0"
        HostHeartbeat(str(slot), interval_s=0.0).beat(step=99)
        assert read_heartbeats(str(run_dir)) != {}
        ZooCluster(num_processes=1, run_dir=str(run_dir))
        assert read_heartbeats(str(run_dir)) == {}

    def test_clean_exit_is_not_missing(self, tmp_path):
        from analytics_zoo_tpu.parallel.launcher import ZooCluster
        script = _write(tmp_path / "w.py", "import sys; sys.exit(0)")
        cluster = ZooCluster(num_processes=2)
        cluster.start(script)
        cluster.wait(timeout=30)
        health = cluster.check_health()
        assert health.missing == []
        assert health.ok
        assert health.first_death is None


# ------------------------------------------ serving write backpressure
def _enqueue_npy(broker, uri, arr):
    import base64
    import io

    from analytics_zoo_tpu.serving.server import INPUT_STREAM
    buf = io.BytesIO()
    np.save(buf, arr)
    broker.xadd(INPUT_STREAM, {
        "uri": uri, "data": base64.b64encode(buf.getvalue()).decode(),
        "request_id": f"req-{uri}"})


class _StubModel:
    def predict(self, x):
        return np.tile(np.array([2.0, 1.0, 0.0], np.float32),
                       (len(x), 1))


class TestServingWriteBackpressure:
    def _serving(self, broker, retries=3):
        from analytics_zoo_tpu.serving.server import (
            ClusterServing, ServingConfig)
        return ClusterServing(
            _StubModel(),
            ServingConfig(batch_size=2, top_n=1,
                          result_write_retries=retries),
            broker=broker)

    def test_abandons_to_dead_letter_instead_of_crashing(self):
        from analytics_zoo_tpu.observability import get_registry
        from analytics_zoo_tpu.serving.redis_client import EmbeddedBroker
        from analytics_zoo_tpu.serving.server import DEAD_LETTER_STREAM

        class ResultWritesFail(EmbeddedBroker):
            def hset(self, key, fields):
                if key.startswith("result:"):
                    raise ConnectionError("broker write refused")
                return super().hset(key, fields)

        broker = ResultWritesFail()
        serving = self._serving(broker, retries=3)
        # readiness must see the outage: configure the error-rate gate
        serving.config.healthz_max_error_rate = 0.5
        _enqueue_npy(broker, "a", np.zeros((4,), np.float32))
        _enqueue_npy(broker, "b", np.zeros((4,), np.float32))
        reg = get_registry()
        abandoned = reg.counter(
            "serving_result_write_abandoned_total", "")
        retried = reg.counter("serving_redis_retry_total", "")
        errors = reg.counter("serving_errors_total", "").value
        a0, r0 = abandoned.value, retried.value
        # the old behavior raised out of the worker loop here; now:
        # processed but DELIVERED zero
        served = serving.run_once(block_ms=0)
        assert served == 0
        assert serving.total_records == 2        # processed (progress)
        assert abandoned.value - a0 == 2
        assert retried.value - r0 == 2 * 3       # 3 bounded attempts each
        # abandoned writes are failures to error accounting and the
        # /healthz window — an orchestrator pulls this worker instead
        # of routing to a black hole
        assert reg.counter("serving_errors_total",
                           "").value - errors == 2
        not_ready = serving.readiness()
        assert not_ready is not None
        assert not_ready["reason"] == "error_rate"
        # dead letter carries the correlation ids
        d = lambda v: v.decode() if isinstance(v, bytes) else v  # noqa: E731
        entries = broker.xread(DEAD_LETTER_STREAM, count=10)
        letters = [{d(k): d(v) for k, v in f.items()}
                   for _i, f in entries]
        assert sorted(l["uri"] for l in letters) == ["a", "b"]
        assert sorted(l["request_id"] for l in letters) == \
            ["req-a", "req-b"]
        assert all("ConnectionError" in l["error"] for l in letters)
        # the loop is still alive
        assert serving.run_once(block_ms=0) == 0

    def test_flaky_broker_recovers_within_budget(self):
        from analytics_zoo_tpu.observability import get_registry
        from analytics_zoo_tpu.serving.redis_client import EmbeddedBroker

        class FlakyBroker(EmbeddedBroker):
            fail_next = 2

            def hset(self, key, fields):
                if key.startswith("result:") and self.fail_next > 0:
                    self.fail_next -= 1
                    raise ConnectionError("transient broker flake")
                return super().hset(key, fields)

        broker = FlakyBroker()
        serving = self._serving(broker, retries=4)
        _enqueue_npy(broker, "ok", np.zeros((4,), np.float32))
        abandoned = get_registry().counter(
            "serving_result_write_abandoned_total", "")
        a0 = abandoned.value
        assert serving.run_once(block_ms=0) == 1
        assert abandoned.value == a0             # landed within budget
        assert broker.hgetall("result:ok")       # result is there

    def test_config_yaml_knob(self, tmp_path):
        from analytics_zoo_tpu.serving.server import ServingConfig
        cfg = tmp_path / "config.yaml"
        cfg.write_text("params:\n  batch_size: 4\n"
                       "  result_write_retries: 3\n")
        assert ServingConfig.from_yaml(
            str(cfg)).result_write_retries == 3
        assert ServingConfig().result_write_retries == 8   # default


# --------------------------------------------------- bench degradation
class TestBenchDegraded:
    def test_probe_chaos_yields_structured_degraded_exit_zero(self):
        """The r03/r04 acceptance: a contended chip (simulated by a
        scripted probe fault) makes bench emit structured
        status=degraded lines and exit 0 under --max-degraded,
        instead of timing out empty."""
        env = dict(os.environ)
        env["ZOO_TPU_CHAOS"] = ChaosPlan([FaultSpec(
            site=chaos_lib.SITE_BENCH_PROBE, at_step=0,
            kind="raise", message="simulated chip contention")]
        ).to_json()
        r = subprocess.run(
            [sys.executable, "bench.py", "--workload", "input_pipeline",
             "--max-degraded", "1"],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=180)
        lines = [json.loads(ln) for ln in r.stdout.splitlines()
                 if ln.strip().startswith("{")]
        assert r.returncode == 0, r.stdout + r.stderr
        per_workload = [ln for ln in lines
                        if ln.get("status") == "degraded"
                        and ln.get("workload") == "input_pipeline"]
        assert per_workload and per_workload[0]["value"] == 0
        assert per_workload[0]["degraded_reason"] == \
            "backend_unreachable"
        (summary,) = [ln for ln in lines
                      if ln.get("bench_status") == "degraded"]
        assert summary["within_budget"] is True
        assert summary["workloads_degraded"] == ["input_pipeline"]
        assert "simulated chip contention" in summary["error_tail"]
