"""Worker for the real 2-process pipeline-/expert-parallel test.

Launched (2x) by tests/test_multiprocess_pp_ep.py via ``ZooCluster``.
Round-4 gap: the pp microbatch routing (ppermute baton passing) and
MoE expert dispatch had only ever executed single-process on the
conftest 8-device mesh — their ``process_count > 1`` branches (gloo
cross-process collectives, global-array placement) never ran.

Mesh layouts are chosen so the INTERESTING axis spans the process
boundary:

  * pp section — mesh {pipe: 2, data: 4}: stage 0 lives on process
    0's devices, stage 1 on process 1's, so every pipeline tick's
    ppermute crosses processes.
  * ep section — mesh {expert: 2, data: 4}: half the experts live on
    each process, so dispatch/combine and the gradient psum cross
    processes every step.

Each section asserts parity against the SAME computation run
sequentially / single-device in-process (both workers compute the
identical oracle from seeded inputs), then saves results for the
parent to cross-check between workers.

Also exercises the put_epoch_source multi-host tiling refusal: rows
that don't tile this host's data-parallel share must raise, not
silently degrade.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def _put(arr, mesh, spec):
    """Global array from an identical-on-every-host numpy array."""
    sharding = NamedSharding(mesh, spec)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx])


def _stage_weights(num_stages, d, seed):
    rs = np.random.RandomState(seed)
    return [{"w": rs.randn(d, d).astype(np.float32) * 0.3,
             "b": rs.randn(d).astype(np.float32) * 0.1}
            for _ in range(num_stages)]


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def run_pp(out, mesh_lib):
    from analytics_zoo_tpu.parallel.pipeline import (
        pipeline_apply, stack_stage_params)

    mesh = mesh_lib.create_mesh({"pipe": 2, "data": 4})
    d, batch, micro = 8, 16, 4
    per_stage = _stage_weights(2, d, seed=11)
    rs = np.random.RandomState(12)
    x = rs.randn(batch, d).astype(np.float32)
    y = rs.randn(batch, d).astype(np.float32)

    stacked_np = jax.tree_util.tree_map(
        lambda *ls: np.stack(ls), *per_stage)
    stacked = jax.tree_util.tree_map(
        lambda a: _put(a, mesh, P("pipe")), stacked_np)
    xd = _put(x, mesh, P())
    yd = _put(y, mesh, P())

    def loss_fn(params, xx, yy):
        with mesh:
            h = pipeline_apply(_stage_fn, params, xx, mesh,
                               num_microbatches=micro)
        return jnp.mean((h - yy) ** 2)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(stacked, xd, yd)
    loss = float(loss)

    # sequential oracle, no mesh — identical on both workers
    h = jnp.asarray(x)
    for p in per_stage:
        h = _stage_fn(p, h)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda ps: jnp.mean(
            (_stage_fn(ps[1], _stage_fn(ps[0], jnp.asarray(x)))
             - jnp.asarray(y)) ** 2))(per_stage)
    assert abs(loss - float(ref_loss)) < 1e-5, (loss, float(ref_loss))

    # this process's stage grads (the pipe-sharded leading axis) match
    # the sequential grads for the stage its shard actually holds —
    # shard.index names the global stage slice, so no assumption about
    # how create_mesh laid processes onto the pipe axis
    for key in ("w", "b"):
        shard = grads[key].addressable_shards[0]
        stage = shard.index[0].start or 0
        local = np.asarray(shard.data)[0]
        want = np.asarray(ref_grads[stage][key])
        np.testing.assert_allclose(
            local, want, rtol=1e-4, atol=1e-5,
            err_msg=f"pp grad {key} (stage {stage})")
    out["pp_loss"] = np.float32(loss)
    out["pp_ref_loss"] = np.float32(float(ref_loss))


def run_ep(out, mesh_lib):
    import optax

    from analytics_zoo_tpu.pipeline.api.keras.layers import MoE

    mesh = mesh_lib.create_mesh({"expert": 2, "data": 4})
    d, e, rows = 8, 4, 32
    layer = MoE(num_experts=e, hidden_dim=16, capacity_factor=4.0)
    params0 = layer.init(jax.random.PRNGKey(7), (None, d))["params"]
    params0 = jax.tree_util.tree_map(np.asarray, params0)
    rs = np.random.RandomState(13)
    x = rs.randn(rows, d).astype(np.float32)
    w_true = rs.randn(d, d).astype(np.float32)
    y = x @ w_true

    tx = optax.adam(5e-2)

    def loss_fn(p, xx, yy):
        return jnp.mean((layer.call(p, xx) - yy) ** 2)

    # ---- single-device oracle trajectory (identical on both hosts)
    ref_losses = []
    p_ref = jax.tree_util.tree_map(jnp.asarray, params0)
    st_ref = tx.init(p_ref)
    for _ in range(4):
        l, g = jax.value_and_grad(loss_fn)(p_ref, jnp.asarray(x),
                                           jnp.asarray(y))
        up, st_ref = tx.update(g, st_ref, p_ref)
        p_ref = optax.apply_updates(p_ref, up)
        ref_losses.append(float(l))

    # ---- sharded trajectory over the cross-process expert mesh
    sharded = {k: _put(np.asarray(v), mesh,
                       layer.param_pspecs.get(k, P()))
               for k, v in params0.items()}
    xd = _put(x, mesh, P(("data",)))
    yd = _put(y, mesh, P(("data",)))

    @jax.jit
    def step(p, st, xx, yy):
        l, g = jax.value_and_grad(loss_fn)(p, xx, yy)
        up, st = tx.update(g, st, p)
        return optax.apply_updates(p, up), st, l

    st = jax.jit(tx.init)(sharded)
    losses = []
    for _ in range(4):
        sharded, st, l = step(sharded, st, xd, yd)
        losses.append(float(l))

    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4,
                               atol=1e-5, err_msg="ep loss trajectory")
    out["ep_losses"] = np.asarray(losses, np.float32)
    out["ep_ref_losses"] = np.asarray(ref_losses, np.float32)


def run_sp(out, mesh_lib):
    """Sequence parallelism: ring attention with the seq axis ACROSS
    processes — every ring step's ppermute moves K/V blocks over gloo.
    Loss and q/k/v grads must match dense attention computed locally."""
    from analytics_zoo_tpu.ops.attention import (
        scaled_dot_product_attention)
    from analytics_zoo_tpu.parallel.ring_attention import ring_attention

    mesh = mesh_lib.create_mesh({"seq": 2, "data": 4})
    rs = np.random.RandomState(21)
    b, h, t, d = 2, 3, 16, 8
    q, k, v = (rs.randn(b, h, t, d).astype(np.float32)
               for _ in range(3))
    spec = P(None, None, "seq", None)
    qd, kd, vd = (_put(a, mesh, spec) for a in (q, k, v))

    def loss_fn(qq, kk, vv):
        out_ = ring_attention(qq, kk, vv, mesh, causal=True)
        return jnp.mean(out_ ** 2)

    loss, grads = jax.jit(jax.value_and_grad(
        loss_fn, argnums=(0, 1, 2)))(qd, kd, vd)
    loss = float(loss)

    def ref_loss_fn(qq, kk, vv):
        return jnp.mean(scaled_dot_product_attention(
            qq, kk, vv, causal=True) ** 2)

    ref_loss, ref_grads = jax.value_and_grad(
        ref_loss_fn, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    assert abs(loss - float(ref_loss)) < 1e-5, (loss, float(ref_loss))

    # each grad is seq-sharded: this process's shard must equal the
    # dense-attention grad's same global slice
    for name, g, ref in zip("qkv", grads, ref_grads):
        shard = g.addressable_shards[0]
        local = np.asarray(shard.data)
        want = np.asarray(ref)[tuple(shard.index)]
        np.testing.assert_allclose(local, want, rtol=1e-4, atol=1e-5,
                                   err_msg=f"sp grad d{name}")
    out["sp_loss"] = np.float32(loss)
    out["sp_ref_loss"] = np.float32(float(ref_loss))


def run_put_epoch_guard(out):
    """Multi-host put_epoch_source with non-tiling rows must refuse
    loudly (round-4 weak spot: docstring-only constraint)."""
    from analytics_zoo_tpu.parallel import mesh as mesh_lib
    from analytics_zoo_tpu.parallel.trainer import DistributedTrainer
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense

    Layer.reset_name_counters()
    m = Sequential()
    m.add(Dense(4, input_shape=(8,)))
    m.init()
    trainer = DistributedTrainer(m, None,
                                 mesh=mesh_lib.create_mesh({"data": 8}))
    # 8-way data axis over 2 hosts -> each host's share is 4; 7 rows
    # cannot tile it
    bad_x = [np.zeros((7, 8), np.float32)]
    bad_y = np.zeros((7, 4), np.float32)
    try:
        trainer.put_epoch_source(bad_x, bad_y)
    except ValueError as err:
        msg = str(err)
        assert "put_epoch_source" in msg and "tile" in msg, msg
        out["guard_raised"] = np.int32(1)
    else:
        out["guard_raised"] = np.int32(0)
    # …and rows that DO tile place fine: each host's 8 rows become
    # its slice of the 16-row global epoch
    ok_x = [np.zeros((8, 8), np.float32)]
    ok_y = np.zeros((8, 4), np.float32)
    xd, yd = trainer.put_epoch_source(ok_x, ok_y)
    assert xd[0].shape == (16, 8), xd[0].shape


def main():
    out_dir = os.environ["ZOO_TEST_OUT"]

    from analytics_zoo_tpu.common.zoo_context import init_zoo_context
    from analytics_zoo_tpu.parallel import mesh as mesh_lib

    ctx = init_zoo_context(mesh_shape={"data": 8})
    assert ctx.process_count == 2, ctx
    pid = ctx.process_index

    from analytics_zoo_tpu.ops import dtypes
    dtypes.set_policy(param_dtype="float32", compute_dtype="float32")

    out = {}
    run_pp(out, mesh_lib)
    run_ep(out, mesh_lib)
    run_sp(out, mesh_lib)
    run_put_epoch_guard(out)
    np.savez(os.path.join(out_dir, f"worker{pid}.npz"), **out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
