"""End-to-end training tests over the virtual 8-device mesh — the
analogue of the reference's DistriEstimatorSpec / TrainingSpec
(SURVEY.md §4.1) which train small MLPs through the full distributed
optimizer on local[N] Spark."""

import os

import jax
import numpy as np
import pytest

from analytics_zoo_tpu.common.triggers import MaxEpoch
from analytics_zoo_tpu.feature.feature_set import FeatureSet
from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
from analytics_zoo_tpu.pipeline.estimator import Estimator


def make_regression(n=512, d=8, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, 1).astype(np.float32)
    x = rs.randn(n, d).astype(np.float32)
    y = x @ w + 0.01 * rs.randn(n, 1).astype(np.float32)
    return x, y


def make_classification(n=512, d=10, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    w = rs.randn(d, classes).astype(np.float32)
    y = np.argmax(x @ w, axis=-1).astype(np.int32)
    return x, y


def test_mesh_uses_all_virtual_devices():
    from analytics_zoo_tpu.common.zoo_context import get_zoo_context
    ctx = get_zoo_context()
    assert ctx.num_devices == 8
    assert ctx.mesh.shape["data"] == 8


def test_fit_reduces_loss_regression():
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam
    x, y = make_regression()
    model = Sequential()
    model.add(Dense(16, activation="relu", input_shape=(8,)))
    model.add(Dense(1))
    model.compile(optimizer=Adam(lr=0.02), loss="mse")
    history = model.fit(x, y, batch_size=64, nb_epoch=15)
    assert history[0]["loss"] > history[-1]["loss"]
    assert history[-1]["loss"] < 0.5


def test_fit_classification_with_validation():
    x, y = make_classification()
    model = Sequential()
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam
    model.add(Dense(32, activation="relu", input_shape=(10,)))
    model.add(Dense(3))
    model.compile(optimizer=Adam(lr=0.02),
                  loss="sparse_categorical_crossentropy_with_logits",
                  metrics=["accuracy"])
    history = model.fit(x, y, batch_size=64, nb_epoch=10,
                        validation_data=(x, y))
    assert history[-1]["val"]["sparse_categorical_accuracy"] > 0.8


def test_evaluate_and_predict_consistency():
    x, y = make_classification(n=200)
    model = Sequential()
    model.add(Dense(3, input_shape=(10,)))
    model.compile(optimizer="sgd",
                  loss="sparse_categorical_crossentropy_with_logits",
                  metrics=["accuracy"])
    model.fit(x, y, batch_size=40, nb_epoch=3)
    scores = model.evaluate(x, y, batch_size=64)
    preds = model.predict(x, batch_size=64)
    assert preds.shape == (200, 3)
    manual_acc = float(np.mean(np.argmax(preds, -1) == y))
    assert abs(scores["sparse_categorical_accuracy"] - manual_acc) < 1e-6


def test_predict_handles_partial_batches():
    x, y = make_regression(n=130)
    model = Sequential()
    model.add(Dense(1, input_shape=(8,)))
    model.compile(optimizer="sgd", loss="mse")
    preds = model.predict(x, batch_size=64)
    assert preds.shape == (130, 1)
    # > 8 batches exercises the sliding in-flight window in
    # predict_in_batches (pop-and-fetch path), and row order must
    # survive the windowed fetch
    small = model.predict(x, batch_size=8)   # 17 batches
    np.testing.assert_allclose(np.asarray(small), np.asarray(preds),
                               rtol=1e-5, atol=1e-6)


def test_checkpoint_resume(tmp_path):
    x, y = make_regression()
    train = FeatureSet.from_ndarrays(x, y)

    def build():
        from analytics_zoo_tpu.pipeline.api.keras import Layer
        Layer.reset_name_counters()  # checkpoint keys are layer names
        m = Sequential()
        m.add(Dense(8, activation="relu", input_shape=(8,)))
        m.add(Dense(1))
        m.compile(optimizer="adam", loss="mse")
        return m

    ckpt_dir = str(tmp_path / "ckpt")
    m1 = build()
    est1 = Estimator(m1, optim_method=m1.optim_method, model_dir=ckpt_dir)
    est1.train(train, "mse", end_trigger=MaxEpoch(3), batch_size=64)
    assert est1.train_state.epoch == 3
    files = os.listdir(ckpt_dir)
    assert any(f.endswith(".ckpt") for f in files)

    # A fresh estimator on the same dir resumes at epoch 3 and continues.
    m2 = build()
    est2 = Estimator(m2, optim_method=m2.optim_method, model_dir=ckpt_dir)
    est2.train(train, "mse", end_trigger=MaxEpoch(5), batch_size=64)
    assert est2.train_state.epoch == 5
    assert len(est2.history) == 2  # only epochs 4 and 5 ran here


def test_gradient_clipping_paths():
    x, y = make_regression(n=128)
    for setter in ("const", "l2"):
        model = Sequential()
        model.add(Dense(1, input_shape=(8,)))
        model.compile(optimizer="sgd", loss="mse")
        if setter == "const":
            model.set_constant_gradient_clipping(-0.1, 0.1)
        else:
            model.set_gradient_clipping_by_l2_norm(1.0)
        history = model.fit(x, y, batch_size=64, nb_epoch=2)
        assert np.isfinite(history[-1]["loss"])


def test_disk_slice_feature_set(tmp_path):
    x, y = make_regression(n=256)
    np.save(tmp_path / "x.npy", x)
    np.save(tmp_path / "y.npy", y)
    fs = FeatureSet.from_npy_dir(str(tmp_path), num_slices=4)
    batches = list(fs.slice_batches(epoch=0, slice_index=0, batch_size=16))
    assert len(batches) == 4  # 256/4 slices = 64 rows -> 4 batches of 16
    model = Sequential()
    model.add(Dense(1, input_shape=(8,)))
    model.compile(optimizer="adam", loss="mse")
    est = Estimator(model, optim_method=model.optim_method)
    est.train(fs, "mse", end_trigger=MaxEpoch(2), batch_size=16)
    assert est.train_state.epoch == 2


def test_deterministic_shuffling_is_reproducible():
    fs = FeatureSet.from_ndarrays(np.arange(100, dtype=np.float32),
                                  np.arange(100, dtype=np.float32))
    b1 = [b[0] for b in fs.epoch_batches(1, 10)]
    b2 = [b[0] for b in fs.epoch_batches(1, 10)]
    b3 = [b[0] for b in fs.epoch_batches(2, 10)]
    np.testing.assert_array_equal(np.concatenate(b1), np.concatenate(b2))
    assert not np.array_equal(np.concatenate(b1), np.concatenate(b3))


def test_epoch_chunks_match_epoch_batches():
    """Chunked iteration covers exactly the same rows in the same order
    as per-step iteration (same per-epoch permutation, remainder
    dropped), in chunks of whole batches."""
    fs = FeatureSet.from_ndarrays(np.arange(103, dtype=np.float32),
                                  np.arange(103, dtype=np.float32))
    per_step = np.concatenate(
        [b[0] for b in fs.epoch_batches(3, 10)])
    chunks = list(fs.epoch_chunks(3, 10, steps=4))
    np.testing.assert_array_equal(
        np.concatenate([c[0] for c in chunks]), per_step)
    assert [c[2] for c in chunks] == [4, 4, 2]   # 10 batches -> 4+4+2


def _fit_with_engine(x, y, steps_per_dispatch, hbm_cache_mb,
                     epochs=4, batch_size=16, expect_fallback=False):
    """Train the same Dropout model through one of the three dispatch
    engines: per-step (steps_per_dispatch=1), chunked scan, or the HBM
    epoch cache (hbm_cache_mb>0 + chunk conditions).

    Asserts via the estimator's own log that the REQUESTED engine
    actually ran — the HBM path falls back to chunked on device
    failure, which would otherwise make engine-equivalence tests
    vacuously pass."""
    import logging

    from analytics_zoo_tpu.common.config import get_config
    from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dropout
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import SGD

    Layer.reset_name_counters()
    cfg = get_config()
    old_k = cfg.get("train.steps_per_dispatch")
    old_mb = cfg.get("train.hbm_cache_mb")
    cfg.set("train.steps_per_dispatch", steps_per_dispatch)
    cfg.set("train.hbm_cache_mb", hbm_cache_mb)

    logger = logging.getLogger("analytics_zoo_tpu.estimator")
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    cap = _Capture(level=logging.DEBUG)
    old_level = logger.level
    logger.addHandler(cap)
    logger.setLevel(logging.DEBUG)
    try:
        m = Sequential()
        m.add(Dense(8, activation="relu", input_shape=(6,)))
        m.add(Dropout(0.25))
        m.add(Dense(1))
        est = Estimator(m, optim_method=SGD(learning_rate=0.05))
        est.train(FeatureSet.from_ndarrays(x, y), "mse",
                  end_trigger=MaxEpoch(epochs), batch_size=batch_size)
    finally:
        logger.removeHandler(cap)
        logger.setLevel(old_level)
        # re-fetch: est.train's lazy context init REPLACES the global
        # config (carrying programmatic sets), so restoring onto the
        # stale `cfg` object would be a no-op on the live one
        live = get_config()
        live.set("train.steps_per_dispatch", old_k)
        live.set("train.hbm_cache_mb", old_mb)

    hbm_requested = hbm_cache_mb > 0 and steps_per_dispatch > 1
    if expect_fallback:
        assert any("falling back to chunked" in r
                   for r in records), records
    else:
        assert any("HBM epoch cache active" in r
                   for r in records) == hbm_requested, records
        assert not any("falling back to chunked" in r
                       for r in records), records
    return est


def _dropout_problem(n=320):
    rs = np.random.RandomState(0)
    x = rs.randn(n, 6).astype(np.float32)
    w = rs.randn(6, 1).astype(np.float32)
    return x, (x @ w).astype(np.float32)


def test_dispatch_engines_are_pure_performance_knobs():
    """All three dispatch engines — per-step, chunked scan, and the
    device-resident HBM epoch cache — are SEMANTICS-PRESERVING: same
    step count, same rng stream (fold_in by the global iteration —
    verified with a Dropout model, which consumes rng every step),
    same final params."""
    x, y = _dropout_problem()
    stepped = _fit_with_engine(x, y, 1, 0)        # per-step dispatch
    chunked = _fit_with_engine(x, y, 8, 0)        # chunked lax.scan
    cached = _fit_with_engine(x, y, 8, 2048)      # HBM epoch cache
    assert stepped.train_state.iteration == \
        chunked.train_state.iteration == \
        cached.train_state.iteration == 4 * (320 // 16)
    s_leaves = jax.tree_util.tree_leaves(stepped.variables["params"])
    for est in (chunked, cached):
        for c, s in zip(
                jax.tree_util.tree_leaves(est.variables["params"]),
                s_leaves):
            # "same semantics" here means same batches, same rng
            # stream, same update RULE — not the same XLA program: the
            # per-step jit, the scan body, and the fused epoch program
            # schedule/fuse float32 ops differently, so each of the 80
            # SGD steps may differ by ~1 ulp and the drift compounds
            # multiplicatively through relu/dropout. 1e-4 absolute on
            # O(1)-magnitude params (~80 steps x ~1e-6/step) separates
            # reassociation noise from a real semantics bug (a wrong
            # batch or rng fold shifts params by O(1e-2) here).
            np.testing.assert_allclose(np.asarray(c), np.asarray(s),
                                       rtol=1e-4, atol=1e-4)
    # reported loss granularity differs by design (chunk mean vs last
    # batch); the optimizer trajectory — the semantics — is identical
    for est in (stepped, chunked, cached):
        assert np.isfinite(est.train_state.last_loss)


def test_eval_batch_hbm_cache_matches_streaming():
    """Validation scores are identical whether the eval set streams
    host->device every epoch or is placed once under the HBM budget."""
    from analytics_zoo_tpu.common.config import get_config
    from analytics_zoo_tpu.pipeline.api.keras.engine import Layer
    from analytics_zoo_tpu.pipeline.api.keras.metrics import MAE
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import SGD

    import logging

    x, y = _dropout_problem(160)
    vx, vy = x[:48], y[:48]

    def fit(cache_mb):
        Layer.reset_name_counters()
        get_config().set("train.hbm_cache_mb", cache_mb)
        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        logger = logging.getLogger("analytics_zoo_tpu.estimator")
        cap = _Capture(level=logging.DEBUG)
        old_level = logger.level
        logger.addHandler(cap)
        logger.setLevel(logging.DEBUG)
        try:
            m = Sequential()
            m.add(Dense(4, input_shape=(6,)))
            m.add(Dense(1))
            est = Estimator(m, optim_method=SGD(learning_rate=0.05))
            est.train(FeatureSet.from_ndarrays(x, y), "mse",
                      end_trigger=MaxEpoch(3), batch_size=16,
                      validation_set=FeatureSet.from_ndarrays(vx, vy),
                      validation_method=[MAE()])
        finally:
            logger.removeHandler(cap)
            logger.setLevel(old_level)
            get_config().set("train.hbm_cache_mb", 2048)
        engaged = any("eval-batch HBM cache active" in r
                      for r in records)
        return [r["val"] for r in est.history], engaged

    cached, cached_engaged = fit(2048)
    streamed, streamed_engaged = fit(0)
    assert cached_engaged and not streamed_engaged
    assert len(cached) == len(streamed) == 3
    for c, s in zip(cached, streamed):
        for k in c:
            np.testing.assert_allclose(c[k], s[k], rtol=1e-6)


def test_infer_placement_cache_reuses_and_invalidates():
    """Repeated predict() reuses the device-placed weights (no
    re-upload per call); swapping weights via set_weights invalidates
    the cache and predictions change accordingly."""
    from analytics_zoo_tpu.parallel.trainer import DistributedTrainer

    m = Sequential()
    m.add(Dense(4, input_shape=(6,)))
    m.compile("sgd", "mse")
    x = np.random.RandomState(0).randn(32, 6).astype(np.float32)

    calls = []
    orig = DistributedTrainer.place_params

    def counting(self, params):
        calls.append(1)
        return orig(self, params)

    DistributedTrainer.place_params = counting
    try:
        p1 = m.predict(x, batch_size=16)
        p2 = m.predict(x, batch_size=16)
        assert len(calls) == 1          # second call hit the cache
        np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
        zeros = [np.zeros_like(w) for w in m.get_weights()]
        m.set_weights(zeros)
        p3 = m.predict(x, batch_size=16)
        assert len(calls) == 2          # set_weights invalidated
        np.testing.assert_allclose(np.asarray(p3), 0.0, atol=1e-6)
    finally:
        DistributedTrainer.place_params = orig


def test_remat_is_numerically_transparent():
    """train.remat (jax.checkpoint around the objective) recomputes
    the forward in the backward — same math, same final params."""
    from analytics_zoo_tpu.common.config import get_config

    x, y = _dropout_problem()
    get_config().set("train.remat", True)
    try:
        remat = _fit_with_engine(x, y, 8, 2048)
    finally:
        get_config().set("train.remat", False)
    plain = _fit_with_engine(x, y, 8, 2048)
    for c, s in zip(
            jax.tree_util.tree_leaves(remat.variables["params"]),
            jax.tree_util.tree_leaves(plain.variables["params"])):
        np.testing.assert_allclose(np.asarray(c), np.asarray(s),
                                   rtol=1e-5, atol=1e-6)


def test_programmatic_config_survives_lazy_context_init():
    """get_config().set(...) made BEFORE the context exists must
    survive the lazy init_zoo_context a first fit() triggers (it
    rebuilds the config from defaults/conf/env and used to discard
    the programmatic layer)."""
    from analytics_zoo_tpu.common.config import get_config
    from analytics_zoo_tpu.common.zoo_context import (
        get_zoo_context, reset_zoo_context)

    reset_zoo_context()
    get_config().set("train.steps_per_dispatch", 7)
    try:
        get_zoo_context()    # lazy init rebuilds the config
        assert get_config().get("train.steps_per_dispatch") == 7
    finally:
        get_config().set("train.steps_per_dispatch", 16)


def test_hbm_cache_falls_back_to_chunked_on_device_failure(monkeypatch):
    """If the HBM epoch path fails at dispatch (e.g. device OOM — the
    budget gate can't see free HBM), fit() falls back to chunked
    dispatch and still trains to the same result."""
    from analytics_zoo_tpu.parallel.trainer import DistributedTrainer

    def broken_permute(self):
        def boom(*a, **k):
            raise RuntimeError("synthetic RESOURCE_EXHAUSTED")
        return boom

    monkeypatch.setattr(DistributedTrainer, "permute_rows_fn",
                        broken_permute)
    x, y = _dropout_problem()
    fell_back = _fit_with_engine(x, y, 8, 2048, expect_fallback=True)
    monkeypatch.undo()
    chunked = _fit_with_engine(x, y, 8, 0)
    assert fell_back.train_state.iteration == \
        chunked.train_state.iteration == 4 * (320 // 16)
    for c, s in zip(
            jax.tree_util.tree_leaves(fell_back.variables["params"]),
            jax.tree_util.tree_leaves(chunked.variables["params"])):
        np.testing.assert_allclose(np.asarray(c), np.asarray(s),
                                   rtol=1e-5, atol=1e-6)


def test_hbm_cache_falls_back_when_placement_fails(monkeypatch):
    """An OOM during the one-time device placement (before the epoch
    loop) must also fall back to chunked dispatch, not abort fit()."""
    from analytics_zoo_tpu.parallel.trainer import DistributedTrainer

    def broken_put(self, x, y):
        raise RuntimeError("synthetic RESOURCE_EXHAUSTED at placement")

    monkeypatch.setattr(DistributedTrainer, "put_epoch_source",
                        broken_put)
    x, y = _dropout_problem()
    fell_back = _fit_with_engine(x, y, 8, 2048, expect_fallback=True)
    monkeypatch.undo()
    chunked = _fit_with_engine(x, y, 8, 0)
    for c, s in zip(
            jax.tree_util.tree_leaves(fell_back.variables["params"]),
            jax.tree_util.tree_leaves(chunked.variables["params"])):
        np.testing.assert_allclose(np.asarray(c), np.asarray(s),
                                   rtol=1e-5, atol=1e-6)


def test_hbm_cache_pads_ragged_rows_to_the_mesh():
    """HBM-cache path with a row count that tiles neither the batch
    nor the 8-device data axis: the source pads to shard, the epoch
    drops the remainder, and the result still bit-matches per-step."""
    x, y = _dropout_problem(103)   # 103 rows, dp=8, batch 16 -> 6 steps
    cached = _fit_with_engine(x, y, 8, 2048, epochs=3)
    stepped = _fit_with_engine(x, y, 1, 0, epochs=3)
    assert cached.train_state.iteration == \
        stepped.train_state.iteration == 3 * (103 // 16)
    for c, s in zip(
            jax.tree_util.tree_leaves(cached.variables["params"]),
            jax.tree_util.tree_leaves(stepped.variables["params"])):
        np.testing.assert_allclose(np.asarray(c), np.asarray(s),
                                   rtol=1e-5, atol=1e-6)
