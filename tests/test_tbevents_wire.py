"""tfevents wire-format coverage: an independent reader verifies the
TFRecord framing (length + masked-crc32c) and the hand-rolled Event
protobuf varint encoding round-trip, without TF in the loop; plus
JSONL read-back tolerance of a torn final line."""

import json
import struct

import pytest

from analytics_zoo_tpu.native import crc32c
from analytics_zoo_tpu.utils.tb_writer import (
    TBEventWriter, encode_scalar_event, frame_record, masked_crc32c)


# ----------------------------------------------------- reference reader
def read_records(data: bytes):
    """Independent TFRecord reader: verifies both masked CRCs per
    record and returns the payloads."""
    out, off = [], 0
    while off < len(data):
        header = data[off:off + 8]
        assert len(header) == 8, "truncated length header"
        (length,) = struct.unpack("<Q", header)
        (len_crc,) = struct.unpack("<I", data[off + 8:off + 12])
        assert len_crc == masked_crc32c(header), "length CRC mismatch"
        payload = data[off + 12:off + 12 + length]
        assert len(payload) == length, "truncated payload"
        (data_crc,) = struct.unpack(
            "<I", data[off + 12 + length:off + 16 + length])
        assert data_crc == masked_crc32c(payload), "data CRC mismatch"
        out.append(payload)
        off += 16 + length
    return out


def read_varint(buf: bytes, off: int):
    shift, val = 0, 0
    while True:
        b = buf[off]
        off += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, off
        shift += 7


def parse_event(buf: bytes):
    """Minimal proto parser for the Event fields tb_writer emits."""
    out = {}
    off = 0
    while off < len(buf):
        key, off = read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 1:       # fixed64 (wall_time double)
            (out[field],) = struct.unpack("<d", buf[off:off + 8])
            off += 8
        elif wire == 0:     # varint (step int64)
            out[field], off = read_varint(buf, off)
        elif wire == 2:     # length-delimited (summary / file_version)
            ln, off = read_varint(buf, off)
            out[field] = buf[off:off + ln]
            off += ln
        elif wire == 5:     # fixed32 (simple_value float)
            (out[field],) = struct.unpack("<f", buf[off:off + 4])
            off += 4
        else:
            raise AssertionError(f"unexpected wire type {wire}")
    return out


class TestWireFormat:
    def test_frame_record_round_trip(self):
        payloads = [b"", b"x", b"hello world" * 100]
        blob = b"".join(frame_record(p) for p in payloads)
        assert read_records(blob) == payloads

    def test_corrupt_crc_detected(self):
        rec = bytearray(frame_record(b"payload"))
        rec[-1] ^= 0xFF   # flip a data-CRC byte
        with pytest.raises(AssertionError, match="data CRC"):
            read_records(bytes(rec))

    def test_scalar_event_round_trip(self):
        ev = encode_scalar_event("Loss/train", 0.125, step=42,
                                 wall_time=1234.5)
        parsed = parse_event(ev)
        assert parsed[1] == 1234.5          # wall_time
        assert parsed[2] == 42              # step
        value = parse_event(parsed[5])[1]   # summary -> first value
        fields = parse_event(value)
        assert fields[1] == b"Loss/train"   # tag
        assert fields[2] == pytest.approx(0.125)   # simple_value

    def test_varint_multibyte_step(self):
        # step > 2^21 exercises multi-byte varints end-to-end
        ev = encode_scalar_event("t", 1.0, step=(1 << 40) + 3,
                                 wall_time=0.0)
        assert parse_event(ev)[2] == (1 << 40) + 3

    def test_writer_file_is_fully_framed(self, tmp_path):
        w = TBEventWriter(str(tmp_path))
        w.add_scalar("a", 1.0, 0)
        w.add_scalar("b", 2.0, 1)
        w.close()
        w.close()   # idempotent
        records = read_records(open(w.path, "rb").read())
        # file_version header + 2 scalars
        assert len(records) == 3
        assert parse_event(records[0])[3] == b"brain.Event:2"
        tags = [parse_event(parse_event(parse_event(r)[5])[1])[1]
                for r in records[1:]]
        assert tags == [b"a", b"b"]

    def test_crc32c_reference_vector(self):
        # RFC 3720 test vector: 32 zero bytes -> 0x8a9136aa
        assert crc32c(b"\x00" * 32) == 0x8A9136AA


class TestJsonlTolerance:
    def test_read_scalar_tolerates_torn_final_line(self, tmp_path):
        from analytics_zoo_tpu.utils.summary import TrainSummary
        ts = TrainSummary(str(tmp_path), "app")
        ts.add_scalar("Loss", 1.0, 1)
        ts.add_scalar("Loss", 0.5, 2)
        ts.close()
        # simulate a crash mid-write: append half a record
        with open(ts.path, "a") as f:
            f.write(json.dumps({"tag": "Loss", "value": 0.25,
                                "step": 3})[:17])
        assert ts.read_scalar("Loss") == [(1, 1.0), (2, 0.5)]
        # and the writer can still append past the torn line
        ts.add_scalar("Loss", 0.125, 4)
        got = ts.read_scalar("Loss")
        assert got[-1] == (4, 0.125)
        ts.close()
