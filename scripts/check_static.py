#!/usr/bin/env python
"""check_static — the one static-analysis entry point for CI.

Folds the repo's two static passes under a single command with a
shared exit-code convention (0 clean / 1 findings / 2 usage error),
keeping both callable standalone:

1. **zoolint** (``scripts/zoolint`` / ``analytics_zoo_tpu/analysis``)
   over ``analytics_zoo_tpu``, ``scripts`` and ``examples`` against
   the checked-in ``.zoolint-baseline.json`` — jit purity, host-sync
   hygiene, recompile safety, donation, thread safety, PRNG reuse;
2. **metrics_lint** (``scripts/metrics_lint.py``) over a live
   exposition rendered by the real ``MetricsRegistry`` code with a
   representative instrument set — a formatting regression in the
   registry's Prometheus exposition fails here instead of surfacing
   as a scrape error in production.

Everything loads by FILE PATH — no jax, no package import, runs in
<5s on a bare CI image.  Wired into ``dev/run-tests static`` and the
Jenkinsfile ``static`` lane; a tier-1 test runs it as a subprocess.

Usage::

    python scripts/check_static.py                 # both passes
    python scripts/check_static.py --skip-metrics  # zoolint only
    python scripts/check_static.py --jobs 4        # parallel zoolint
    python scripts/check_static.py --changed-only  # pre-commit loop
    python scripts/check_static.py --json > static_report.json
    python scripts/check_static.py --sarif static_report.sarif
    python scripts/check_static.py --zoolint-args="--rules LOCK010"

``--json`` emits ONE merged machine-readable document (zoolint's
full report plus metrics_lint's issue list) so downstream tooling —
``obs_report.py`` joining static comm estimates against measured
collective counters, the Jenkins artifact archiver — reads a single
file with a stable schema.
"""

from __future__ import annotations

import argparse
import contextlib
import importlib.util
import io
import json
import os
import shlex
import sys
from typing import List, Optional

JSON_VERSION = 1

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ZOOLINT_TARGETS = ("analytics_zoo_tpu", "scripts", "examples")
BASELINE = ".zoolint-baseline.json"


def _load_by_path(modname: str, path: str):
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


def run_zoolint(extra_args: Optional[List[str]] = None) -> int:
    # the shared jax-free file-path loader (scripts/_analysis_loader)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    from _analysis_loader import load_analysis_cli
    cli = load_analysis_cli()
    argv = list(extra_args or [])
    if not any(a.startswith("--baseline") or a == "--write-baseline"
               or a.startswith("--diff") for a in argv):
        baseline = os.path.join(REPO, BASELINE)
        if os.path.exists(baseline):
            argv += ["--baseline", baseline]
    argv += ["--root", REPO]
    argv += [os.path.join(REPO, t) for t in ZOOLINT_TARGETS]
    return cli.main(argv)


def _representative_registry():
    """A live ``MetricsRegistry`` (loaded by file path — stdlib-only
    module) exercising every instrument shape the platform exports:
    counter with/without labels, gauge, histogram (bucket series), a
    label value needing escaping, const labels.  Lint failures here
    mean the exposition RENDERER regressed."""
    metrics = _load_by_path(
        "zoo_metrics_standalone",
        os.path.join(REPO, "analytics_zoo_tpu", "observability",
                     "metrics.py"))
    reg = metrics.MetricsRegistry(max_series_per_metric=100)
    reg.set_const_labels(host="ci", process_index="0")
    reg.counter("check_requests_total", "requests").inc(3)
    c = reg.counter("check_errors_total", "errors", labels=("kind",))
    c.labels("decode").inc()
    c.labels('quo"te\\path').inc(2)
    reg.gauge("check_queue_depth", "queue depth").set(7)
    h = reg.histogram("check_latency_seconds", "latency",
                      labels=("path",))
    h.labels("train").observe(0.01)
    h.labels("train").observe(2.5)
    return reg


def run_metrics_lint(extra_args: Optional[List[str]] = None) -> int:
    lint = _load_by_path(
        "zoo_metrics_lint", os.path.join(REPO, "scripts",
                                         "metrics_lint.py"))
    if extra_args:
        return lint.main(extra_args)
    issues = lint.lint_registry(_representative_registry())
    for issue in issues:
        print(f"metrics_lint: {issue}")
    if issues:
        print(f"metrics_lint: {len(issues)} issue(s) in the "
              f"registry's own exposition")
        return 1
    print("metrics_lint: clean (representative live registry dump)")
    return 0


def metrics_lint_issues() -> List[str]:
    """The representative-registry lint as data (for --json)."""
    lint = _load_by_path(
        "zoo_metrics_lint", os.path.join(REPO, "scripts",
                                         "metrics_lint.py"))
    return [str(i) for i in
            lint.lint_registry(_representative_registry())]


def run_json(args) -> int:
    """One merged machine-readable report: zoolint's own --json
    document embedded verbatim (so keys/counts stay joinable with
    zoolint reports elsewhere) plus metrics_lint's issues."""
    doc = {"version": JSON_VERSION, "tool": "check_static"}
    rc = 0
    if not args.skip_zoolint:
        zargs = shlex.split(args.zoolint_args) + ["--json"] \
            + _zoolint_passthrough(args)
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            zrc = run_zoolint(zargs)
        rc = max(rc, zrc)
        try:
            doc["zoolint"] = json.loads(buf.getvalue())
        except ValueError:
            doc["zoolint"] = {"error": "unparseable zoolint output",
                              "raw": buf.getvalue()[:2000]}
            rc = max(rc, 2)
    if not args.skip_metrics:
        margs = shlex.split(args.metrics_args)
        if margs:
            # same passthrough contract as the non-JSON path: lint
            # the user-supplied dump, capturing its report lines
            lint = _load_by_path(
                "zoo_metrics_lint",
                os.path.join(REPO, "scripts", "metrics_lint.py"))
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                mrc = lint.main(margs)
            lines = [ln for ln in buf.getvalue().splitlines()
                     if ln.strip()]
            # main() always prints a trailing summary line ('clean'
            # or 'N issue(s)') — it is not an issue itself
            issues = lines[:-1] if lines else []
            doc["metrics_lint"] = {"total": len(issues),
                                   "issues": issues}
            rc = max(rc, mrc)
        else:
            issues = metrics_lint_issues()
            doc["metrics_lint"] = {"total": len(issues),
                                   "issues": issues}
            if issues:
                rc = max(rc, 1)
    doc["rc"] = rc
    json.dump(doc, sys.stdout, indent=2)
    sys.stdout.write("\n")
    return rc


def _zoolint_passthrough(args) -> List[str]:
    """The zoolint flags check_static forwards verbatim."""
    out: List[str] = []
    if args.jobs > 1:
        out += ["--jobs", str(args.jobs)]
    if args.changed_only is not None:
        out += ["--changed-only", args.changed_only]
    if args.sarif:
        out += ["--sarif", args.sarif]
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="check_static",
        description="run zoolint + metrics_lint with one exit-code "
                    "convention (0 clean / 1 findings / 2 usage)")
    ap.add_argument("--skip-zoolint", action="store_true")
    ap.add_argument("--skip-metrics", action="store_true")
    ap.add_argument("--json", action="store_true",
                    help="merged machine-readable report on stdout")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parallelize zoolint's per-file rule runs")
    ap.add_argument("--changed-only", nargs="?", const="HEAD",
                    default=None, metavar="GITREF",
                    help="zoolint reports only on files changed vs a "
                         "git ref (default HEAD) — the pre-commit "
                         "fast path (full project facts still load)")
    ap.add_argument("--sarif", metavar="FILE", default=None,
                    help="zoolint also writes a SARIF 2.1.0 document "
                         "(archived by the Jenkinsfile next to "
                         "static_report.json)")
    ap.add_argument("--zoolint-args", default="",
                    help="extra args passed through to zoolint "
                         "(quoted string)")
    ap.add_argument("--metrics-args", default="",
                    help="extra args passed through to metrics_lint "
                         "(e.g. a dump file); default lints a "
                         "representative live registry")
    args = ap.parse_args(argv)
    if args.skip_zoolint and args.skip_metrics:
        print("check_static: nothing to do", file=sys.stderr)
        return 2
    if args.json:
        return run_json(args)

    rc = 0
    if not args.skip_zoolint:
        print("== zoolint ==")
        zargs = shlex.split(args.zoolint_args) \
            + _zoolint_passthrough(args)
        rc = max(rc, run_zoolint(zargs))
    if not args.skip_metrics:
        print("== metrics_lint ==")
        rc = max(rc, run_metrics_lint(
            shlex.split(args.metrics_args) or None))
    print(f"check_static: {'clean' if rc == 0 else 'FAILED'} (rc={rc})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
