#!/usr/bin/env python
"""metrics_lint — Prometheus exposition linter for the zoo registry.

Checks a text-exposition document (a live ``/metrics`` dump, a file,
or — in-process — a ``MetricsRegistry``) for the mistakes that turn a
scrape into silent garbage:

* metric names / label names outside the Prometheus charsets
  (``[a-zA-Z_:][a-zA-Z0-9_:]*`` / ``[a-zA-Z_][a-zA-Z0-9_]*``);
* duplicate series (same name + label set exposed twice — Prometheus
  keeps one arbitrarily);
* counters not following the ``_total`` suffix convention;
* histogram ``le`` bucket labels out of order or non-numeric;
* sample lines whose value doesn't parse as a float;
* ``reserved`` label collisions (``le`` used outside histogram
  buckets);
* OpenMetrics-style exemplars (`` # {trace_id="..."} value ts``, what
  ``/metrics?exemplars=1`` serves): allowed only on histogram
  ``_bucket`` lines and counter samples, exemplar label names must be
  in-charset, the exemplar value must parse as a float, and a bucket
  exemplar's value must not exceed its own ``le`` bound.

A tier-1 test runs this against a LIVE registry dump, so a bad metric
name added anywhere in the codebase fails CI rather than surfacing as
a Prometheus scrape error in production.

Usage::

    python scripts/metrics_lint.py metrics.txt
    curl -s host:9090/metrics | python scripts/metrics_lint.py -
    python scripts/metrics_lint.py --url http://host:9090/metrics

Exit code 1 when any issue is found.  Pure stdlib.
"""

from __future__ import annotations

import argparse
import re
import sys
import urllib.request
from typing import Dict, List, Tuple

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# one label pair: name="value" with escaped \" \\ \n inside the value
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*|[^=,{}]+)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r"^(?P<name>[^\s{]+)(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)"
    r"(?:\s+(?P<ts>\S+))?$")
# OpenMetrics exemplar suffix: ' # {labels} value [ts]' appended to a
# bucket/counter sample line (what prometheus_text(exemplars=True)
# emits).  Anchored at end-of-line so a ' # ' INSIDE a quoted label
# value never matches.
_EXEMPLAR_RE = re.compile(
    r" # \{(?P<labels>[^}]*)\} (?P<value>\S+)(?: (?P<ts>\S+))?$")

COUNTER_SUFFIX = "_total"


def _parse_labels(body: str) -> List[Tuple[str, str]]:
    return [(m.group(1), m.group(2))
            for m in _LABEL_PAIR_RE.finditer(body or "")]


def lint_exposition(text: str) -> List[str]:
    """Return a list of human-readable issues ([] = clean)."""
    issues: List[str] = []
    types: Dict[str, str] = {}
    seen_series: Dict[str, int] = {}
    # histogram bucket ordering state: (series-minus-le) -> last le
    last_le: Dict[str, float] = {}

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                name, kind = parts[2], parts[3]
                if kind not in ("counter", "gauge", "histogram",
                                "summary", "untyped"):
                    issues.append(
                        f"line {lineno}: unknown TYPE {kind!r} "
                        f"for {name}")
                if name in types:
                    issues.append(
                        f"line {lineno}: duplicate TYPE declaration "
                        f"for {name}")
                types[name] = kind
                if kind == "counter" and \
                        not name.endswith(COUNTER_SUFFIX):
                    issues.append(
                        f"line {lineno}: counter {name!r} should end "
                        f"with '{COUNTER_SUFFIX}' (naming convention)")
                if not METRIC_NAME_RE.match(name):
                    issues.append(
                        f"line {lineno}: invalid metric name {name!r}")
            continue
        if line.startswith("#"):
            continue
        exemplar = _EXEMPLAR_RE.search(line)
        if exemplar is not None:
            line = line[: exemplar.start()]
        m = _SAMPLE_RE.match(line)
        if not m:
            issues.append(f"line {lineno}: unparseable sample: "
                          f"{line[:80]!r}")
            continue
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    types.get(name[: -len(suffix)]) in ("histogram",
                                                        "summary"):
                base = name[: -len(suffix)]
                break
        if not METRIC_NAME_RE.match(name):
            issues.append(f"line {lineno}: invalid metric name "
                          f"{name!r}")
        labels = _parse_labels(m.group("labels"))
        label_names = [k for k, _ in labels]
        for k in label_names:
            if not LABEL_NAME_RE.match(k):
                issues.append(
                    f"line {lineno}: invalid label name {k!r} on "
                    f"{name}")
            if k.startswith("__"):
                issues.append(
                    f"line {lineno}: reserved label name {k!r} on "
                    f"{name}")
        if len(set(label_names)) != len(label_names):
            issues.append(
                f"line {lineno}: repeated label name on {name}")
        if "le" in label_names and not name.endswith("_bucket"):
            issues.append(
                f"line {lineno}: 'le' label outside a histogram "
                f"bucket on {name}")
        try:
            float(m.group("value").replace("+Inf", "inf")
                  .replace("-Inf", "-inf"))
        except ValueError:
            issues.append(
                f"line {lineno}: non-numeric value "
                f"{m.group('value')!r} for {name}")
        if exemplar is not None:
            # exemplars only make sense on bucket/counter samples
            # (the OpenMetrics placement rule); a TYPE-less _total
            # series is given the benefit of the doubt
            allowed = (name.endswith("_bucket")
                       or types.get(name) == "counter"
                       or (name not in types
                           and name.endswith(COUNTER_SUFFIX)))
            if not allowed:
                issues.append(
                    f"line {lineno}: exemplar on a non-bucket/"
                    f"non-counter sample {name}")
            for k, _v in _parse_labels(exemplar.group("labels")):
                if not LABEL_NAME_RE.match(k):
                    issues.append(
                        f"line {lineno}: invalid exemplar label "
                        f"name {k!r} on {name}")
            ex_val = None
            try:
                ex_val = float(exemplar.group("value"))
            except ValueError:
                issues.append(
                    f"line {lineno}: non-numeric exemplar value "
                    f"{exemplar.group('value')!r} on {name}")
            ts = exemplar.group("ts")
            if ts is not None:
                try:
                    float(ts)
                except ValueError:
                    issues.append(
                        f"line {lineno}: non-numeric exemplar "
                        f"timestamp {ts!r} on {name}")
            if ex_val is not None and name.endswith("_bucket"):
                le = dict(labels).get("le")
                if le is not None:
                    try:
                        le_f = float(le.replace("+Inf", "inf"))
                    except ValueError:
                        le_f = None
                    if le_f is not None and ex_val > le_f:
                        issues.append(
                            f"line {lineno}: exemplar value "
                            f"{ex_val} above its bucket bound "
                            f"le={le} on {name}")
        # duplicate-series detection (le participates: bucket lines
        # are distinct series per bound)
        key = name + "{" + ",".join(
            f'{k}="{v}"' for k, v in sorted(labels)) + "}"
        if key in seen_series:
            issues.append(
                f"line {lineno}: duplicate series {key} (first at "
                f"line {seen_series[key]})")
        else:
            seen_series[key] = lineno
        # bucket ordering: le must be non-decreasing within a series
        if name.endswith("_bucket") and base in types:
            ld = dict(labels)
            le = ld.pop("le", None)
            series = name + repr(sorted(ld.items()))
            if le is not None:
                try:
                    le_f = float(le.replace("+Inf", "inf"))
                except ValueError:
                    issues.append(
                        f"line {lineno}: non-numeric le={le!r} on "
                        f"{name}")
                    continue
                if series in last_le and le_f < last_le[series]:
                    issues.append(
                        f"line {lineno}: le buckets out of order on "
                        f"{name}")
                last_le[series] = le_f
    return issues


def lint_registry(registry) -> List[str]:
    """Lint a live ``MetricsRegistry`` (what the tier-1 test calls).
    The exemplar-enabled exposition is a strict superset of the plain
    one, so linting it covers both views in one pass; registries
    predating the ``exemplars`` kwarg fall back to the plain text."""
    try:
        text = registry.prometheus_text(exemplars=True)
    except TypeError:
        text = registry.prometheus_text()
    return lint_exposition(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="lint a Prometheus text-exposition dump "
                    "(name/label charsets, duplicate series, counter "
                    "_total convention, bucket order)")
    ap.add_argument("path", nargs="?", default=None,
                    help="exposition file, or '-' for stdin")
    ap.add_argument("--url", default=None,
                    help="scrape this /metrics URL instead of a file")
    args = ap.parse_args(argv)

    if args.url:
        with urllib.request.urlopen(args.url, timeout=5.0) as resp:
            text = resp.read().decode()
    elif args.path in (None, "-"):
        text = sys.stdin.read()
    else:
        with open(args.path) as f:
            text = f.read()

    issues = lint_exposition(text)
    for issue in issues:
        print(issue)
    if issues:
        print(f"{len(issues)} issue(s)")
        return 1
    print("clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
