#!/usr/bin/env python
"""metrics_lint — Prometheus exposition linter for the zoo registry.

Checks a text-exposition document (a live ``/metrics`` dump, a file,
or — in-process — a ``MetricsRegistry``) for the mistakes that turn a
scrape into silent garbage:

* metric names / label names outside the Prometheus charsets
  (``[a-zA-Z_:][a-zA-Z0-9_:]*`` / ``[a-zA-Z_][a-zA-Z0-9_]*``);
* duplicate series (same name + label set exposed twice — Prometheus
  keeps one arbitrarily);
* counters not following the ``_total`` suffix convention;
* histogram ``le`` bucket labels out of order or non-numeric;
* sample lines whose value doesn't parse as a float;
* ``reserved`` label collisions (``le`` used outside histogram
  buckets);
* OpenMetrics-style exemplars (`` # {trace_id="..."} value ts``, what
  ``/metrics?exemplars=1`` serves): allowed only on histogram
  ``_bucket`` lines and counter samples, exemplar label names must be
  in-charset, the exemplar value must parse as a float, and a bucket
  exemplar's value must not exceed its own ``le`` bound.

A tier-1 test runs this against a LIVE registry dump, so a bad metric
name added anywhere in the codebase fails CI rather than surfacing as
a Prometheus scrape error in production.

``--tsdb DIR`` lints the embedded time-series store's segment files
instead (schema header, monotonic timestamps, non-decreasing
counters, series-key charsets) — ``check_static
--metrics-args='--tsdb RUN_DIR'`` wires it into the static lane.

``--events DIR`` lints flight-recorder journals (``events.jsonl``,
run-level + per-host): header per writer session, ``events_schema``
version, non-decreasing ``t`` / strictly-increasing ``seq`` per
session, kinds within ``flightrec.EVENT_KINDS``; a torn FINAL line is
the crash-safety contract working and is allowed.

Usage::

    python scripts/metrics_lint.py metrics.txt
    curl -s host:9090/metrics | python scripts/metrics_lint.py -
    python scripts/metrics_lint.py --url http://host:9090/metrics
    python scripts/metrics_lint.py --tsdb /runs/exp7

Exit code 1 when any issue is found.  Pure stdlib.
"""

from __future__ import annotations

import argparse
import re
import sys
import urllib.request
from typing import Dict, List, Tuple

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# one label pair: name="value" with escaped \" \\ \n inside the value
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*|[^=,{}]+)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r"^(?P<name>[^\s{]+)(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)"
    r"(?:\s+(?P<ts>\S+))?$")
# OpenMetrics exemplar suffix: ' # {labels} value [ts]' appended to a
# bucket/counter sample line (what prometheus_text(exemplars=True)
# emits).  Anchored at end-of-line so a ' # ' INSIDE a quoted label
# value never matches.
_EXEMPLAR_RE = re.compile(
    r" # \{(?P<labels>[^}]*)\} (?P<value>\S+)(?: (?P<ts>\S+))?$")

COUNTER_SUFFIX = "_total"


def _parse_labels(body: str) -> List[Tuple[str, str]]:
    return [(m.group(1), m.group(2))
            for m in _LABEL_PAIR_RE.finditer(body or "")]


def lint_exposition(text: str) -> List[str]:
    """Return a list of human-readable issues ([] = clean)."""
    issues: List[str] = []
    types: Dict[str, str] = {}
    seen_series: Dict[str, int] = {}
    # histogram bucket ordering state: (series-minus-le) -> last le
    last_le: Dict[str, float] = {}

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4:
                name, kind = parts[2], parts[3]
                if kind not in ("counter", "gauge", "histogram",
                                "summary", "untyped"):
                    issues.append(
                        f"line {lineno}: unknown TYPE {kind!r} "
                        f"for {name}")
                if name in types:
                    issues.append(
                        f"line {lineno}: duplicate TYPE declaration "
                        f"for {name}")
                types[name] = kind
                if kind == "counter" and \
                        not name.endswith(COUNTER_SUFFIX):
                    issues.append(
                        f"line {lineno}: counter {name!r} should end "
                        f"with '{COUNTER_SUFFIX}' (naming convention)")
                if not METRIC_NAME_RE.match(name):
                    issues.append(
                        f"line {lineno}: invalid metric name {name!r}")
            continue
        if line.startswith("#"):
            continue
        exemplar = _EXEMPLAR_RE.search(line)
        if exemplar is not None:
            line = line[: exemplar.start()]
        m = _SAMPLE_RE.match(line)
        if not m:
            issues.append(f"line {lineno}: unparseable sample: "
                          f"{line[:80]!r}")
            continue
        name = m.group("name")
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and \
                    types.get(name[: -len(suffix)]) in ("histogram",
                                                        "summary"):
                base = name[: -len(suffix)]
                break
        if not METRIC_NAME_RE.match(name):
            issues.append(f"line {lineno}: invalid metric name "
                          f"{name!r}")
        labels = _parse_labels(m.group("labels"))
        label_names = [k for k, _ in labels]
        for k in label_names:
            if not LABEL_NAME_RE.match(k):
                issues.append(
                    f"line {lineno}: invalid label name {k!r} on "
                    f"{name}")
            if k.startswith("__"):
                issues.append(
                    f"line {lineno}: reserved label name {k!r} on "
                    f"{name}")
        if len(set(label_names)) != len(label_names):
            issues.append(
                f"line {lineno}: repeated label name on {name}")
        if "le" in label_names and not name.endswith("_bucket"):
            issues.append(
                f"line {lineno}: 'le' label outside a histogram "
                f"bucket on {name}")
        try:
            float(m.group("value").replace("+Inf", "inf")
                  .replace("-Inf", "-inf"))
        except ValueError:
            issues.append(
                f"line {lineno}: non-numeric value "
                f"{m.group('value')!r} for {name}")
        if exemplar is not None:
            # exemplars only make sense on bucket/counter samples
            # (the OpenMetrics placement rule); a TYPE-less _total
            # series is given the benefit of the doubt
            allowed = (name.endswith("_bucket")
                       or types.get(name) == "counter"
                       or (name not in types
                           and name.endswith(COUNTER_SUFFIX)))
            if not allowed:
                issues.append(
                    f"line {lineno}: exemplar on a non-bucket/"
                    f"non-counter sample {name}")
            for k, _v in _parse_labels(exemplar.group("labels")):
                if not LABEL_NAME_RE.match(k):
                    issues.append(
                        f"line {lineno}: invalid exemplar label "
                        f"name {k!r} on {name}")
            ex_val = None
            try:
                ex_val = float(exemplar.group("value"))
            except ValueError:
                issues.append(
                    f"line {lineno}: non-numeric exemplar value "
                    f"{exemplar.group('value')!r} on {name}")
            ts = exemplar.group("ts")
            if ts is not None:
                try:
                    float(ts)
                except ValueError:
                    issues.append(
                        f"line {lineno}: non-numeric exemplar "
                        f"timestamp {ts!r} on {name}")
            if ex_val is not None and name.endswith("_bucket"):
                le = dict(labels).get("le")
                if le is not None:
                    try:
                        le_f = float(le.replace("+Inf", "inf"))
                    except ValueError:
                        le_f = None
                    if le_f is not None and ex_val > le_f:
                        issues.append(
                            f"line {lineno}: exemplar value "
                            f"{ex_val} above its bucket bound "
                            f"le={le} on {name}")
        # duplicate-series detection (le participates: bucket lines
        # are distinct series per bound)
        key = name + "{" + ",".join(
            f'{k}="{v}"' for k, v in sorted(labels)) + "}"
        if key in seen_series:
            issues.append(
                f"line {lineno}: duplicate series {key} (first at "
                f"line {seen_series[key]})")
        else:
            seen_series[key] = lineno
        # bucket ordering: le must be non-decreasing within a series
        if name.endswith("_bucket") and base in types:
            ld = dict(labels)
            le = ld.pop("le", None)
            series = name + repr(sorted(ld.items()))
            if le is not None:
                try:
                    le_f = float(le.replace("+Inf", "inf"))
                except ValueError:
                    issues.append(
                        f"line {lineno}: non-numeric le={le!r} on "
                        f"{name}")
                    continue
                if series in last_le and le_f < last_le[series]:
                    issues.append(
                        f"line {lineno}: le buckets out of order on "
                        f"{name}")
                last_le[series] = le_f
    return issues


# ------------------------------------------------------------- tsdb lint
_SERIES_KEY_RE = re.compile(
    r'^(?P<name>[^\s{]+)(?:\{(?P<labels>.*)\})?$')


def _lint_series_key(key: str, where: str) -> List[str]:
    m = _SERIES_KEY_RE.match(key)
    if not m:
        return [f"{where}: unparseable series key {key[:80]!r}"]
    issues = []
    if not METRIC_NAME_RE.match(m.group("name")):
        issues.append(f"{where}: invalid metric name "
                      f"{m.group('name')!r}")
    for k, _v in _parse_labels(m.group("labels")):
        if not LABEL_NAME_RE.match(k):
            issues.append(f"{where}: invalid label name {k!r} on "
                          f"{m.group('name')}")
    return issues


def _tsdb_roots(directory: str) -> List[str]:
    """Accept a tsdb dir, a host-<k> slot, or a run dir with
    ``host-*/tsdb`` (the same resolution ``tsdb.read_samples``
    does)."""
    import os
    if os.path.isdir(os.path.join(directory, "tsdb")):
        return [os.path.join(directory, "tsdb")]
    if os.path.isdir(directory):
        hosts = [os.path.join(directory, n, "tsdb")
                 for n in sorted(os.listdir(directory))
                 if n.startswith("host-")]
        hosts = [h for h in hosts if os.path.isdir(h)]
        return hosts if hosts else [directory]
    return []


def lint_tsdb(directory: str, schema: int = 1) -> List[str]:
    """Lint the embedded TSDB's segment files (``seg-*.jsonl``):

    * first parseable line must be a schema header with the expected
      ``tsdb_schema`` version;
    * sample timestamps non-decreasing within a segment;
    * reconstructed absolute counters non-decreasing (a reset is only
      legal on a ``full`` sample — a negative delta is corruption);
    * counter/gauge series keys within the Prometheus charsets;
    * unparseable NON-final lines flagged (a torn final line is the
      crash-safety contract working as designed and is allowed).
    """
    import json as _json
    import os
    issues: List[str] = []
    roots = _tsdb_roots(directory)
    if not roots:
        return [f"{directory}: no tsdb directory found"]
    seen_segments = 0
    for root in roots:
        try:
            segs = sorted(n for n in os.listdir(root)
                          if n.startswith("seg-")
                          and n.endswith(".jsonl"))
        except OSError as e:
            issues.append(f"{root}: unreadable ({e})")
            continue
        for seg in segs:
            seen_segments += 1
            path = os.path.join(root, seg)
            with open(path) as f:
                lines = f.read().splitlines()
            header_seen = False
            last_t = None
            abs_counters: Dict[str, float] = {}
            have_base = False
            checked_keys = set()
            for i, line in enumerate(lines, 1):
                where = f"{path}:{i}"
                try:
                    rec = _json.loads(line)
                except ValueError:
                    if i == len(lines):
                        continue    # torn tail: allowed by design
                    issues.append(f"{where}: unparseable non-final "
                                  f"line")
                    continue
                if not header_seen:
                    if rec.get("tsdb_schema") != schema:
                        issues.append(
                            f"{where}: first record is not a "
                            f"tsdb_schema={schema} header "
                            f"(got {rec.get('tsdb_schema')!r})")
                    header_seen = True
                    if "tsdb_schema" in rec:
                        continue
                if "tsdb_schema" in rec:
                    issues.append(f"{where}: duplicate schema header")
                    continue
                t = rec.get("t")
                if not isinstance(t, (int, float)):
                    issues.append(f"{where}: sample without a "
                                  f"numeric 't'")
                    continue
                if last_t is not None and t < last_t:
                    issues.append(
                        f"{where}: timestamp {t} < previous {last_t} "
                        f"(non-monotonic within segment)")
                last_t = t
                full = bool(rec.get("full"))
                for key, v in (rec.get("c") or {}).items():
                    if key not in checked_keys:
                        checked_keys.add(key)
                        issues.extend(_lint_series_key(key, where))
                    if full:
                        abs_counters[key] = float(v)
                    elif have_base:
                        if float(v) < 0:
                            issues.append(
                                f"{where}: negative counter delta "
                                f"{v} for {key} outside a full "
                                f"sample")
                        abs_counters[key] = abs_counters.get(
                            key, 0.0) + float(v)
                if full:
                    have_base = True
                for key in (rec.get("g") or {}):
                    if key not in checked_keys:
                        checked_keys.add(key)
                        issues.extend(_lint_series_key(key, where))
            if lines and not header_seen:
                issues.append(f"{path}: no parseable records")
    if not seen_segments:
        issues.append(f"{directory}: no tsdb segments found")
    return issues


# ----------------------------------------------------------- events lint
def _load_event_kinds():
    """The known-kind vocabulary from ``flightrec.EVENT_KINDS``,
    loaded by file path (this script stays stdlib + jax-free).
    Returns None when the repo layout isn't there — a standalone lint
    of a copied journal still checks structure, just not kinds."""
    import importlib.util
    import os
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir,
        "analytics_zoo_tpu", "observability", "flightrec.py")
    if not os.path.exists(path):
        return None
    try:
        spec = importlib.util.spec_from_file_location(
            "_zoo_flightrec_lint", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        return frozenset(mod.EVENT_KINDS)
    except Exception:   # noqa: BLE001 — structure lint still runs
        return None


def _events_roots(directory: str) -> List[str]:
    """Accept one events.jsonl, a host-<k> slot, or a run dir (the
    run-level journal plus every ``host-*/events.jsonl`` — the same
    resolution ``flightrec.journal_paths`` does)."""
    import os
    if os.path.isfile(directory):
        return [directory]
    paths = []
    run_level = os.path.join(directory, "events.jsonl")
    if os.path.isfile(run_level):
        paths.append(run_level)
    if os.path.isdir(directory):
        for n in sorted(os.listdir(directory)):
            p = os.path.join(directory, n, "events.jsonl")
            if n.startswith("host-") and os.path.isfile(p):
                paths.append(p)
    return paths


def lint_events(directory: str, schema: int = 1) -> List[str]:
    """Lint flight-recorder journals (``events.jsonl``):

    * first parseable line of each writer session must be a header
      with the expected ``events_schema`` version;
    * ``t`` non-decreasing and ``seq`` strictly increasing within a
      session (a new header re-opens the journal: respawned
      incarnations append a fresh header and restart both);
    * event kinds must be in ``flightrec.EVENT_KINDS``;
    * unparseable NON-final lines flagged (a torn final line is the
      crash-safety contract working as designed and is allowed).
    """
    import json as _json
    issues: List[str] = []
    kinds = _load_event_kinds()
    paths = _events_roots(directory)
    if not paths:
        return [f"{directory}: no events.jsonl found"]
    for path in paths:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError as e:
            issues.append(f"{path}: unreadable ({e})")
            continue
        header_seen = False
        last_t = None
        last_seq = None
        for i, line in enumerate(lines, 1):
            where = f"{path}:{i}"
            try:
                rec = _json.loads(line)
            except ValueError:
                if i == len(lines):
                    continue        # torn tail: allowed by design
                issues.append(f"{where}: unparseable non-final line")
                continue
            if not isinstance(rec, dict):
                issues.append(f"{where}: record is not an object")
                continue
            if "events_schema" in rec:
                # a new writer session: timestamps/seq restart
                if rec.get("events_schema") != schema:
                    issues.append(
                        f"{where}: header events_schema="
                        f"{rec.get('events_schema')!r} (expected "
                        f"{schema})")
                header_seen = True
                last_t = None
                last_seq = None
                continue
            if not header_seen:
                issues.append(
                    f"{where}: event before any events_schema header")
                header_seen = True      # flag once per journal
            kind = rec.get("kind")
            if not isinstance(kind, str) or not kind:
                issues.append(f"{where}: event without a 'kind'")
            elif kinds is not None and kind not in kinds:
                issues.append(f"{where}: unknown event kind {kind!r}")
            t = rec.get("t")
            if not isinstance(t, (int, float)):
                issues.append(f"{where}: event without a numeric 't'")
            else:
                if last_t is not None and t < last_t:
                    issues.append(
                        f"{where}: timestamp {t} < previous {last_t} "
                        f"(non-monotonic within session)")
                last_t = t
            seq = rec.get("seq")
            if not isinstance(seq, int):
                issues.append(f"{where}: event without an integer "
                              f"'seq'")
            else:
                if last_seq is not None and seq <= last_seq:
                    issues.append(
                        f"{where}: seq {seq} <= previous {last_seq} "
                        f"(must be strictly increasing per session)")
                last_seq = seq
        if lines and not header_seen:
            issues.append(f"{path}: no parseable records")
    return issues


def lint_registry(registry) -> List[str]:
    """Lint a live ``MetricsRegistry`` (what the tier-1 test calls).
    The exemplar-enabled exposition is a strict superset of the plain
    one, so linting it covers both views in one pass; registries
    predating the ``exemplars`` kwarg fall back to the plain text."""
    try:
        text = registry.prometheus_text(exemplars=True)
    except TypeError:
        text = registry.prometheus_text()
    return lint_exposition(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="lint a Prometheus text-exposition dump "
                    "(name/label charsets, duplicate series, counter "
                    "_total convention, bucket order)")
    ap.add_argument("path", nargs="?", default=None,
                    help="exposition file, or '-' for stdin")
    ap.add_argument("--url", default=None,
                    help="scrape this /metrics URL instead of a file")
    ap.add_argument("--tsdb", metavar="DIR", default=None,
                    help="lint an embedded-TSDB directory (a run "
                         "dir's host-<k>/tsdb segment files) instead "
                         "of an exposition: schema header, monotonic "
                         "timestamps, non-decreasing counters, "
                         "series-key charsets; wire through "
                         "check_static with "
                         "--metrics-args='--tsdb RUN_DIR'")
    ap.add_argument("--events", metavar="DIR", default=None,
                    help="lint flight-recorder journals (a run dir's "
                         "events.jsonl + host-<k>/events.jsonl, or "
                         "one file) instead of an exposition: schema "
                         "header per writer session, monotonic "
                         "timestamps, strictly-increasing seq, known "
                         "event kinds; a torn FINAL line is allowed "
                         "(crash-safety contract)")
    args = ap.parse_args(argv)

    if args.tsdb or args.events:
        issues = (lint_tsdb(args.tsdb) if args.tsdb else []) + \
            (lint_events(args.events) if args.events else [])
        for issue in issues:
            print(issue)
        if issues:
            print(f"{len(issues)} issue(s)")
            return 1
        print("clean")
        return 0

    if args.url:
        with urllib.request.urlopen(args.url, timeout=5.0) as resp:
            text = resp.read().decode()
    elif args.path in (None, "-"):
        text = sys.stdin.read()
    else:
        with open(args.path) as f:
            text = f.read()

    issues = lint_exposition(text)
    for issue in issues:
        print(issue)
    if issues:
        print(f"{len(issues)} issue(s)")
        return 1
    print("clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
