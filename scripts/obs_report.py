#!/usr/bin/env python
"""obs_report — render an observability snapshot into a human
training-health report, and diff two snapshots for regression checks.

Input formats (auto-detected):

* a registry JSONL written by ``MetricsRegistry.write_jsonl`` (one
  ``{"wall_time", "metrics"}`` line per scrape — the LAST line is
  reported);
* ``bench_metrics.json`` (``{workload: {..., "metrics": snapshot}}`` —
  pick one with ``--workload``, default: every workload in the file);
* a bare registry snapshot dict (``/metrics.json`` saved to a file).

Optionally pair it with a Chrome trace (``--trace trace.json``, from
``Tracer.export_chrome_trace`` or the ``/trace`` endpoint) for a
span-aggregation table.

Diff mode: ``obs_report.py CURRENT --diff BASELINE`` compares the two
snapshots and exits 1 when a higher-is-better metric (throughput, MFU)
dropped, or a latency p50 rose, by more than ``--threshold`` (default
10%) — the offline half of ``bench.py --compare``.

Loadtest mode (auto-detected): a ``zoo-loadtest`` report JSON
(``scripts/zoo-loadtest ... --out report.json``) renders its SLO
verdict and the capacity-planning table (replicas needed per req/s at
the target p99), then falls through to the standard report over the
run's embedded registry snapshot (loadgen latency histograms etc.).

Multi-host mode: ``obs_report.py --merge-hosts <run_dir>`` federates a
launcher run directory (one ``host-<k>/`` slot per worker, written by
``zoo-launch --run-dir``): per-host step-time skew table, named
straggler, pipeline bubble fraction, collective byte/time accounting,
cluster-summed counters, and ONE merged Chrome trace aligned on the
launcher's clock anchor (``<run_dir>/merged_trace.json``).

Incident mode: ``obs_report.py --incident <run_dir>`` renders the
zoo-doctor forensics view — the causally-ordered incident timeline
joined from flight-recorder journals, heartbeats, blackboxes, the
degraded record and tsdb SLO state, plus the ranked root-cause
hypothesis list with evidence citations (reuses ``incident.json``
when a prior ``zoo-doctor`` run left one in the run dir).

Examples::

    python scripts/obs_report.py metrics.jsonl --trace trace.json
    python scripts/obs_report.py bench_metrics.json --workload ncf
    python scripts/obs_report.py run2.jsonl --diff run1.jsonl
    python scripts/obs_report.py --merge-hosts /runs/exp7
    python scripts/obs_report.py --incident /runs/exp7

Pure stdlib + file IO; never imports jax (usable on a laptop against
artifacts scp'd from the pod).  The merge logic lives in
``analytics_zoo_tpu/observability/aggregator.py`` — itself stdlib-only
— which this script loads DIRECTLY BY FILE PATH so the package (and
its jax import) never loads.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple


# --------------------------------------------------------------- loading
def _is_snapshot(d) -> bool:
    return isinstance(d, dict) and (
        "counters" in d or "gauges" in d or "histograms" in d)


def load_snapshots(path: str, workload: Optional[str] = None
                   ) -> List[Tuple[str, Dict]]:
    """Return ``[(label, snapshot), ...]`` from any supported file."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if doc is not None:
        if _is_snapshot(doc):
            return [(path, doc)]
        if isinstance(doc, dict) and "metrics" in doc \
                and _is_snapshot(doc["metrics"]):
            return [(path, doc["metrics"])]
        if isinstance(doc, dict):   # bench_metrics.json shape
            out = []
            for name, entry in sorted(doc.items()):
                snap = entry.get("metrics") \
                    if isinstance(entry, dict) else None
                if _is_snapshot(snap) and (workload is None
                                           or name == workload):
                    out.append((name, snap))
            if out:
                return out
        raise SystemExit(f"{path}: unrecognized snapshot format")
    # JSONL: report the last parseable line
    last = None
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if _is_snapshot(rec):
            last = rec
        elif isinstance(rec, dict) and _is_snapshot(rec.get("metrics")):
            last = rec["metrics"]
    if last is None:
        raise SystemExit(f"{path}: no registry snapshot found")
    return [(path, last)]


# ------------------------------------------------------------- selectors
def _labeled(series: Dict, prefix: str) -> List[Tuple[str, object]]:
    """Entries of a snapshot section whose key is ``prefix`` or
    ``prefix{label=...}``; returns (label-or-'', value)."""
    out = []
    for key, val in sorted(series.items()):
        if key == prefix:
            out.append(("", val))
        elif key.startswith(prefix + "{"):
            out.append((key[len(prefix) + 1:-1], val))
    return out


def _fmt_seconds(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.2f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.1f}ms"
    return f"{v * 1e6:.0f}us"


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    def fmt(r):
        return "  ".join(str(c).ljust(w) for c, w in zip(r, widths))
    sep = "  ".join("-" * w for w in widths)
    return "\n".join([fmt(header), sep] + [fmt(r) for r in rows])


# --------------------------------------------------------------- report
def render_report(label: str, snap: Dict,
                  trace_events: Optional[List[Dict]] = None) -> str:
    counters = snap.get("counters", {})
    gauges = snap.get("gauges", {})
    hists = snap.get("histograms", {})
    lines: List[str] = [f"== training-health report: {label} =="]

    # ---- step-time attribution ------------------------------------
    attr = _labeled(hists, "train_step_time_seconds")
    if attr:
        total_time = sum(h["sum"] for _, h in attr) or 1e-12
        rows = []
        for lab, h in attr:
            comp = lab.split("=", 1)[-1].strip('"') if lab else "?"
            rows.append([
                comp, h["count"], _fmt_seconds(h["p50"]),
                _fmt_seconds(h["p95"]), f"{h['sum']:.2f}s",
                f"{100 * h['sum'] / total_time:.0f}%"])
        lines += ["", "step-time attribution "
                  "(device is sampled — compare p50s, not sums):",
                  _table(rows, ["component", "count", "p50", "p95",
                                "total", "share"])]
    step_lat = _labeled(hists, "train_step_latency_seconds")
    for lab, h in step_lat:
        lines.append(
            f"step latency [{lab or 'all'}]: p50 "
            f"{_fmt_seconds(h['p50'])}  p95 {_fmt_seconds(h['p95'])}  "
            f"({h['count']} steps)")

    # ---- throughput / MFU -----------------------------------------
    tput = gauges.get("train_throughput_samples_per_sec")
    if tput:
        lines.append(f"throughput: {tput:.1f} samples/s "
                     f"(last epoch)")
    mfu = gauges.get("train_mfu")
    dev_step = gauges.get("train_device_step_seconds")
    flops = _labeled(gauges, "train_step_flops")
    if mfu:
        lines.append(
            f"MFU: {100 * mfu:.1f}% of chip peak "
            f"(sampled device step {_fmt_seconds(dev_step)})")
    elif flops:
        lines.append(
            "MFU: not computed (unknown chip peak — set "
            "observability.peak_flops); cost-analysis FLOPs known: "
            + ", ".join(f"{lab}={v:.3g}" for lab, v in flops))

    # ---- compilation ----------------------------------------------
    comp_rows = []
    for lab, n in _labeled(counters, "jax_compiles_total"):
        fn = lab.split("=", 1)[-1].strip('"') if lab else "?"
        secs = dict(_labeled(counters, "jax_compile_seconds_total")
                    ).get(lab, 0.0)
        rec = dict(_labeled(counters, "jax_recompiles_total")
                   ).get(lab, 0)
        comp_rows.append([fn, int(n), f"{secs:.2f}s", int(rec)])
    if comp_rows:
        lines += ["", "compilation (recompiles>0 after warmup = churn "
                  "— a shape/dtype drifts between steps):",
                  _table(comp_rows, ["function", "compiles",
                                     "first-call wall", "recompiles"])]
    backend_s = counters.get("jax_backend_compile_seconds_total")
    if backend_s:
        lines.append(
            f"backend compile: "
            f"{int(counters.get('jax_backend_compiles_total', 0))} "
            f"XLA compilations, {backend_s:.2f}s total")
    # ---- executable-cache effectiveness (docs/aot-compile.md) ------
    hits = sum(v for _l, v in
               _labeled(counters, "compile_cache_hits_total"))
    misses = sum(v for _l, v in
                 _labeled(counters, "compile_cache_misses_total"))
    if hits or misses:
        load_s = sum(v for _l, v in
                     _labeled(counters, "compile_cache_load_seconds"))
        cold_s = sum(v for _l, v in
                     _labeled(counters, "jax_compile_seconds_total"))
        rate = 100.0 * hits / (hits + misses)
        lines.append(
            f"executable cache: {int(hits)} hit(s) / {int(misses)} "
            f"miss(es) ({rate:.0f}% hit rate) — warm loads "
            f"{load_s:.2f}s vs {cold_s:.2f}s cold first-call compile")
        errors = _labeled(counters, "compile_cache_errors_total")
        evict = counters.get("compile_cache_evictions_total")
        for lab, n in errors:
            lines.append(f"  cache entries rejected [{lab}]: {int(n)}")
        if evict:
            lines.append(f"  cache entries LRU-evicted: {int(evict)}")

    # ---- fused kernel suite / roofline (docs/perf-tuning.md) -------
    builds = _labeled(counters, "fused_kernel_builds_total")
    if builds:
        saved = {}
        for lab, v in _labeled(gauges, "kernel_bytes_saved_per_step"):
            saved[lab.split("=", 1)[-1].strip('"')] = v
        roof = {}
        for lab, v in _labeled(gauges, "kernel_roofline_attainment"):
            roof[lab.split("=", 1)[-1].strip('"')] = v
        per_kernel: Dict[str, Dict[str, int]] = {}
        for lab, n in builds:
            parts = dict(p.split("=", 1) for p in lab.split(","))
            k = parts.get("kernel", "?").strip('"')
            path = parts.get("path", "?").strip('"')
            per_kernel.setdefault(k, {})[path] = int(n)
        rows = []
        for k in sorted(set(per_kernel) | set(saved) | set(roof)):
            paths = per_kernel.get(k, {})
            path = "+".join(sorted(paths)) or "-"
            sv = saved.get(k)
            rf = roof.get(k)
            rows.append([
                k, path, sum(paths.values()),
                _fmt_bytes(sv) + "/step" if sv else "-",
                f"{rf:.2f}x" if rf is not None else "-"])
        lines += ["", "fused kernel suite (path=lax means the Pallas "
                  "probe declined — XLA fuses the same math; roofline "
                  "1.0 = HBM-bandwidth-bound floor reached):",
                  _table(rows, ["kernel", "path", "builds",
                                "bytes saved", "roofline"])]

    # ---- health ----------------------------------------------------
    nonfinite = _labeled(counters, "train_nonfinite_total")
    events = _labeled(counters, "watchdog_events_total")
    status = gauges.get("train_health_status", 0)
    verdict = {0: "healthy", 1: "warned", 2: "HALTED"}.get(
        int(status), "?")
    lines += ["", f"health: {verdict}"]
    for lab, n in nonfinite:
        lines.append(f"  non-finite steps [{lab}]: {int(n)}")
    for lab, n in events:
        lines.append(f"  watchdog events [{lab}]: {int(n)}")
    retries = counters.get("train_retry_total")
    if retries:
        lines.append(f"  retry-loop restores: {int(retries)}")

    # ---- input pipeline -------------------------------------------
    waits = _labeled(hists, "data_batch_wait_seconds")
    for lab, h in waits:
        lines.append(
            f"data wait [{lab or 'pipeline'}]: p50 "
            f"{_fmt_seconds(h['p50'])}  p95 {_fmt_seconds(h['p95'])} "
            f"({h['count']} batches)")

    # ---- device ----------------------------------------------------
    in_use = _labeled(gauges, "device_bytes_in_use")
    limit = dict(_labeled(gauges, "device_bytes_limit"))
    for lab, v in in_use:
        cap = limit.get(lab)
        pct = f" ({100 * v / cap:.0f}% of limit)" if cap else ""
        lines.append(f"HBM in use [{lab}]: {v / (1 << 30):.2f} GiB{pct}")
    stale = [lab for lab, v in
             _labeled(gauges, "device_telemetry_stale") if v]
    if stale:
        lines.append(f"  STALE telemetry on device(s): {stale}")

    # ---- trace aggregation ----------------------------------------
    if trace_events:
        agg: Dict[str, List[float]] = {}
        for e in trace_events:
            if e.get("ph") == "X":
                agg.setdefault(e["name"], []).append(
                    e.get("dur", 0.0) / 1e6)
        rows = [[name, len(durs), _fmt_seconds(sum(durs) / len(durs)),
                 f"{sum(durs):.2f}s"]
                for name, durs in sorted(
                    agg.items(), key=lambda kv: -sum(kv[1]))[:12]]
        if rows:
            lines += ["", "trace spans (top by total time):",
                      _table(rows, ["span", "count", "mean", "total"])]
    return "\n".join(lines)


# ------------------------------------------------------------- loadtest
def _peek_loadtest(path: Optional[str]) -> Optional[Dict]:
    """The loadtest-report document, when ``path`` is one (the
    ``kind`` tag, or a capacity_planning section); None otherwise."""
    if not path:
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if isinstance(doc, dict) and (
            doc.get("kind") == "zoo_loadtest_report"
            or "capacity_planning" in doc):
        return doc
    return None


def render_loadtest_report(label: str, doc: Dict) -> str:
    """Render a ``zoo-loadtest`` report document: the SLO verdict
    check-by-check, then the capacity-planning table fitted from the
    run's ramp."""
    lines = [f"== loadtest report: {label} "
             f"(scenario {doc.get('scenario', '?')}) =="]
    verdict = doc.get("verdict") or {}
    lines.append(f"verdict: "
                 f"{'PASS' if verdict.get('passed') else 'FAIL'}")
    for c in verdict.get("checks", []):
        mark = ("SKIP" if c.get("skipped")
                else "ok  " if c.get("passed") else "FAIL")
        lines.append(f"  [{mark}] {c.get('name')}: {c.get('detail')}")
    lat = verdict.get("latency") or {}
    if lat:
        lines.append(
            "latency (from SCHEDULED is the coordinated-omission-"
            "safe basis the verdict gates on): "
            + "  ".join(f"{k}={v:.1f}ms"
                        for k, v in sorted(lat.items())))
    cap = doc.get("capacity_planning") or {}
    rows = [[w["window_s"][0], w["offered_rps"], w["replicas"],
             w["rps_per_replica"], w["p99_from_scheduled_ms"],
             "yes" if w["met_slo"] else "NO"]
            for w in cap.get("windows", [])]
    if rows:
        lines += ["", f"capacity fit (target p99 <= "
                  f"{cap.get('target_p99_ms', 0):.0f}ms):",
                  _table(rows, ["t0", "offered rps", "replicas",
                                "rps/replica", "p99 ms", "met SLO"])]
    per = cap.get("rps_per_replica_at_slo")
    if per:
        needed = cap.get("replicas_for", {})
        lines.append(
            f"plan: {per:.1f} req/s per replica at the target — "
            + "  ".join(f"{k}rps needs {v}"
                        for k, v in needed.items()))
    else:
        lines.append("plan: NO window met the target SLO — the fit "
                     "has no feasible point (add capacity or relax "
                     "the target)")
    return "\n".join(lines)


# ---------------------------------------------------------- req forensics
def _segments(stations: List[Dict]) -> List[Tuple[str, float, float, Dict]]:
    """(station, offset_s, segment_s, attrs) per mark, time-ordered.
    A segment is the time since the previous station — the wait the
    request spent to REACH this station — so the segments sum to the
    timeline's measured latency by construction."""
    marks = sorted(stations, key=lambda s: float(s.get("t", 0.0)))
    out = []
    prev = marks[0].get("t", 0.0) if marks else 0.0
    for m in marks:
        t = float(m.get("t", 0.0))
        attrs = {k: v for k, v in m.items()
                 if k not in ("station", "t")}
        out.append((m.get("station", "?"), t, max(t - prev, 0.0),
                    attrs))
        prev = t
    return out


def render_requests_report(label: str, doc: Dict,
                           top: int = 10) -> str:
    """The slowest-request waterfall: per-station breakdown of where
    each tail request's time went, plus the aggregate station profile
    of the tail.  ``doc`` is a merged ``requests.json`` document
    (``aggregator.merge_requests``)."""
    tls = doc.get("timelines") or []
    lines = [f"== request forensics: {label} =="]
    hosts = doc.get("hosts_merged")
    lines.append(
        f"{len(tls)} timeline(s) kept"
        + (f" across {hosts} host(s)" if hosts else "")
        + f"; sampler kept {doc.get('kept', len(tls))} / dropped "
          f"{doc.get('dropped', 0)} (tail-based: errors/sheds/"
          f"quarantines + slowest-K always survive)")
    by_outcome: Dict[str, int] = {}
    for tl in tls:
        oc = tl.get("outcome", "?")
        by_outcome[oc] = by_outcome.get(oc, 0) + 1
    if by_outcome:
        lines.append("outcomes: " + "  ".join(
            f"{k}={v}" for k, v in sorted(by_outcome.items())))
    if not tls:
        lines.append("no timelines — was the run traced? "
                     "(observability.reqtrace on, requests.json "
                     "flushed/exported)")
        return "\n".join(lines)

    ranked = sorted(tls, key=lambda t: -float(t.get("latency_s", 0.0)))
    shown = ranked[:top]
    # aggregate tail profile: which station dominates the slow set
    agg: Dict[str, float] = {}
    for tl in shown:
        for st, _off, seg, _a in _segments(tl.get("stations") or []):
            agg[st] = agg.get(st, 0.0) + seg
    lines.append("")
    lines.append(f"slowest {len(shown)} request(s) — station "
                 f"waterfall (segment = time to REACH the station; "
                 f"segments sum to the measured latency):")
    for i, tl in enumerate(shown, 1):
        segs = _segments(tl.get("stations") or [])
        lat = float(tl.get("latency_s", 0.0))
        dominant = max(segs, key=lambda s: s[2])[0] if segs else "-"
        lines.append(
            f"\n#{i}  trace {tl.get('trace_id', '?')}  "
            f"[{tl.get('outcome', '?')}]  "
            f"{tl.get('transport') or '?'}:"
            f"{tl.get('endpoint') or 'default'}  "
            f"latency {_fmt_seconds(lat)}  dominant={dominant}")
        rows = []
        for st, off, seg, attrs in segs:
            extra = "  ".join(f"{k}={v}" for k, v
                              in sorted(attrs.items()))
            bar = "#" * min(int(round(40 * seg / lat))
                            if lat > 0 else 0, 40)
            rows.append([st, f"+{_fmt_seconds(off)}",
                         _fmt_seconds(seg), bar, extra])
        lines.append(_table(rows, ["station", "offset", "segment",
                                   "", "attrs"]))
        ssum = sum(s[2] for s in segs)
        lines.append(f"    segments sum {_fmt_seconds(ssum)} vs "
                     f"measured {_fmt_seconds(lat)}")
    total = sum(agg.values()) or 1e-12
    rows = [[st, _fmt_seconds(v), f"{100 * v / total:.0f}%"]
            for st, v in sorted(agg.items(), key=lambda kv: -kv[1])]
    lines += ["", "tail profile (summed over the slowest set — the "
              "station to fix first):",
              _table(rows, ["station", "total", "share"])]
    return "\n".join(lines)


# ----------------------------------------------------------------- SLO
def _load_obs_module(name: str):
    """Load ``observability/<name>.py`` by FILE PATH (tsdb/slo/drift
    are stdlib-only by contract) — the same jax-free trick as the
    aggregator loader."""
    import importlib.util
    modname = f"_zoo_{name}"
    if modname in sys.modules:
        return sys.modules[modname]
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "analytics_zoo_tpu", "observability", f"{name}.py")
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _spark(values: List[float], width: int = 40) -> str:
    """A one-line ASCII timeline: 8-level bars, newest right."""
    if not values:
        return ""
    if len(values) > width:
        step = len(values) / width
        values = [values[int(i * step)] for i in range(width)]
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    bars = " .:-=+*#@"
    return "".join(
        bars[int((v - lo) / span * (len(bars) - 1))] for v in values)


def _find_slo_spec(target: str, explicit: Optional[str]) -> Optional[str]:
    """--slo-spec wins; else slo.yaml beside the run dir, else the
    repo's checked-in slo.yaml."""
    if explicit:
        return explicit
    candidates = [os.path.join(target, "slo.yaml")] \
        if os.path.isdir(target) else []
    candidates.append(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "slo.yaml"))
    for c in candidates:
        if os.path.isfile(c):
            return c
    return None


def render_incident_report(target: str) -> str:
    """The ``--incident`` section: zoo-doctor's causally-ordered
    timeline + ranked root-cause hypotheses for a finished run dir.
    Renders an existing ``incident.json`` (a file, or one inside the
    run dir) without re-diagnosing; otherwise runs the diagnoser
    in-process.  Entirely jax-free: incident loads by file path."""
    inc = _load_obs_module("incident")
    if os.path.isfile(target):
        with open(target) as f:
            doc = json.load(f)
    else:
        existing = os.path.join(target, "incident.json")
        if os.path.isfile(existing):
            with open(existing) as f:
                doc = json.load(f)
        else:
            doc = inc.diagnose(target)
    return inc.render_incident(doc)


def render_slo_report(target: str,
                      spec_path: Optional[str] = None) -> str:
    """The ``--slo`` section: error-budget timelines, burn-rate
    tables, alert transitions, and drift callouts — from a run dir's
    tsdb segments (``host-<k>/tsdb/``) or a ``slo_report.json``
    written by ``zoo-loadtest --slo-out``.  Entirely jax-free: tsdb/
    slo/drift load by file path."""
    # a slo_report.json document renders directly
    if os.path.isfile(target):
        with open(target) as f:
            doc = json.load(f)
        return _render_slo_doc(target, doc)
    tsdb = _load_obs_module("tsdb")
    slo = _load_obs_module("slo")
    drift = _load_obs_module("drift")
    store = tsdb.SeriesStore.from_run_dir(target)
    lines = [f"== SLO report: {target} =="]
    if not store.samples:
        lines.append(
            "no tsdb samples found (expected host-<k>/tsdb/seg-*."
            "jsonl — is observability.tsdb on and the run flushed?)")
        return "\n".join(lines)
    t0, t1 = store.time_range()
    lines.append(f"{len(store.samples)} sample(s) over "
                 f"{t1 - t0:.1f}s; {len(store.counter_keys(''))} "
                 f"counter / {len(store.gauge_keys(''))} gauge series")
    spec = _find_slo_spec(target, spec_path)
    if spec is None:
        lines.append("no SLO spec (--slo-spec slo.yaml) — rendering "
                     "drift only")
        objectives = []
    else:
        objectives = slo.load_slo_yaml(spec)
        lines.append(f"spec: {spec} ({len(objectives)} objective(s))")
    if objectives:
        engine = slo.SloEngine(objectives)
        times = sorted({s["t"] for s in store.samples})
        history: Dict[str, List] = {}
        for t in times:
            for st in engine.evaluate(store, now=t):
                history.setdefault(st.slo_key, []).append(st)
        for key in sorted(history):
            sts = history[key]
            last = sts[-1]
            lines += ["", f"objective {key} [{last.detail}] "
                      f"target {last.target:.2%}:"]
            lines.append(
                f"  now: alert={last.alert}  budget_remaining="
                f"{last.budget_remaining:.2f}  bad_fraction="
                f"{last.bad_fraction:.2%}")
            rows = [[w, f"{b['long']:.2f}", f"{b['short']:.2f}"]
                    for w, b in sorted(last.burn.items())]
            lines.append(_table(rows, ["window", "burn(long)",
                                       "burn(short)"]))
            budgets = [s.budget_remaining for s in sts]
            lines.append(f"  budget timeline [{min(budgets):.2f}.."
                         f"{max(budgets):.2f}]: "
                         f"{_spark(budgets)}")
            trans = engine.transitions(last.name, last.group)
            if trans:
                lines.append("  transitions: " + "  ".join(
                    f"+{t - t0:.1f}s->{lvl}" for t, lvl in trans))
    callouts = drift.drift_report(store, [""])
    drifting = [c for c in callouts if c["drifting"]]
    lines += ["", f"drift: {len(drifting)} of {len(callouts)} "
              f"series flagged (score >= 1.0 at peak)"]
    for c in (drifting or callouts[:3]):
        peak_off = (f"+{c['peak_at'] - t0:.1f}s"
                    if c.get("peak_at") is not None else "-")
        lines.append(
            f"  {'DRIFT ' if c['drifting'] else ''}{c['series']}: "
            f"peak {c['peak_score']:.2f} at {peak_off} "
            f"(last {c['score']:.2f}, {c['points']} pts)")
    return "\n".join(lines)


def _render_slo_doc(label: str, doc: Dict) -> str:
    """Render a ``zoo-loadtest --slo-out`` document."""
    lines = [f"== SLO report: {label} "
             f"(scenario {doc.get('scenario', '?')}) =="]
    for c in doc.get("checks", []):
        mark = "ok  " if c.get("passed") else "FAIL"
        lines.append(f"  [{mark}] {c.get('name')}: {c.get('detail')}")
    timeline = doc.get("timeline") or []
    if timeline:
        by_key: Dict[str, List[Dict]] = {}
        for row in timeline:
            for st in row:
                key = st.get("name", "?")
                if st.get("group"):
                    key += f"/{st['group']}"
                by_key.setdefault(key, []).append(st)
        for key in sorted(by_key):
            sts = by_key[key]
            budgets = [s.get("budget_remaining", 0.0) for s in sts]
            worst = max(sts, key=lambda s: {"ok": 0, "warn": 1,
                                            "page": 2}.get(
                                                s.get("alert"), 0))
            lines.append(
                f"  {key}: worst alert={worst.get('alert')}  budget "
                f"[{min(budgets):.2f}..{max(budgets):.2f}] "
                f"{_spark(budgets)}")
    return "\n".join(lines)


# ------------------------------------------------------------ multi-host
def _load_aggregator_module():
    """Load observability/aggregator.py by FILE PATH (not package
    import): the module is stdlib-only by contract, so the merge works
    on machines without jax installed."""
    import importlib.util
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "analytics_zoo_tpu", "observability", "aggregator.py")
    spec = importlib.util.spec_from_file_location("_zoo_aggregator",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    # register before exec: modules the aggregator itself path-loads
    # (reqtrace.py) define dataclasses, whose field-annotation
    # resolution needs the defining module present in sys.modules
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_batchjobs_report_module():
    """Load batchjobs/report.py (stdlib-only by contract) as a
    synthetic package by file path — same jax-free trick as the
    aggregator loader, but with a package shell so the module's
    relative imports (spec.py, manifest.py) resolve."""
    import importlib.util
    import types
    pkg_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "analytics_zoo_tpu", "batchjobs")
    name = "_zoo_batchjobs"
    if name + ".report" in sys.modules:
        return sys.modules[name + ".report"]
    pkg = types.ModuleType(name)
    pkg.__path__ = [pkg_dir]
    sys.modules[name] = pkg
    spec = importlib.util.spec_from_file_location(
        name + ".report", os.path.join(pkg_dir, "report.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def render_job_report(run_dir: str) -> str:
    """The --job section: shard progress table + capacity/cost report
    from the job ledger, then the fleet's batch_* counters and the
    per-host straggler callout joined from the merged host snapshots
    (when the workers left any)."""
    batch = _load_batchjobs_report_module()
    lines = [f"== batch job report: {run_dir} ==", "",
             batch.render_job_section(run_dir)]
    try:
        agg = _load_aggregator_module()
        aggregator = agg.ClusterAggregator.from_run_dir(run_dir,
                                                        offline=True)
        host_snaps, merged = aggregator.cluster_view()
    except Exception:
        host_snaps, merged = {}, None
    if host_snaps and merged:
        counters = {k: v for k, v in
                    merged.get("counters", {}).items()
                    if k.startswith("batch_")}
        if counters:
            lines += ["", "fleet batch counters (merged over "
                      f"{len(host_snaps)} host snapshot(s)):"]
            for k in sorted(counters):
                lines.append(f"  {k} = {counters[k]:g}")
        cluster = merged.get("cluster", {})
        if cluster.get("straggler"):
            lines.append(
                f"  STRAGGLER (step-time skew): "
                f"{cluster['straggler']} "
                f"(+{cluster.get('skew_fraction', 0.0):.0%} vs "
                f"median)")
    return "\n".join(lines)


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{v:.0f}B"
        v /= 1024.0
    return f"{v:.1f}TiB"


def render_cluster_report(run_dir: str, agg_mod=None,
                          merged_trace_out: Optional[str] = None
                          ) -> Tuple[str, Dict]:
    """The fleet-level report: skew table, straggler, bubbles,
    collectives, cluster totals.  Returns (text, merged_snapshot)."""
    agg = agg_mod if agg_mod is not None else _load_aggregator_module()
    # offline by definition: a finished run's recorded ports may have
    # been reused by unrelated processes — never scrape them here
    aggregator = agg.ClusterAggregator.from_run_dir(run_dir,
                                                    offline=True)
    # the same collect/merge/attribute path the live /metrics/cluster
    # serves, so the offline report and the endpoint can never
    # disagree about skew gauges or missing-host accounting
    host_snaps, merged = aggregator.cluster_view()
    if not host_snaps:
        raise SystemExit(
            f"{run_dir}: no worker snapshots found (expected "
            f"host-<k>/metrics.jsonl slots — launch with "
            f"zoo-launch --run-dir)")
    report = merged["cluster"]
    # persist the federated snapshot so a later run can gate against
    # it: obs_report.py --merge-hosts RUN_B --diff RUN_A/cluster_
    # snapshot.json compares cluster views, not one host vs four
    snap_path = os.path.join(run_dir, "cluster_snapshot.json")
    try:
        with open(snap_path, "w") as f:
            json.dump(merged, f, indent=2)
    except OSError:
        snap_path = None

    lines = [f"== cluster report: {run_dir} "
             f"({len(host_snaps)} hosts) =="]
    missing = report.get("missing_hosts")
    if missing:
        lines.append(
            f"MISSING: {len(missing)} of {report['expected_hosts']} "
            f"workers left no snapshot (crashed before first flush?): "
            f"{missing}")

    # ---- per-host step-time skew ----------------------------------
    rows = []
    for host in sorted(report["per_host"]):
        d = report["per_host"][host]
        rows.append([
            host, d["steps"], _fmt_seconds(d["mean_step_s"]),
            _fmt_seconds(d["p50_step_s"]),
            _fmt_seconds(d["mean_barrier_wait_s"])])
    if rows:
        lines += ["", "per-host step time (barrier wait ~0 on the "
                  "straggler, ~skew on the fastest host):",
                  _table(rows, ["host", "steps", "mean", "p50",
                                "barrier wait"])]
    if report.get("straggler"):
        lines.append(
            f"STRAGGLER: {report['straggler']} "
            f"(+{report['skew_fraction']:.0%} vs median step time, "
            f"skew {_fmt_seconds(report['skew_seconds'])})")
    elif len(host_snaps) >= 2:
        lines.append(
            f"no straggler beyond threshold (max-median skew "
            f"{_fmt_seconds(report.get('skew_seconds', 0.0))}, "
            f"{report.get('skew_fraction', 0.0):+.0%})")

    # ---- pipeline / collectives -----------------------------------
    bubble = report.get("pipeline_bubble_fraction")
    if bubble is not None:
        lines.append(f"pipeline bubble fraction: {bubble:.2f} "
                     f"(P-1 of M+P-1 ticks idle — raise "
                     f"num_microbatches to amortize)")
    coll = report.get("collectives")
    if coll:
        rows = []
        for op in sorted(coll):
            d = coll[op]
            secs = _fmt_seconds(d["seconds"]) if d["seconds"] else "-"
            rows.append([op, _fmt_bytes(d["bytes"]), secs])
        lines += ["", "collectives (estimated from sharding specs; "
                  "time needs observability.ici_gbps):",
                  _table(rows, ["op", "bytes", "est time"])]

    # ---- cluster-summed counters ----------------------------------
    totals = [(k, v) for k, v in sorted(merged["counters"].items())
              if v]
    if totals:
        rows = [[k, f"{v:.6g}"] for k, v in totals[:20]]
        lines += ["", "cluster totals (counters summed across hosts):",
                  _table(rows, ["counter", "total"])]
        if len(totals) > 20:
            lines.append(f"... and {len(totals) - 20} more")

    # ---- merged trace ---------------------------------------------
    out_path = merged_trace_out or os.path.join(run_dir,
                                                "merged_trace.json")
    try:
        merged_trace = agg.merge_traces(run_dir, out_path)
        n_ev = len(merged_trace.get("traceEvents", []))
        if n_ev:
            lines.append("")
            lines.append(
                f"merged trace: {out_path} ({n_ev} events, "
                f"{merged_trace['otherData']['hosts_merged']} hosts, "
                f"aligned on the launcher clock anchor — open in "
                f"https://ui.perfetto.dev)")
    except Exception as e:   # traces are optional artifacts
        lines.append(f"(trace merge skipped: {e})")
    if snap_path:
        lines.append(f"cluster snapshot: {snap_path} (gate a later "
                     f"run with --merge-hosts RUN --diff {snap_path})")
    return "\n".join(lines), merged


# ----------------------------------------------------------------- diff
# (metric selector, direction) pairs the diff gates on; "up" = higher
# is better (regression when it drops), "down" = lower is better
_DIFF_KEYS = [
    ("gauge", "train_throughput_samples_per_sec", "up"),
    ("gauge", "train_mfu", "up"),
    ("hist_p50", "train_step_latency_seconds", "down"),
    ("hist_p50", "train_step_time_seconds", "down"),
    ("hist_p50", "serving_request_latency_seconds", "down"),
    ("hist_p50", "data_batch_wait_seconds", "down"),
]


def _diff_values(snap: Dict, kind: str, name: str
                 ) -> List[Tuple[str, float]]:
    if kind == "gauge":
        return [(lab, float(v))
                for lab, v in _labeled(snap.get("gauges", {}), name)]
    return [(lab, float(h["p50"]))
            for lab, h in _labeled(snap.get("histograms", {}), name)
            if h.get("count")]


def render_diff(cur_label: str, cur: Dict, base_label: str, base: Dict,
                threshold: float) -> Tuple[str, int]:
    lines = [f"== diff: {cur_label} vs baseline {base_label} "
             f"(threshold {threshold:.0%}) =="]
    regressions = 0
    for kind, name, direction in _DIFF_KEYS:
        base_vals = dict(_diff_values(base, kind, name))
        for lab, cur_v in _diff_values(cur, kind, name):
            base_v = base_vals.get(lab)
            if base_v is None or base_v <= 0 or cur_v <= 0:
                continue
            change = cur_v / base_v - 1.0
            worse = change < -threshold if direction == "up" \
                else change > threshold
            mark = "  REGRESSION" if worse else ""
            regressions += bool(worse)
            disp = f"{name}{{{lab}}}" if lab else name
            if kind != "gauge":
                disp += " p50"
            lines.append(f"{disp}: {base_v:.6g} -> {cur_v:.6g} "
                         f"({change:+.1%}){mark}")
    if regressions:
        lines.append(f"{regressions} regression(s) beyond "
                     f"{threshold:.0%}")
    else:
        lines.append("no regressions beyond threshold")
    return "\n".join(lines), (1 if regressions else 0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a registry snapshot (+ optional Chrome "
                    "trace) into a training-health report; --diff "
                    "gates on regressions; --merge-hosts federates a "
                    "multi-host run directory")
    ap.add_argument("snapshot", nargs="?", default=None,
                    help="registry JSONL / bench_metrics"
                         ".json / snapshot JSON")
    ap.add_argument("--trace", default=None,
                    help="Chrome-trace JSON (Tracer.export_chrome_"
                         "trace or /trace)")
    ap.add_argument("--workload", default=None,
                    help="bench_metrics.json: report only this "
                         "workload")
    ap.add_argument("--diff", metavar="BASELINE", default=None,
                    help="compare against a baseline snapshot; exit 1 "
                         "on regression")
    ap.add_argument("--threshold", type=float, default=0.10)
    ap.add_argument("--merge-hosts", metavar="RUN_DIR", default=None,
                    help="launcher run directory (host-<k>/ slots): "
                         "render the cluster skew/straggler report, "
                         "merge the per-host traces, then report the "
                         "federated snapshot")
    ap.add_argument("--merged-trace-out", default=None,
                    help="where --merge-hosts writes the merged "
                         "Chrome trace (default "
                         "RUN_DIR/merged_trace.json)")
    ap.add_argument("--requests", metavar="RUN_DIR_OR_FILE",
                    default=None,
                    help="render the slowest-request station "
                         "waterfall from requests.json timelines: a "
                         "single requests.json, or a run directory "
                         "whose host-<k>/requests.json are merged "
                         "(partial timelines sharing a trace_id are "
                         "joined)")
    ap.add_argument("--slowest", type=int, default=10,
                    help="--requests: how many of the slowest "
                         "requests to waterfall (default 10)")
    ap.add_argument("--job", metavar="RUN_DIR", default=None,
                    help="batch job run directory (zoo-batch): render "
                         "the shard progress table, capacity/cost "
                         "report and per-host straggler callout from "
                         "the job ledger + merged host snapshots")
    ap.add_argument("--slo", metavar="RUN_DIR_OR_FILE", default=None,
                    help="render error-budget timelines, burn-rate "
                         "tables and drift callouts from a run dir's "
                         "tsdb segments (host-<k>/tsdb/), or a "
                         "slo_report.json from zoo-loadtest --slo-out")
    ap.add_argument("--slo-spec", metavar="SLO_YAML", default=None,
                    help="--slo: SLO objective spec file (default: "
                         "<run_dir>/slo.yaml, then the repo slo.yaml)")
    ap.add_argument("--incident", metavar="RUN_DIR_OR_FILE",
                    default=None,
                    help="render zoo-doctor's incident timeline + "
                         "ranked root-cause hypotheses from a run "
                         "dir's forensic artifacts (reuses an "
                         "existing incident.json when present), or "
                         "from an incident.json file directly")
    args = ap.parse_args(argv)

    if args.merge_hosts is None and args.snapshot is None \
            and args.requests is None and args.job is None \
            and args.slo is None and args.incident is None:
        ap.error("need a snapshot file, --merge-hosts RUN_DIR, "
                 "--requests RUN_DIR, --job RUN_DIR, --slo RUN_DIR, "
                 "or --incident RUN_DIR")

    if args.incident:
        print(render_incident_report(args.incident))
        print()
        if args.merge_hosts is None and args.snapshot is None \
                and args.requests is None and args.job is None \
                and args.slo is None:
            return 0

    if args.slo:
        print(render_slo_report(args.slo, args.slo_spec))
        print()
        if args.merge_hosts is None and args.snapshot is None \
                and args.requests is None and args.job is None:
            return 0

    if args.job:
        print(render_job_report(args.job))
        print()
        if args.merge_hosts is None and args.snapshot is None \
                and args.requests is None:
            return 0

    if args.requests:
        agg = _load_aggregator_module()
        merged_reqs = agg.merge_requests(args.requests)
        print(render_requests_report(args.requests, merged_reqs,
                                     top=args.slowest))
        print()
        if args.merge_hosts is None and args.snapshot is None:
            return 0

    if args.merge_hosts:
        text, merged = render_cluster_report(
            args.merge_hosts, merged_trace_out=args.merged_trace_out)
        print(text)
        print()
        # the federated snapshot then flows through the standard
        # report (and --diff, e.g. against a previous run's merge)
        snaps = [("cluster", merged)]
        if args.snapshot:
            snaps += load_snapshots(args.snapshot, args.workload)
    elif (doc := _peek_loadtest(args.snapshot)) is not None:
        # a zoo-loadtest report: verdict + capacity table first, then
        # the embedded registry snapshot through the standard report
        print(render_loadtest_report(args.snapshot, doc))
        print()
        snaps = ([(args.snapshot, doc["metrics"])]
                 if _is_snapshot(doc.get("metrics")) else [])
    else:
        snaps = load_snapshots(args.snapshot, args.workload)
    trace_events = None
    if args.trace:
        with open(args.trace) as f:
            doc = json.load(f)
        trace_events = doc.get("traceEvents", doc) \
            if isinstance(doc, dict) else doc

    rc = 0
    for label, snap in snaps:
        print(render_report(label, snap, trace_events))
        print()
    if args.diff:
        base = load_snapshots(args.diff, args.workload)
        # pair snapshots by label (multi-workload bench_metrics.json:
        # EVERY shared workload gates, a regression in any of them
        # fails); fall back to first-vs-first when labels don't
        # overlap (plain files, whose label is their path)
        base_map = dict(base)
        pairs = [(lab, snap, lab, base_map[lab])
                 for lab, snap in snaps if lab in base_map]
        if not pairs:
            pairs = [(snaps[0][0], snaps[0][1], base[0][0], base[0][1])]
        missing = [lab for lab, _ in snaps
                   if base_map and lab not in base_map and len(base) > 1]
        for cur_label, cur, base_label, base_snap in pairs:
            text, r = render_diff(cur_label, cur, base_label,
                                  base_snap, args.threshold)
            print(text)
            rc = max(rc, r)
        if missing:
            print(f"not in baseline (not gated): {missing}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
