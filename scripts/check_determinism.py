#!/usr/bin/env python
"""Determinism smoke check for the input-pipeline engine.

Runs two seeded pipeline epochs TWICE and compares content digests, and
re-composes a sharded stream against the unsharded one — the two
contracts (docs/data.md) whose silent regression would corrupt every
resumed or multi-host training run:

  1. same seed  => bit-identical batch stream across runs;
  2. shard h of S sees rows [h*B:(h+1)*B] of every global batch, so
     concatenating all shards reproduces the unsharded stream;
  3. checkpoint at step k => the resumed stream is exactly batches
     k+1, k+2, ... (no replayed or skipped samples);
  4. a fixed-shape jitted step sees ZERO recompilations after its
     warmup (CompileMonitor smoke — a silent shape/dtype drift would
     recompile every step on TPU), and a deliberate post-warmup shape
     change IS flagged as churn.

Prints one JSON line and exits 0 (deterministic) / 1 (regression).
Pure CPU, a few seconds — run it from CI or the tier-1 wrapper
(tests/test_data_pipeline.py::test_check_determinism_script).
"""

import hashlib
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from analytics_zoo_tpu.data import DataPipeline  # noqa: E402

N, BATCH, SEED, EPOCHS = 1000, 32, 20260803, 2


def make_pipeline(batch_size=BATCH, shard_index=0, shard_count=1,
                  name="det"):
    rs = np.random.RandomState(7)
    x = rs.randn(N, 8).astype(np.float32)
    y = np.arange(N, dtype=np.int64).reshape(N, 1)
    return DataPipeline(x, y, batch_size=batch_size, seed=SEED,
                        shard_index=shard_index,
                        shard_count=shard_count, name=name)


def stream_digest(pipe, epochs=EPOCHS) -> str:
    h = hashlib.sha256()
    for _ in range(epochs):
        for bx, by in pipe:
            h.update(np.ascontiguousarray(bx).tobytes())
            h.update(np.ascontiguousarray(by).tobytes())
    return h.hexdigest()


def main() -> int:
    failures = []

    # 1 — cross-run digest
    d1 = stream_digest(make_pipeline(name="det-a"))
    d2 = stream_digest(make_pipeline(name="det-b"))
    if d1 != d2:
        failures.append("same-seed digests differ across runs")

    # 2 — shard recomposition
    shards = 4
    global_pipe = make_pipeline(name="det-g")
    shard_pipes = [make_pipeline(batch_size=BATCH // shards,
                                 shard_index=i, shard_count=shards,
                                 name=f"det-s{i}")
                   for i in range(shards)]
    for batches in zip(global_pipe, *shard_pipes):
        (gx, gy), parts = batches[0], batches[1:]
        if not (np.array_equal(gx, np.concatenate([p[0] for p in parts]))
                and np.array_equal(
                    gy, np.concatenate([p[1] for p in parts]))):
            failures.append("shard recomposition mismatch")
            break

    # 3 — checkpoint/resume exactness
    full = make_pipeline(name="det-f")
    reference = [by.ravel().tolist() for by in
                 (b[1] for _ in range(2) for b in full)]
    part = make_pipeline(name="det-p")
    it = iter(part)
    k = 11
    consumed = [next(it)[1].ravel().tolist() for _ in range(k)]
    state = part.state_dict()
    resumed = make_pipeline(name="det-r")
    resumed.load_state_dict(state)
    rest = [b[1].ravel().tolist() for _ in range(2) for b in resumed]
    # `resumed` finishes the interrupted epoch then runs 2 more full
    # epochs; compare the overlapping window against the reference
    if consumed + rest[:len(reference) - k] != reference:
        failures.append(
            f"resume from step {k} replayed or skipped samples")

    # 4 — zero recompilations after warmup (CompileMonitor smoke)
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.observability.diagnostics import CompileMonitor
    from analytics_zoo_tpu.observability.metrics import MetricsRegistry

    mon = CompileMonitor(warmup_calls=2, registry=MetricsRegistry())
    step = mon.wrap("det_step",
                    jax.jit(lambda a: (a * 2.0 + 1.0).sum()))
    fixed = jnp.ones((BATCH, 8), jnp.float32)
    for _ in range(6):
        float(step(fixed))
    st = mon.stats("det_step")
    if st.get("compiles") != 1 or st.get("recompiles_after_warmup"):
        failures.append(
            f"fixed-shape step recompiled after warmup: {st}")
    # the detector itself must fire on a real post-warmup shape change
    float(step(jnp.ones((BATCH * 2, 8), jnp.float32)))
    st = mon.stats("det_step")
    if st.get("recompiles_after_warmup") != 1:
        failures.append(
            f"post-warmup shape change not flagged as churn: {st}")

    out = {
        "check": "input_pipeline_determinism",
        "ok": not failures,
        "stream_digest": d1,
        "epochs": EPOCHS,
        "records": N,
        "batch_size": BATCH,
        "shards_checked": shards,
        "resume_step": k,
        "compile_monitor": {
            "compiles": st.get("compiles"),
            "recompiles_after_warmup": st.get("recompiles_after_warmup"),
            "compile_seconds": round(st.get("compile_seconds") or 0, 3),
        },
        "failures": failures,
    }
    print(json.dumps(out))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
