"""Shared jax-free loader for ``analytics_zoo_tpu.analysis``.

Used by ``scripts/zoolint`` and ``scripts/check_static.py``: registers
a STUB parent package, then loads the analysis package by file path,
so the real ``analytics_zoo_tpu/__init__.py`` (which imports jax)
never runs — the static passes must finish in seconds on CI images
with no accelerator stack (the contract ``scripts/obs_report.py``
keeps for the aggregator).  Process-local: interpreters using this
loader only ever run the linters.
"""

from __future__ import annotations

import importlib.util
import os
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_analysis_cli():
    """Return ``analytics_zoo_tpu.analysis.cli`` without importing
    jax, installing the stub parent + analysis package on first use."""
    if "analytics_zoo_tpu" not in sys.modules:
        stub = types.ModuleType("analytics_zoo_tpu")
        stub.__path__ = [os.path.join(REPO, "analytics_zoo_tpu")]
        sys.modules["analytics_zoo_tpu"] = stub
    if "analytics_zoo_tpu.analysis" not in sys.modules:
        pkg_dir = os.path.join(REPO, "analytics_zoo_tpu", "analysis")
        spec = importlib.util.spec_from_file_location(
            "analytics_zoo_tpu.analysis",
            os.path.join(pkg_dir, "__init__.py"),
            submodule_search_locations=[pkg_dir])
        mod = importlib.util.module_from_spec(spec)
        sys.modules["analytics_zoo_tpu.analysis"] = mod
        spec.loader.exec_module(mod)
    from analytics_zoo_tpu.analysis import cli
    return cli
