"""TFOptimizer: the TFPark distributed-training driver.

Reference: pyzoo/zoo/tfpark/tf_optimizer.py:332 — wraps an exported TF
loss graph in TFTrainingHelper and drives zoo's Estimator;
``from_keras`` (:537), ``from_loss`` (:467), ``from_train_op`` (:430).

TPU redesign: there is no session/graph export.  ``from_keras``
converts the tf.keras model to native layers (converter.py) and
``from_loss`` takes a native model + criterion directly; ``optimize``
drives the same distributed Estimator the Keras API uses (pjit train
step, psum gradient sync), so TFPark users keep their entry points
while the hot loop is pure XLA.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from analytics_zoo_tpu.common.triggers import MaxEpoch, Trigger
from analytics_zoo_tpu.pipeline.estimator.estimator import Estimator


class TFOptimizer:
    def __init__(self, model, criterion, optim_method, train_set,
                 batch_size: int = 32, val_set=None, val_methods=None,
                 model_dir: Optional[str] = None):
        self.model = model
        self.criterion = criterion
        self.optim_method = optim_method
        self.train_set = train_set
        self.batch_size = batch_size
        self.val_set = val_set
        if val_set is not None and not val_methods:
            # default to tracking validation loss (Model.fit does the same)
            from analytics_zoo_tpu.pipeline.api.keras.metrics import Loss
            from analytics_zoo_tpu.pipeline.api.keras import objectives
            val_methods = [Loss(objectives.get(criterion))]
        self.val_methods = val_methods
        self.model_dir = model_dir
        self.estimator = Estimator(model, optim_method=optim_method,
                                   model_dir=model_dir)

    # ------------------------------------------------------------ factories
    @classmethod
    def from_keras(cls, keras_model, dataset, optim_method=None,
                   model_dir: Optional[str] = None, **kwargs
                   ) -> "TFOptimizer":
        """tf.keras model (compiled) + TFDataset → TFOptimizer.

        (ref tf_optimizer.py:537: exports loss graph from the compiled
        keras model; here the model converts to native layers and the
        compiled loss/optimizer map to zoo equivalents.)
        """
        from analytics_zoo_tpu.tfpark.model import KerasModel
        if not isinstance(keras_model, KerasModel):
            keras_model = KerasModel(keras_model)
        zoo_model = keras_model.model
        assert zoo_model.loss is not None, \
            "compile() the keras model first (loss is required)"
        from analytics_zoo_tpu.pipeline.api.keras import objectives
        criterion = objectives.get(zoo_model.loss)
        optim = optim_method or zoo_model.optim_method
        fs, batch = _dataset_to_featureset(dataset, training=True)
        return cls(zoo_model, criterion, optim, fs, batch_size=batch,
                   val_set=getattr(dataset, "val_set", None),
                   model_dir=model_dir, **kwargs)

    @classmethod
    def from_loss(cls, model, criterion, dataset, optim_method=None,
                  model_dir: Optional[str] = None, **kwargs
                  ) -> "TFOptimizer":
        """Native model + criterion (objective name or callable) +
        TFDataset → TFOptimizer (ref tf_optimizer.py:467, where 'loss'
        is a TF scalar tensor; the functional equivalent is the
        criterion applied to the model's output)."""
        from analytics_zoo_tpu.pipeline.api.keras import (objectives,
                                                          optimizers)
        criterion = objectives.get(criterion)
        optim = optimizers.get(optim_method) if optim_method else None
        fs, batch = _dataset_to_featureset(dataset, training=True)
        return cls(model, criterion, optim, fs, batch_size=batch,
                   val_set=getattr(dataset, "val_set", None),
                   model_dir=model_dir, **kwargs)

    @classmethod
    def from_train_op(cls, train_op, loss, sess=None, dataset=None,
                      metrics=None, updates=None, tensor_with_value=None,
                      model_dir: Optional[str] = None, **kwargs
                      ) -> "TFOptimizer":
        """TF1 ``train_op`` + loss tensor → TFOptimizer, for the
        CANONICAL ``Optimizer.minimize``/``apply_gradients`` graph
        shapes only (ref tf_optimizer.py:430).

        The reference keeps the in-graph update op alive
        (TFTrainingHelperV2 + FakeOptimMethod); there is no TF session
        in this runtime's hot loop, so instead the graph is RECOGNIZED:
        the ``Apply*`` training ops map onto the native OptimMethod
        with the same update rule and hyperparameters, the loss head
        (reduce_mean over softmax-CE / sparse-softmax-CE /
        squared_difference) maps onto the matching objective, and the
        logits subgraph recompiles op-by-op to jnp (tf1_graph.py).
        Anything outside those shapes raises with the offending op
        named — substituting different update semantics silently is
        exactly what this entry point must never do.  For exotic
        graphs, migrate to ``from_loss`` (explicit optimizer) or pass
        an optax.GradientTransformation as optim_method."""
        if updates is not None or tensor_with_value is not None:
            raise NotImplementedError(
                "from_train_op: 'updates' / 'tensor_with_value' carry "
                "in-graph side effects that do not survive "
                "recompilation; migrate them into the model or "
                "from_loss")
        if metrics is not None:
            raise NotImplementedError(
                "from_train_op: 'metrics' are TF tensors in the "
                "source graph and are not recompiled; pass native "
                "val_methods to optimize()/Estimator.evaluate instead "
                "of silently dropping them")
        if dataset is None:
            raise ValueError(
                "from_train_op requires dataset= (a TFDataset, "
                "FeatureSet or (x, y) tuple); the placeholder-feeding "
                "dataset cannot be recovered from the graph here")
        import tensorflow as tf

        from analytics_zoo_tpu.pipeline.api.keras import (Sequential,
                                                          objectives)
        from analytics_zoo_tpu.tfpark.tf1_graph import recompile_train_op
        if sess is None:
            sess = tf.compat.v1.get_default_session()
            if sess is None:
                raise ValueError(
                    "from_train_op needs the session holding the "
                    "variable values (pass sess=)")
        net, criterion, optim = recompile_train_op(train_op, loss, sess)
        model = Sequential()
        model.add(net)
        fs, batch = _dataset_to_featureset(dataset, training=True)
        return cls(model, objectives.get(criterion), optim, fs,
                   batch_size=batch,
                   val_set=getattr(dataset, "val_set", None),
                   model_dir=model_dir, **kwargs)

    # -------------------------------------------------------------- running
    def set_train_summary(self, log_dir: str, app_name: str):
        self.estimator.set_tensorboard(log_dir, app_name)
        return self

    def set_constant_gradient_clipping(self, min_value, max_value):
        self.estimator.set_constant_gradient_clipping(min_value, max_value)
        return self

    def set_gradient_clipping_by_l2_norm(self, clip_norm):
        self.estimator.set_l2_norm_gradient_clipping(clip_norm)
        return self

    def optimize(self, end_trigger: Optional[Trigger] = None,
                 checkpoint_trigger: Optional[Trigger] = None):
        """Run distributed training (ref optimize(), tf_optimizer.py:645)."""
        end_trigger = end_trigger or MaxEpoch(1)
        self.estimator.train(
            self.train_set, self.criterion, end_trigger=end_trigger,
            checkpoint_trigger=checkpoint_trigger,
            validation_set=self.val_set,
            validation_method=self.val_methods,
            batch_size=self.batch_size)
        return self.estimator.history


def _dataset_to_featureset(dataset, training: bool):
    """TFDataset | FeatureSet | (x, y) → (FeatureSet, batch size)."""
    from analytics_zoo_tpu.feature.feature_set import FeatureSet
    from analytics_zoo_tpu.tfpark.tf_dataset import TFDataset
    if isinstance(dataset, TFDataset):
        batch = dataset.batch_size if training else dataset.batch_per_thread
        return dataset.feature_set, (batch if batch and batch > 0 else 32)
    if isinstance(dataset, FeatureSet):
        return dataset, 32
    if isinstance(dataset, tuple):
        x, y = dataset
        return FeatureSet.from_ndarrays(x, y), 32
    raise TypeError(f"unsupported dataset {type(dataset)}")
