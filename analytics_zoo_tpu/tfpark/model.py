"""TFPark KerasModel: distributed training of tf.keras models.

Reference: pyzoo/zoo/tfpark/model.py:34-373 — wraps a compiled tf.keras
model, ``fit`` runs it through TFOptimizer (graph export + per-executor
TF sessions under the BigDL allreduce), ``predict``/``evaluate`` via
TFNet.

TPU redesign: the architecture is converted to native layers once
(converter.py) and the native engine does everything; losses/optimizers
declared on the tf.keras compile are mapped to zoo equivalents.
``train_on_batch`` and weight get/set mirror the reference surface.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


_LOSS_MAP = {
    "sparse_categorical_crossentropy": "sparse_categorical_crossentropy",
    "categorical_crossentropy": "categorical_crossentropy",
    "binary_crossentropy": "binary_crossentropy",
    "mse": "mse", "mean_squared_error": "mse",
    "mae": "mae", "mean_absolute_error": "mae",
}


class KerasModel:
    def __init__(self, tf_keras_model):
        from analytics_zoo_tpu.tfpark.converter import convert_keras_model
        self.tf_model = tf_keras_model
        self.model = convert_keras_model(tf_keras_model)
        self._compiled = False
        self._maybe_compile()

    def _maybe_compile(self):
        m = self.tf_model
        loss = getattr(m, "loss", None)
        if loss is None:
            return
        loss_name = loss if isinstance(loss, str) else \
            getattr(loss, "name", getattr(loss, "__name__", None))
        mapped = _LOSS_MAP.get(str(loss_name))
        if mapped is None:
            return
        # tf.keras models usually end in a softmax; the probability
        # losses are correct as-is.
        opt = getattr(m, "optimizer", None)
        opt_name = type(opt).__name__.lower() if opt is not None else "adam"
        try:
            lr = float(np.asarray(opt.learning_rate))
        except Exception:
            lr = 0.001
        from analytics_zoo_tpu.pipeline.api.keras import optimizers as O
        zoo_opt = {"adam": O.Adam(lr=lr), "sgd": O.SGD(lr),
                   "rmsprop": O.RMSprop(lr=lr)}.get(opt_name, O.Adam(lr=lr))
        metrics = ["accuracy"] if getattr(m, "metrics_names", None) else []
        self.model.compile(optimizer=zoo_opt, loss=mapped, metrics=metrics)
        self._compiled = True

    # ------------------------------------------------------------- training
    def fit(self, x=None, y=None, batch_size=32, epochs=1,
            validation_data=None, distributed=True):
        assert self._compiled, \
            "compile the tf.keras model before wrapping (loss mapping)"
        return self.model.fit(x, y, batch_size=batch_size,
                              nb_epoch=epochs,
                              validation_data=validation_data)

    def train_on_batch(self, x, y):
        hist = self.model.fit(x, y, batch_size=len(np.asarray(y)),
                              nb_epoch=1)
        return hist[-1]["loss"]

    def evaluate(self, x, y, batch_size=32, distributed=True):
        return self.model.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size=256, distributed=True):
        return self.model.predict(x, batch_size=batch_size)

    # -------------------------------------------------------------- weights
    def get_weights(self) -> List[np.ndarray]:
        return self.model.get_weights()

    def set_weights(self, weights) -> None:
        self.model.set_weights(weights)

    def save_model(self, path: str) -> None:
        self.model.save_model(path)

    def load_weights(self, path: str) -> None:
        self.model.load_weights(path)
