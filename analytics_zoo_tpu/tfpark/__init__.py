from analytics_zoo_tpu.tfpark.model import KerasModel
from analytics_zoo_tpu.tfpark.tf_dataset import TFDataset

__all__ = ["KerasModel", "TFDataset"]
