from analytics_zoo_tpu.tfpark.model import KerasModel
from analytics_zoo_tpu.tfpark.tf_dataset import TFDataset
from analytics_zoo_tpu.tfpark.tf_optimizer import TFOptimizer
from analytics_zoo_tpu.tfpark.tf_predictor import TFPredictor
from analytics_zoo_tpu.tfpark.estimator import (ModeKeys, TFEstimator,
                                                TFEstimatorSpec)

__all__ = ["KerasModel", "TFDataset", "TFOptimizer", "TFPredictor",
           "TFEstimator", "TFEstimatorSpec", "ModeKeys"]
