"""TFEstimator: the tf.estimator-style model_fn API.

Reference: pyzoo/zoo/tfpark/estimator.py:30-318 — ``TFEstimator(
model_fn)`` where ``model_fn(features, labels, mode) ->
TFEstimatorSpec``; train/evaluate/predict run over TFDataset through
TFOptimizer/TFNet.

TPU redesign: ``model_fn`` builds a *native* model (once per mode) and
returns a spec naming the loss criterion and optimizer; the estimator
drives the shared distributed engine.  ModeKeys and the
train(input_fn, steps) surface match the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional


class ModeKeys:
    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "infer"


@dataclass
class TFEstimatorSpec:
    """(ref TFEstimatorSpec in estimator.py — loss/train_op/predictions)"""
    mode: str
    predictions: Any = None        # native model producing predictions
    loss: Any = None               # criterion name or Objective
    optim_method: Any = None       # OptimMethod (the train_op analogue)
    metrics: Any = None


class TFEstimator:
    def __init__(self, model_fn: Callable, model_dir: Optional[str] = None):
        self.model_fn = model_fn
        self.model_dir = model_dir
        self._specs = {}

    def _spec(self, mode: str) -> TFEstimatorSpec:
        if mode not in self._specs:
            spec = self.model_fn(features=None, labels=None, mode=mode)
            if not isinstance(spec, TFEstimatorSpec):
                raise TypeError("model_fn must return TFEstimatorSpec")
            self._specs[mode] = spec
        return self._specs[mode]

    @staticmethod
    def _resolve(input_fn, training: bool):
        """input_fn | dataset → (FeatureSet, batch size)."""
        dataset = input_fn() if callable(input_fn) else input_fn
        from analytics_zoo_tpu.tfpark.tf_optimizer import (
            _dataset_to_featureset)
        return _dataset_to_featureset(dataset, training=training)

    def train(self, input_fn, steps: Optional[int] = None,
              end_trigger=None, checkpoint_trigger=None):
        """(ref estimator.py train: builds TFOptimizer from the TRAIN
        spec and optimizes for ``steps``)."""
        from analytics_zoo_tpu.common.triggers import MaxEpoch, MaxIteration
        from analytics_zoo_tpu.pipeline.estimator.estimator import Estimator
        from analytics_zoo_tpu.pipeline.api.keras import objectives
        spec = self._spec(ModeKeys.TRAIN)
        fs, batch = self._resolve(input_fn, training=True)
        est = Estimator(spec.predictions, optim_method=spec.optim_method,
                        model_dir=self.model_dir)
        if end_trigger is None:
            end_trigger = MaxIteration(steps) if steps else MaxEpoch(1)
        est.train(fs, objectives.get(spec.loss), end_trigger=end_trigger,
                  checkpoint_trigger=checkpoint_trigger, batch_size=batch)
        self._trained_model = spec.predictions
        return self

    def evaluate(self, input_fn, eval_methods=None, steps=None):
        """Returns {metric_name: value} (ref estimator.py evaluate)."""
        from analytics_zoo_tpu.pipeline.estimator.estimator import Estimator
        from analytics_zoo_tpu.pipeline.api.keras import objectives
        spec = self._spec(ModeKeys.EVAL)
        model = getattr(self, "_trained_model", None) or spec.predictions
        fs, batch = self._resolve(input_fn, training=False)
        est = Estimator(model)
        return est.evaluate(fs, criterion=objectives.get(spec.loss)
                            if spec.loss else None,
                            validation_method=eval_methods or spec.metrics,
                            batch_size=batch)

    def predict(self, input_fn, predict_keys=None):
        """Yields prediction arrays (ref estimator.py predict)."""
        spec = self._spec(ModeKeys.PREDICT)
        model = getattr(self, "_trained_model", None) or spec.predictions
        fs, batch = self._resolve(input_fn, training=False)
        xs = fs.x if hasattr(fs, "x") else fs
        return model.predict(xs, batch_size=batch)
