"""Pretrained BERT checkpoint import.

The reference's BERT estimators consume google-research checkpoint
directories (pyzoo/zoo/tfpark/text/estimator/bert_base.py —
``bert_config_file`` + ``init_checkpoint``;
zoo/pipeline/api/keras/layers/BERT.scala:66).  This module loads those
published artifacts into the native BERT encoder
(pipeline/api/keras/layers/attention.py):

* a **google TF checkpoint** — ``bert_model.ckpt`` prefix or the
  directory holding it (read via ``tf.train.load_checkpoint``; TF
  kernels are already (in, out));
* a **HuggingFace transformers** ``BertModel`` instance or its torch
  state_dict (torch Linear weights are (out, in) and get transposed).

Per-block Q/K/V projections fuse into the encoder's single
``qkv_kernel`` matmul (concatenated on the output dim — the fused
``(B,T,3H) -> (b,t,3,heads,head_dim)`` reshape reads Q then K then V,
matching this concatenation order).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np


# -------------------------------------------------------------- config io
def bert_kwargs_from_config(config_path: str) -> Dict[str, Any]:
    """Translate a google ``bert_config.json`` into ``BERT(...)``
    kwargs (google field names per bert/modeling.py BertConfig)."""
    with open(config_path) as f:
        c = json.load(f)
    act = str(c.get("hidden_act", "gelu"))
    return dict(
        vocab=int(c["vocab_size"]),
        hidden_size=int(c["hidden_size"]),
        n_block=int(c["num_hidden_layers"]),
        n_head=int(c["num_attention_heads"]),
        intermediate_size=int(c["intermediate_size"]),
        max_position_len=int(c.get("max_position_embeddings", 512)),
        type_vocab_size=int(c.get("type_vocab_size", 2)),
        hidden_drop=float(c.get("hidden_dropout_prob", 0.1)),
        attn_drop=float(c.get("attention_probs_dropout_prob", 0.1)),
        # google "gelu" is the exact erf gelu; HF "gelu_new" is the
        # tanh approximation this framework calls "gelu"
        hidden_act={"gelu": "gelu_erf", "gelu_new": "gelu"}.get(act, act),
    )


# ------------------------------------------------------------ source readers
def _google_reader(src: str) -> Callable[[str, int], np.ndarray]:
    """get(name_template, block_index) over a TF checkpoint."""
    import tensorflow as tf

    prefix = src
    if os.path.isdir(src):
        ckpt = tf.train.latest_checkpoint(src)
        if ckpt is None:
            for cand in ("bert_model.ckpt", "model.ckpt"):
                if os.path.exists(os.path.join(src, cand + ".index")):
                    ckpt = os.path.join(src, cand)
                    break
        if ckpt is None:
            raise FileNotFoundError(
                f"no TF checkpoint found under {src!r}")
        prefix = ckpt
    reader = tf.train.load_checkpoint(prefix)

    def get(name: str) -> np.ndarray:
        return np.asarray(reader.get_tensor(name))

    return get


def _hf_reader(src) -> Callable[[str, int], np.ndarray]:
    """get(name) over a HF BertModel / torch state_dict, addressed by
    the GOOGLE variable names (translated internally)."""
    if hasattr(src, "state_dict"):
        src = src.state_dict()
    sd = {k: (v.detach().cpu().numpy() if hasattr(v, "detach")
              else np.asarray(v)) for k, v in src.items()}
    # some exports prefix with "bert."
    if not any(k.startswith("embeddings.") for k in sd) and any(
            k.startswith("bert.") for k in sd):
        sd = {k[len("bert."):]: v for k, v in sd.items()
              if k.startswith("bert.")}

    g2hf = {
        "bert/embeddings/word_embeddings":
            "embeddings.word_embeddings.weight",
        "bert/embeddings/token_type_embeddings":
            "embeddings.token_type_embeddings.weight",
        "bert/embeddings/position_embeddings":
            "embeddings.position_embeddings.weight",
        "bert/embeddings/LayerNorm/gamma": "embeddings.LayerNorm.weight",
        "bert/embeddings/LayerNorm/beta": "embeddings.LayerNorm.bias",
        "bert/pooler/dense/kernel": "pooler.dense.weight",
        "bert/pooler/dense/bias": "pooler.dense.bias",
    }

    def translate(name: str) -> str:
        if name in g2hf:
            return g2hf[name]
        # bert/encoder/layer_N/...
        parts = name.split("/")
        assert parts[1] == "encoder", name
        n = parts[2].split("_")[1]
        tail = "/".join(parts[3:])
        t2hf = {
            "attention/self/query/kernel": "attention.self.query.weight",
            "attention/self/query/bias": "attention.self.query.bias",
            "attention/self/key/kernel": "attention.self.key.weight",
            "attention/self/key/bias": "attention.self.key.bias",
            "attention/self/value/kernel": "attention.self.value.weight",
            "attention/self/value/bias": "attention.self.value.bias",
            "attention/output/dense/kernel":
                "attention.output.dense.weight",
            "attention/output/dense/bias": "attention.output.dense.bias",
            "attention/output/LayerNorm/gamma":
                "attention.output.LayerNorm.weight",
            "attention/output/LayerNorm/beta":
                "attention.output.LayerNorm.bias",
            "intermediate/dense/kernel": "intermediate.dense.weight",
            "intermediate/dense/bias": "intermediate.dense.bias",
            "output/dense/kernel": "output.dense.weight",
            "output/dense/bias": "output.dense.bias",
            "output/LayerNorm/gamma": "output.LayerNorm.weight",
            "output/LayerNorm/beta": "output.LayerNorm.bias",
        }
        return f"encoder.layer.{n}.{t2hf[tail]}"

    def get(name: str) -> np.ndarray:
        arr = sd[translate(name)]
        # torch Linear weights are (out, in); callers address GOOGLE
        # kernels, which are (in, out)
        return arr.T if name.endswith("/kernel") else arr

    return get


# ---------------------------------------------------------------- installer
def load_bert_checkpoint(model, src) -> None:
    """Import pretrained BERT weights into ``model`` in place.

    ``model`` is any graph Model containing the native BERT encoder
    (the encoder itself, or an estimator's head model — encoder layers
    precede head layers in creation order).  ``src`` is a google
    checkpoint prefix/directory, a HF ``BertModel``, or a torch
    state_dict.
    """
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.api.keras.layers.attention import (
        MultiHeadSelfAttention, PositionwiseFeedForward)
    from analytics_zoo_tpu.pipeline.api.keras.layers.embedding import (
        Embedding)
    from analytics_zoo_tpu.pipeline.api.keras.layers.normalization import (
        LayerNorm)

    get = _google_reader(src) if isinstance(src, (str, os.PathLike)) \
        else _hf_reader(src)

    embeds = [l for l in model.layers if isinstance(l, Embedding)]
    lns = [l for l in model.layers if isinstance(l, LayerNorm)]
    attns = [l for l in model.layers
             if isinstance(l, MultiHeadSelfAttention)]
    ffns = [l for l in model.layers
            if isinstance(l, PositionwiseFeedForward)]
    denses = [l for l in model.layers if isinstance(l, Dense)]
    n = len(attns)
    if len(embeds) < 3 or len(lns) != 2 * n + 1 or len(ffns) != n \
            or not denses:
        raise ValueError(
            f"model does not look like the native BERT encoder "
            f"(embeddings={len(embeds)}, layernorms={len(lns)}, "
            f"attention={n}, ffn={len(ffns)}, dense={len(denses)})")

    # lazy init: only initialises if the model has no variables yet —
    # re-importing into a fine-tuned model must NOT wipe head weights
    variables = model.get_variables()
    params, state = variables["params"], variables["state"]

    def put(layer, key: str, value: np.ndarray) -> None:
        cur = params[layer.name][key]
        if tuple(np.shape(cur)) != tuple(np.shape(value)):
            raise ValueError(
                f"{layer.name}.{key}: checkpoint shape "
                f"{tuple(np.shape(value))} != model shape "
                f"{tuple(np.shape(cur))}")
        params[layer.name][key] = np.asarray(value).astype(
            np.asarray(cur).dtype)

    # embeddings: builder creation order is token, segment, position
    tok, seg, pos = embeds[0], embeds[1], embeds[2]
    put(tok, "embeddings", get("bert/embeddings/word_embeddings"))
    put(seg, "embeddings", get("bert/embeddings/token_type_embeddings"))
    emb_pos = get("bert/embeddings/position_embeddings")
    # checkpoints carry 512 position rows; the model may be built with
    # a shorter max_position_len — slice the prefix (standard practice)
    model_pos = np.shape(params[pos.name]["embeddings"])[0]
    put(pos, "embeddings", emb_pos[:model_pos])
    put(lns[0], "gamma", get("bert/embeddings/LayerNorm/gamma"))
    put(lns[0], "beta", get("bert/embeddings/LayerNorm/beta"))

    for i in range(n):
        p = f"bert/encoder/layer_{i}"
        qkv_k = np.concatenate(
            [get(f"{p}/attention/self/{w}/kernel") for w in
             ("query", "key", "value")], axis=1)
        qkv_b = np.concatenate(
            [get(f"{p}/attention/self/{w}/bias") for w in
             ("query", "key", "value")])
        put(attns[i], "qkv_kernel", qkv_k)
        put(attns[i], "qkv_bias", qkv_b)
        put(attns[i], "out_kernel",
            get(f"{p}/attention/output/dense/kernel"))
        put(attns[i], "out_bias", get(f"{p}/attention/output/dense/bias"))
        put(lns[2 * i + 1], "gamma",
            get(f"{p}/attention/output/LayerNorm/gamma"))
        put(lns[2 * i + 1], "beta",
            get(f"{p}/attention/output/LayerNorm/beta"))
        put(ffns[i], "up_kernel", get(f"{p}/intermediate/dense/kernel"))
        put(ffns[i], "up_bias", get(f"{p}/intermediate/dense/bias"))
        put(ffns[i], "down_kernel", get(f"{p}/output/dense/kernel"))
        put(ffns[i], "down_bias", get(f"{p}/output/dense/bias"))
        put(lns[2 * i + 2], "gamma", get(f"{p}/output/LayerNorm/gamma"))
        put(lns[2 * i + 2], "beta", get(f"{p}/output/LayerNorm/beta"))

    # pooler = the first Dense created (BERT.build runs before any head)
    put(denses[0], "kernel", get("bert/pooler/dense/kernel"))
    put(denses[0], "bias", get("bert/pooler/dense/bias"))

    model.set_variables({"params": params, "state": state})


def bert_for_checkpoint(ckpt_dir: str, seq_len: int = 128, **overrides):
    """Build a native ``BERT`` from a google checkpoint directory's
    ``bert_config.json`` (the reference's bert_config_file contract)."""
    from analytics_zoo_tpu.pipeline.api.keras.layers.attention import BERT

    base = ckpt_dir if os.path.isdir(ckpt_dir) \
        else os.path.dirname(ckpt_dir)     # a ckpt PREFIX also works
    cfg_path = os.path.join(base, "bert_config.json")
    kwargs: Dict[str, Any] = {}
    if os.path.exists(cfg_path):
        kwargs = bert_kwargs_from_config(cfg_path)
    kwargs["seq_len"] = seq_len
    kwargs.update(overrides)
    return BERT(**kwargs)
