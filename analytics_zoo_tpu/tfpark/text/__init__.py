"""TFPark text models (ref: pyzoo/zoo/tfpark/text)."""

from analytics_zoo_tpu.tfpark.text.estimator import (  # noqa: F401
    BERTBaseEstimator, BERTClassifier, BERTNER, BERTSQuAD)
from analytics_zoo_tpu.tfpark.text.keras_models import (  # noqa: F401
    IntentEntity, NER, SequenceTagger, TextKerasModel)
from analytics_zoo_tpu.tfpark.text.bert_checkpoint import (  # noqa: F401
    bert_kwargs_from_config, load_bert_checkpoint)
