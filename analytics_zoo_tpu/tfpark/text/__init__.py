"""TFPark text models (ref: pyzoo/zoo/tfpark/text)."""

from analytics_zoo_tpu.tfpark.text.estimator import (  # noqa: F401
    BERTBaseEstimator, BERTClassifier, BERTNER, BERTSQuAD)
from analytics_zoo_tpu.tfpark.text.keras_models import (  # noqa: F401
    IntentEntity, NER, SequenceTagger, TextKerasModel)
