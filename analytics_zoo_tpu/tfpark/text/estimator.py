"""BERT estimators.

Reference: pyzoo/zoo/tfpark/text/estimator/{bert_base.py,
bert_classifier.py, bert_ner.py, bert_squad.py} — TFEstimator-based
fine-tuning heads over the google-research BERT graph.

TPU build: heads over the native BERT encoder
(pipeline/api/keras/layers/attention.py:BERT) with the same
train/evaluate/predict surface; inputs follow the reference's feature
dict {input_ids, token_type_ids, position_ids?, attention_mask}.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from analytics_zoo_tpu.pipeline.api.keras import layers as L
from analytics_zoo_tpu.pipeline.api.keras.layers.attention import BERT
from analytics_zoo_tpu.pipeline.api.keras.topology import Model


def _bert_io(bert: BERT):
    model = bert.build()
    return model, bert.cfg


class BERTBaseEstimator:
    """Feature-extraction base (ref bert_base.py): exposes the pooled
    and sequence outputs of the encoder plus the shared train surface."""

    head_on_pooled = True

    def __init__(self, bert: Optional[BERT] = None,
                 bert_checkpoint: Optional[str] = None, **bert_kwargs):
        """``bert_checkpoint`` is the reference's ``bert_config_file``
        + ``init_checkpoint`` contract (bert_base.py): a google BERT
        checkpoint directory — the encoder is configured from its
        ``bert_config.json`` and initialised from its weights (heads
        stay randomly initialised, as in fine-tuning)."""
        if bert is None and bert_checkpoint is not None:
            from analytics_zoo_tpu.tfpark.text.bert_checkpoint import (
                bert_for_checkpoint)
            bert = bert_for_checkpoint(bert_checkpoint, **bert_kwargs)
        self.bert = bert or BERT(**bert_kwargs)
        self.encoder, self.cfg = _bert_io(self.bert)
        self.model = self._build_model()
        if bert_checkpoint is not None:
            from analytics_zoo_tpu.tfpark.text.bert_checkpoint import (
                load_bert_checkpoint)
            load_bert_checkpoint(self.model, bert_checkpoint)
            if self.encoder is not self.model:
                # the head model and the bare encoder each hold their
                # own variable trees (layers are shared, variables are
                # not) — sync the encoder's copies from the loaded
                # model instead of re-reading the checkpoint
                mv = self.model.get_variables()
                ev = self.encoder.get_variables()
                for kind in ("params", "state"):
                    for lname in ev[kind]:
                        if lname in mv[kind]:
                            ev[kind][lname] = mv[kind][lname]
                self.encoder.set_variables(ev)

    # subclasses attach a head; the base serves raw features
    def _build_model(self) -> Model:
        return self.encoder

    @staticmethod
    def _inputs(features: dict, seq_len: int):
        ids = np.asarray(features["input_ids"])
        seg = np.asarray(features.get("token_type_ids",
                                      np.zeros_like(ids)))
        pos = np.asarray(features.get(
            "position_ids",
            np.broadcast_to(np.arange(seq_len), ids.shape)))
        mask = np.asarray(features.get("attention_mask",
                                       np.ones_like(ids)))
        return [ids, seg, pos, mask]

    def train(self, features: dict, labels, loss: str,
              optim_method=None, batch_size: int = 8, epochs: int = 1):
        from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
            AdamWeightDecay)
        x = self._inputs(features, self.cfg["seq_len"])
        self.model.compile(optim_method or AdamWeightDecay(lr=2e-5),
                           loss)
        self.model.fit(x, np.asarray(labels), batch_size=batch_size,
                       nb_epoch=epochs)
        return self

    def evaluate(self, features: dict, labels, batch_size: int = 8):
        x = self._inputs(features, self.cfg["seq_len"])
        return self.model.evaluate(x, np.asarray(labels),
                                   batch_size=batch_size)

    def predict(self, features: dict, batch_size: int = 8):
        x = self._inputs(features, self.cfg["seq_len"])
        return self.model.predict(x, batch_size=batch_size)


class BERTClassifier(BERTBaseEstimator):
    """Sequence classification head on the pooled output
    (ref bert_classifier.py: dense+softmax over pooled_output)."""

    def __init__(self, num_classes: int, dropout: float = 0.1,
                 **bert_kwargs):
        self.num_classes = num_classes
        self.dropout = dropout
        super().__init__(**bert_kwargs)

    def _build_model(self) -> Model:
        pooled = self.encoder.outputs[1]
        x = L.Dropout(self.dropout)(pooled)
        logits = L.Dense(self.num_classes)(x)
        return Model(self.encoder.inputs, logits)

    def train(self, features, labels, optim_method=None,
              batch_size: int = 8, epochs: int = 1):
        return super().train(
            features, labels,
            loss="sparse_categorical_crossentropy_with_logits",
            optim_method=optim_method, batch_size=batch_size,
            epochs=epochs)


class BERTNER(BERTBaseEstimator):
    """Token-classification head on the sequence output
    (ref bert_ner.py)."""

    def __init__(self, num_entities: int, dropout: float = 0.1,
                 **bert_kwargs):
        self.num_entities = num_entities
        self.dropout = dropout
        super().__init__(**bert_kwargs)

    def _build_model(self) -> Model:
        seq_out = self.encoder.outputs[0]
        x = L.Dropout(self.dropout)(seq_out)
        logits = L.TimeDistributed(L.Dense(self.num_entities))(x)
        return Model(self.encoder.inputs, logits)

    def train(self, features, labels, optim_method=None,
              batch_size: int = 8, epochs: int = 1):
        return super().train(
            features, labels,
            loss="sparse_categorical_crossentropy_with_logits",
            optim_method=optim_method, batch_size=batch_size,
            epochs=epochs)


class BERTSQuAD(BERTBaseEstimator):
    """Span-extraction head (ref bert_squad.py): per-token start/end
    logits over the sequence output."""

    def _build_model(self) -> Model:
        seq_out = self.encoder.outputs[0]
        span = L.TimeDistributed(L.Dense(2))(seq_out)   # (B, T, 2)
        return Model(self.encoder.inputs, span)

    def predict_spans(self, features: dict, batch_size: int = 8):
        """Return (start_logits, end_logits) arrays."""
        out = np.asarray(self.predict(features, batch_size=batch_size))
        return out[..., 0], out[..., 1]
