"""TFPark Keras-style text models.

Reference: pyzoo/zoo/tfpark/text/keras/{text_model.py, ner.py,
pos_tagging.py, intent_extraction.py} — NLP-architect-derived tf.keras
models (word+char BiLSTM taggers, joint intent/entity nets) wrapped in
``TextKerasModel``.

TPU build: the same architectures assembled from native layers; the
``fit/evaluate/predict/save_model`` surface comes from the zoo engine
directly.
"""

from __future__ import annotations

from typing import Optional

from analytics_zoo_tpu.pipeline.api.keras import layers as L
from analytics_zoo_tpu.pipeline.api.keras.engine import Input
from analytics_zoo_tpu.pipeline.api.keras.topology import Model


class TextKerasModel:
    """Base wrapper (ref text_model.py:TextKerasModel): holds a native
    graph model and forwards the training surface."""

    def __init__(self, model: Model):
        self.model = model

    def compile(self, optimizer, loss, metrics=None):
        self.model.compile(optimizer, loss, metrics)
        return self

    def fit(self, x, y, batch_size: int = 32, epochs: int = 1, **kwargs):
        return self.model.fit(x, y, batch_size=batch_size,
                              nb_epoch=epochs, **kwargs)

    def evaluate(self, x, y, batch_size: int = 32):
        return self.model.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: int = 256, distributed: bool = False):
        return self.model.predict(x, batch_size=batch_size)

    def save_model(self, path: str, over_write: bool = True):
        self.model.save_model(path, over_write=over_write)

    def get_weights(self):
        return self.model.get_weights()


class NER(TextKerasModel):
    """Named-entity recognizer (ref ner.py:21): word + char embeddings,
    char BiLSTM summarised per word, stacked word BiLSTMs, softmax tag
    head (the reference uses NLP-architect's NERCRF; the head here is a
    per-token softmax — same inputs/outputs surface)."""

    def __init__(self, num_entities: int, word_vocab_size: int,
                 char_vocab_size: int, word_length: int = 12,
                 seq_len: int = 50, word_emb_dim: int = 100,
                 char_emb_dim: int = 30, tagger_lstm_dim: int = 100,
                 dropout: float = 0.5):
        words = Input(shape=(seq_len,))
        chars = Input(shape=(seq_len, word_length))

        w = L.Embedding(word_vocab_size, word_emb_dim)(words)
        c = L.Embedding(char_vocab_size, char_emb_dim)(chars)
        # summarize each word's characters with a time-distributed BiLSTM
        c = L.TimeDistributed(
            L.Bidirectional(L.LSTM(char_emb_dim, return_sequences=False))
        )(c)
        x = L.Merge(mode="concat", concat_axis=-1)([w, c])
        x = L.Dropout(dropout)(x)
        x = L.Bidirectional(L.LSTM(tagger_lstm_dim,
                                   return_sequences=True))(x)
        x = L.Bidirectional(L.LSTM(tagger_lstm_dim,
                                   return_sequences=True))(x)
        out = L.TimeDistributed(
            L.Dense(num_entities, activation="softmax"))(x)
        super().__init__(Model([words, chars], out))


class SequenceTagger(TextKerasModel):
    """Joint POS + chunk tagger (ref pos_tagging.py:48): shared word
    embedding/BiLSTM trunk with two softmax heads."""

    def __init__(self, num_pos_labels: int, num_chunk_labels: int,
                 word_vocab_size: int, char_vocab_size: Optional[int] = None,
                 word_length: int = 12, feature_size: int = 100,
                 classifier: str = "softmax", seq_len: int = 50,
                 dropout: float = 0.2):
        words = Input(shape=(seq_len,))
        inputs = [words]
        w = L.Embedding(word_vocab_size, feature_size)(words)
        feats = w
        if char_vocab_size:
            chars = Input(shape=(seq_len, word_length))
            inputs.append(chars)
            c = L.Embedding(char_vocab_size, feature_size // 4)(chars)
            c = L.TimeDistributed(
                L.Bidirectional(L.LSTM(feature_size // 4,
                                       return_sequences=False)))(c)
            feats = L.Merge(mode="concat", concat_axis=-1)([w, c])
        x = L.Dropout(dropout)(feats)
        x = L.Bidirectional(L.LSTM(feature_size, return_sequences=True))(x)
        pos = L.TimeDistributed(
            L.Dense(num_pos_labels, activation="softmax"))(x)
        chunk = L.TimeDistributed(
            L.Dense(num_chunk_labels, activation="softmax"))(x)
        super().__init__(Model(inputs, [pos, chunk]))


class IntentEntity(TextKerasModel):
    """Joint intent classification + slot filling
    (ref intent_extraction.py:46): char-enriched BiLSTM encoder, an
    intent head off the final state and a per-token entity head."""

    def __init__(self, num_intents: int, num_entities: int,
                 word_vocab_size: int, char_vocab_size: int,
                 word_length: int = 12, seq_len: int = 50,
                 token_emb_size: int = 100, char_emb_size: int = 30,
                 tagger_lstm_dim: int = 100, dropout: float = 0.2):
        words = Input(shape=(seq_len,))
        chars = Input(shape=(seq_len, word_length))
        w = L.Embedding(word_vocab_size, token_emb_size)(words)
        c = L.Embedding(char_vocab_size, char_emb_size)(chars)
        c = L.TimeDistributed(
            L.Bidirectional(L.LSTM(char_emb_size,
                                   return_sequences=False)))(c)
        x = L.Merge(mode="concat", concat_axis=-1)([w, c])
        x = L.Dropout(dropout)(x)
        enc = L.Bidirectional(L.LSTM(tagger_lstm_dim,
                                     return_sequences=True))(x)
        # intent head: pool over time
        pooled = L.GlobalMaxPooling1D()(enc)
        intent = L.Dense(num_intents, activation="softmax")(pooled)
        # entity head: per-token tags
        ents = L.Bidirectional(L.LSTM(tagger_lstm_dim,
                                      return_sequences=True))(enc)
        ents = L.TimeDistributed(
            L.Dense(num_entities, activation="softmax"))(ents)
        super().__init__(Model([words, chars], [intent, ents]))
