"""TFDataset: feed tf.data pipelines (and other sources) into the zoo
engine.

Reference: pyzoo/zoo/tfpark/tf_dataset.py:115 with factories
``from_rdd/from_ndarrays/from_tf_data_dataset/...`` (:304-643) and the
per-executor tf.data execution of TFDataFeatureSet.scala:31.

TPU design: tf.data remains a *host-side* producer (exactly its role on
the reference's executors); batches drain into the columnar FeatureSet
path / the device prefetcher.  ``batch_size`` is the global training
batch; ``batch_per_thread`` maps to inference batch (reference
semantics).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.feature.feature_set import FeatureSet


class TFDataset:
    def __init__(self, feature_set: FeatureSet, batch_size: int = -1,
                 batch_per_thread: int = -1):
        self.feature_set = feature_set
        self.batch_size = batch_size
        self.batch_per_thread = batch_per_thread

    # ------------------------------------------------------------ factories
    @classmethod
    def from_ndarrays(cls, tensors, batch_size: int = -1,
                      batch_per_thread: int = -1,
                      val_tensors=None) -> "TFDataset":
        x, y = tensors if isinstance(tensors, tuple) else (tensors, None)
        fs = FeatureSet.from_ndarrays(x, y)
        ds = cls(fs, batch_size, batch_per_thread)
        if val_tensors is not None:
            vx, vy = val_tensors
            ds.val_set = FeatureSet.from_ndarrays(vx, vy, shuffle=False)
        return ds

    @classmethod
    def from_tf_data_dataset(cls, dataset, batch_size: int = -1,
                             batch_per_thread: int = -1,
                             max_items: Optional[int] = None
                             ) -> "TFDataset":
        """Materialise a (finite or capped) tf.data.Dataset host-side.

        The reference ships the serialized tf.data graph to executors
        (TFDataFeatureSet); here the host is the executor, so we simply
        drain the iterator into columnar storage.
        """
        xs, ys = [], []
        for i, item in enumerate(dataset.as_numpy_iterator()):
            if max_items is not None and i >= max_items:
                break
            if isinstance(item, tuple) and len(item) == 2:
                xs.append(item[0])
                ys.append(item[1])
            else:
                xs.append(item)
        x = np.stack(xs)
        y = np.stack(ys) if ys else None
        if y is not None and y.ndim == 1:
            y = y[:, None]
        return cls(FeatureSet.from_ndarrays(x, y),
                   batch_size, batch_per_thread)

    @classmethod
    def from_feature_set(cls, fs: FeatureSet, batch_size: int = -1,
                         batch_per_thread: int = -1) -> "TFDataset":
        return cls(fs, batch_size, batch_per_thread)

    @classmethod
    def from_tfrecord_file(cls, paths, features, label: Optional[str] = None,
                           batch_size: int = -1,
                           batch_per_thread: int = -1) -> "TFDataset":
        """Read TFRecord Examples with the pure-Python reader
        (feature/tfrecord.py; reference tf_dataset.py:479 used the
        tensorflow-hadoop input format).

        ``features``: list of feature names forming x — a single array
        when one name, else a list pytree in order (multi-input models);
        ``label``: optional label feature name.
        """
        from analytics_zoo_tpu.feature.tfrecord import load_tfrecord_arrays
        names = list(features) + ([label] if label else [])
        cols = load_tfrecord_arrays(paths, feature_names=names)
        missing = [n for n in names if n not in cols]
        if missing:
            raise ValueError(f"features {missing} not found in TFRecords "
                             f"(have {sorted(cols)})")
        xs = [cols[n] for n in features]
        x = xs[0] if len(xs) == 1 else xs
        y = cols[label] if label else None
        return cls(FeatureSet.from_ndarrays(x, y),
                   batch_size, batch_per_thread)

    @classmethod
    def from_image_set(cls, image_set, batch_size: int = -1,
                       batch_per_thread: int = -1) -> "TFDataset":
        """ImageSet → dataset (reference tf_dataset.py from_image_set)."""
        return cls(image_set.to_feature_set(),
                   batch_size, batch_per_thread)

    @classmethod
    def from_text_set(cls, text_set, batch_size: int = -1,
                      batch_per_thread: int = -1) -> "TFDataset":
        """TextSet (already word2idx + shaped) → dataset."""
        return cls(text_set.to_feature_set(),
                   batch_size, batch_per_thread)

    @classmethod
    def from_dataframe(cls, df, feature_cols, labels_cols=None,
                       batch_size: int = -1,
                       batch_per_thread: int = -1) -> "TFDataset":
        """pandas DataFrame columns → dataset (reference from_dataframe
        took a Spark DataFrame; the driver-side table here is pandas)."""
        def col(c):
            v = df[c].to_numpy()
            if v.dtype == object:   # column of arrays
                v = np.stack(v)
            return v
        xs = [col(c) for c in feature_cols]
        x = xs[0] if len(xs) == 1 else xs
        y = None
        if labels_cols:
            names = [labels_cols] if isinstance(labels_cols, str) \
                else list(labels_cols)
            ys = [y_[:, None] if y_.ndim == 1 else y_
                  for y_ in (col(c) for c in names)]
            y = ys[0] if len(ys) == 1 else ys
        return cls(FeatureSet.from_ndarrays(x, y),
                   batch_size, batch_per_thread)

    @classmethod
    def from_bytes(cls, records, labels=None, transform=None,
                   batch_size: int = -1,
                   batch_per_thread: int = -1) -> "TFDataset":
        """Encoded image bytes → decoded dataset (the in-process form
        of the reference's TFBytesDataset, tf_dataset.py:826: a byte
        RDD of JPEGs decoded per executor).

        ``transform``: optional ``Preprocessing`` applied per decoded
        HWC uint8 image (resize/normalize/...); without one, all
        images must already share a shape.
        """
        from analytics_zoo_tpu.feature.image import decode_image_bytes
        imgs = []
        for i, rec in enumerate(records):
            img = decode_image_bytes(rec, context=f"record {i}")
            if transform is not None:
                img = transform(img)
            imgs.append(np.asarray(img))
        x = np.stack(imgs)
        y = None
        if labels is not None:
            y = np.asarray(labels)
            if y.ndim == 1:
                y = y[:, None]
        return cls(FeatureSet.from_ndarrays(x, y),
                   batch_size, batch_per_thread)

    @classmethod
    def from_strings(cls, texts, labels=None, word_index=None,
                     sequence_length: int = 128,
                     max_words_num: int = -1,
                     shuffle: bool = True,
                     batch_size: int = -1,
                     batch_per_thread: int = -1) -> "TFDataset":
        """Raw strings → tokenize → word2idx → pad → dataset (the
        in-process form of the reference's TFTextDataset,
        tf_dataset.py:876: a string RDD run through TextSet stages).

        Returns the dataset; the fitted ``word_index`` is available as
        ``ds.word_index`` for inference-time reuse (pass it back in).
        """
        from analytics_zoo_tpu.feature.text import TextSet
        ts = (TextSet.from_texts(list(texts), labels).tokenize()
              .word2idx(max_words_num=max_words_num,
                        existing_map=word_index)
              .shape_sequence(sequence_length))
        ds = cls(ts.to_feature_set(shuffle=shuffle),
                 batch_size, batch_per_thread)
        ds.word_index = ts.word_index
        return ds

    @classmethod
    def from_string_rdd(cls, *a, **kw):
        raise NotImplementedError(
            "RDD sources require the Spark-bridge deployment; use "
            "from_strings / from_bytes / from_ndarrays / "
            "from_tf_data_dataset / from_feature_set")

    from_rdd = from_string_rdd
    from_bytes_rdd = from_string_rdd

    def get_training_batch_size(self) -> int:
        if self.batch_size <= 0:
            raise ValueError("this TFDataset was built for inference "
                             "(batch_per_thread); pass batch_size")
        return self.batch_size
