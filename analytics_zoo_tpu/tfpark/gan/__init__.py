"""GAN training (ref: pyzoo/zoo/tfpark/gan)."""

from analytics_zoo_tpu.tfpark.gan.gan_estimator import (  # noqa: F401
    GANEstimator, least_squares_discriminator_loss,
    least_squares_generator_loss, modified_discriminator_loss,
    modified_generator_loss, wasserstein_discriminator_loss,
    wasserstein_generator_loss)
