"""GANEstimator: alternating generator/discriminator training.

Reference: pyzoo/zoo/tfpark/gan/gan_estimator.py (GANEstimator over
TFGAN losses) paired with Scala ``GanOptimMethod`` (GanOptimMethod
.scala:26) which interleaves dSteps discriminator updates with gSteps
generator updates inside the distributed optimizer.

TPU redesign: the two adversarial updates are two jitted train steps
over the same device mesh; the alternation schedule is host-side and
exact (no fake-optimizer tricks needed — each step owns its param
pytree).  Loss functions mirror tf.contrib.gan's standard set.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("analytics_zoo_tpu.gan")


# --------------------------------------------------------------- GAN losses
def modified_generator_loss(fake_logits):
    """Non-saturating GAN loss: -log sigmoid(D(G(z)))."""
    return -jnp.mean(jax.nn.log_sigmoid(fake_logits))


def modified_discriminator_loss(real_logits, fake_logits):
    # log(1 - sigmoid(x)) == log_sigmoid(-x), numerically stable
    return -(jnp.mean(jax.nn.log_sigmoid(real_logits))
             + jnp.mean(jax.nn.log_sigmoid(-fake_logits)))


def wasserstein_generator_loss(fake_logits):
    return -jnp.mean(fake_logits)


def wasserstein_discriminator_loss(real_logits, fake_logits):
    return jnp.mean(fake_logits) - jnp.mean(real_logits)


def least_squares_generator_loss(fake_logits):
    return 0.5 * jnp.mean((fake_logits - 1.0) ** 2)


def least_squares_discriminator_loss(real_logits, fake_logits):
    return 0.5 * (jnp.mean((real_logits - 1.0) ** 2)
                  + jnp.mean(fake_logits ** 2))


class GANEstimator:
    def __init__(self, generator, discriminator,
                 generator_loss_fn: Callable = modified_generator_loss,
                 discriminator_loss_fn: Callable =
                 modified_discriminator_loss,
                 generator_optim_method=None,
                 discriminator_optim_method=None,
                 d_steps: int = 1, g_steps: int = 1,
                 model_dir: Optional[str] = None):
        """``generator``/``discriminator``: native models (noise→sample,
        sample→logits)."""
        from analytics_zoo_tpu.pipeline.api.keras import optimizers
        self.generator = generator
        self.discriminator = discriminator
        self.g_loss_fn = generator_loss_fn
        self.d_loss_fn = discriminator_loss_fn
        self.g_optim = optimizers.get(generator_optim_method) \
            or optimizers.Adam(lr=1e-4)
        self.d_optim = optimizers.get(discriminator_optim_method) \
            or optimizers.Adam(lr=1e-4)
        self.d_steps = d_steps
        self.g_steps = g_steps
        self.model_dir = model_dir
        self._built = False

    def _build(self, rng):
        g_rng, d_rng = jax.random.split(rng)
        gv = self.generator.init(rng=g_rng)
        dv = self.discriminator.init(rng=d_rng)
        self.g_params, self.g_state = gv["params"], gv["state"]
        self.d_params, self.d_state = dv["params"], dv["state"]
        self.g_opt_state = self.g_optim.init(self.g_params)
        self.d_opt_state = self.d_optim.init(self.d_params)

        gen, disc = self.generator, self.discriminator
        g_loss_fn, d_loss_fn = self.g_loss_fn, self.d_loss_fn

        def d_step(g_params, d_params, g_state, d_state, d_opt_state,
                   real, noise, rng):
            def loss(dp):
                # one key per stochastic apply: reusing `rng` would
                # hand G and both D passes identical dropout masks
                g_key, dr_key, df_key = jax.random.split(rng, 3)
                fake, _ = gen.apply(g_params, noise, state=g_state,
                                    training=True, rng=g_key)
                fake = jax.lax.stop_gradient(fake)
                real_logits, ds = disc.apply(dp, real, state=d_state,
                                             training=True, rng=dr_key)
                fake_logits, _ = disc.apply(dp, fake, state=ds,
                                            training=True, rng=df_key)
                return d_loss_fn(real_logits, fake_logits), ds
            (l, new_state), grads = jax.value_and_grad(
                loss, has_aux=True)(d_params)
            updates, new_opt = self.d_optim.update(grads, d_opt_state,
                                                   d_params)
            return jax.tree_util.tree_map(
                lambda p, u: p + u, d_params, updates), new_state, \
                new_opt, l

        def g_step(g_params, d_params, g_state, d_state, g_opt_state,
                   noise, rng):
            def loss(gp):
                g_key, d_key = jax.random.split(rng)
                fake, gs = gen.apply(gp, noise, state=g_state,
                                     training=True, rng=g_key)
                fake_logits, _ = disc.apply(d_params, fake, state=d_state,
                                            training=True, rng=d_key)
                return g_loss_fn(fake_logits), gs
            (l, new_state), grads = jax.value_and_grad(
                loss, has_aux=True)(g_params)
            updates, new_opt = self.g_optim.update(grads, g_opt_state,
                                                   g_params)
            return jax.tree_util.tree_map(
                lambda p, u: p + u, g_params, updates), new_state, \
                new_opt, l

        from analytics_zoo_tpu.compile import engine_jit
        self._d_step = engine_jit(d_step, key_hint="gan_d_step")
        self._g_step = engine_jit(g_step, key_hint="gan_g_step")
        self._built = True

    def train(self, real_data, noise_dim: int, batch_size: int = 32,
              steps: int = 100, rng=None, log_every: int = 50):
        """Alternate ``d_steps`` discriminator and ``g_steps`` generator
        updates per iteration (GanOptimMethod semantics)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if not self._built:
            init_rng, rng = jax.random.split(rng)
            self._build(init_rng)
        real_data = np.asarray(real_data)
        n = len(real_data)
        history = []
        for step in range(steps):
            rng, *keys = jax.random.split(rng, 1 + self.d_steps
                                          + self.g_steps)
            ki = iter(keys)
            d_loss = g_loss = None
            for _ in range(self.d_steps):
                # disjoint keys: one for the minibatch gather, one for
                # the noise draw, one consumed inside the jitted step
                idx_key, noise_key, step_key = \
                    jax.random.split(next(ki), 3)
                idx = jax.random.randint(idx_key, (batch_size,), 0, n)
                # real_data lives on host, so indexing it needs host
                # indices — this one device pull per d_step is the
                # operation, not an accident
                # zoolint: disable=SYNC002 — host-side minibatch gather
                real = real_data[np.asarray(idx)]
                noise = jax.random.normal(noise_key,
                                          (batch_size, noise_dim))
                self.d_params, self.d_state, self.d_opt_state, d_loss = \
                    self._d_step(self.g_params, self.d_params,
                                 self.g_state, self.d_state,
                                 self.d_opt_state, real, noise,
                                 step_key)
            for _ in range(self.g_steps):
                noise_key, step_key = jax.random.split(next(ki))
                noise = jax.random.normal(noise_key,
                                          (batch_size, noise_dim))
                self.g_params, self.g_state, self.g_opt_state, g_loss = \
                    self._g_step(self.g_params, self.d_params,
                                 self.g_state, self.d_state,
                                 self.g_opt_state, noise, step_key)
            entry = {}
            if d_loss is not None:
                entry["d_loss"] = float(d_loss)
            if g_loss is not None:
                entry["g_loss"] = float(g_loss)
            if (step + 1) % log_every == 0:
                log.info("step %d %s", step + 1,
                         " ".join(f"{k} {v:.4f}" for k, v in
                                  entry.items()))
            history.append(entry)
        return history

    def generate(self, noise) -> np.ndarray:
        """Sample from the trained generator."""
        out, _ = self.generator.apply(self.g_params, jnp.asarray(noise),
                                      state=self.g_state, training=False)
        return np.asarray(out)
