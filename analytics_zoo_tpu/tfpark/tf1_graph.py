"""TF1 train_op recognition + canonical-graph recompilation.

Reference: pyzoo/zoo/tfpark/tf_optimizer.py:420-450 — ``from_train_op``
walks the user's TF1 graph, extracts (grads, variables) and keeps the
in-graph update op as the optimizer (FakeOptimMethod).

TPU redesign: there is no TF session in the hot loop, so the in-graph
update op cannot be "kept".  Instead this module RECOGNIZES the
canonical ``Optimizer.minimize`` / ``apply_gradients`` graph shapes —
the ``Apply*``/``ResourceApply*`` training ops ``minimize`` emits — and
maps them onto the matching native OptimMethod (same update rule, same
hyperparameters, read out of the graph).  The forward/loss subgraph is
recompiled op-by-op into jnp (the TorchNet fx→jnp pattern,
net/torch_net.py) behind a tight whitelist: MatMul/BiasAdd stacks with
standard activations and the canonical loss heads.  ANYTHING outside
the canonical shapes refuses loudly with the offending op named —
silently substituting different update semantics is exactly what
``from_train_op`` must never do.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

import jax.numpy as jnp

from analytics_zoo_tpu.pipeline.api.keras.engine import Layer, Params


def _exotic(what: str) -> "NotImplementedError":
    return NotImplementedError(
        f"from_train_op only recognizes canonical TF1 "
        f"Optimizer.minimize/apply_gradients graphs; {what}. "
        "Migrate to TFOptimizer.from_loss(model, criterion, dataset, "
        "optim_method=...) for anything richer.")


# ---------------------------------------------------------------- optimizer
# training-op input layouts (tensorflow/core/ops/training_ops.cc);
# Resource* variants share them with a VarHandleOp in slot 0
_APPLY_SPECS = {
    "ApplyGradientDescent": dict(kind="sgd", var=0, grad=2, lr=1),
    "ApplyMomentum": dict(kind="momentum", var=0, grad=3, lr=2,
                          momentum=4),
    "ApplyKerasMomentum": dict(kind="momentum", var=0, grad=3, lr=2,
                               momentum=4),
    "ApplyAdam": dict(kind="adam", var=0, grad=9, lr=5, beta1=6,
                      beta2=7, epsilon=8),
    "ApplyAdagrad": dict(kind="adagrad", var=0, grad=3, lr=2),
    "ApplyAdagradV2": dict(kind="adagrad", var=0, grad=4, lr=2,
                           epsilon=3),
    "ApplyRMSProp": dict(kind="rmsprop", var=0, grad=7, lr=3, rho=4,
                         momentum=5, epsilon=6),
}
_APPLY_SPECS.update({f"Resource{k}": v for k, v in _APPLY_SPECS.items()})

# op types minimize() wraps around the Apply ops (grouping, the
# optional global_step bump) — safe to traverse / ignore
_WRAPPER_TYPES = ("NoOp", "Identity", "Group")
_IGNORED_TYPES = ("AssignAdd", "AssignAddVariableOp", "Const",
                  "ReadVariableOp", "VarHandleOp")
# optimizer bookkeeping writes (Adam's beta-power bump) — ignorable
# ONLY when the target is one of the Apply ops' own accumulators
_ASSIGN_TYPES = ("Assign", "AssignSub", "AssignVariableOp",
                 "AssignSubVariableOp")


def _collect_apply_ops(train_op) -> List:
    """The Apply*/ResourceApply* ops under a canonical train_op."""
    seen, out, assigns, stack = set(), [], [], [train_op]
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        if op.type in _APPLY_SPECS:
            out.append(op)
        elif op.type in _WRAPPER_TYPES:
            stack.extend(op.control_inputs)
            stack.extend(t.op for t in op.inputs)
        elif op.type in _ASSIGN_TYPES:
            # do NOT descend value inputs: Adam's beta-power bump is
            # Assign(handle, Mul(...)) and the Mul is bookkeeping, not
            # an exotic op; the own-state check below still polices
            # WHAT gets written
            assigns.append(op)
            stack.extend(op.control_inputs)
        elif op.type in _IGNORED_TYPES:
            # inputs too: minimize(global_step=...) hangs the update
            # group off a control dep of the AssignAdd's Const input
            stack.extend(op.control_inputs)
            stack.extend(t.op for t in op.inputs)
        else:
            raise _exotic(
                f"op {op.name!r} (type {op.type}) is not part of one")
    if not out:
        raise _exotic(
            f"no Apply*/ResourceApply* training op found under "
            f"{train_op.name!r}")
    # any Assign inside the train op must be the optimizer writing its
    # OWN accumulators (e.g. Adam's beta powers, which are inputs of
    # the Apply ops); a user-grouped side-effect assign would be
    # silently dropped by recompilation, so it refuses instead
    def _src_name(t):
        # dereference reads: the Apply op consumes beta_power VALUES
        # (ReadVariableOp), the Assign writes the HANDLE
        op = t.op
        while op.type in ("ReadVariableOp", "Identity") and op.inputs:
            op = op.inputs[0].op
        return op.name

    own_state = {_src_name(t) for a in out for t in a.inputs}
    for a in assigns:
        target = a.inputs[0].op.name
        if target not in own_state:
            raise _exotic(
                f"op {a.name!r} (type {a.type}) writes "
                f"{target!r}, which is not optimizer state")
    return out


def recognize_optimizer(train_op, sess):
    """train_op → (native OptimMethod, [variable ops]) or refuse."""
    from analytics_zoo_tpu.pipeline.api.keras import optimizers as opt

    apply_ops = _collect_apply_ops(train_op)
    kinds = {op.type for op in apply_ops}
    if len(kinds) > 1:
        raise _exotic(f"mixed training-op types {sorted(kinds)}")
    spec = _APPLY_SPECS[apply_ops[0].type]
    op0 = apply_ops[0]

    # the grads fed to the Apply ops must be minimize()'s own raw
    # autodiff outputs (the "gradients*/" name scope tf.gradients
    # creates) — a user-transformed gradient (clip_by_norm, scaling)
    # fed through apply_gradients would be silently replaced by the
    # native engine's plain d(loss)/d(var) otherwise
    for op in apply_ops:
        g = op.inputs[_APPLY_SPECS[op.type]["grad"]].op
        if not g.name.startswith("gradients"):
            raise _exotic(
                f"gradient {g.name!r} (type {g.type}) feeding "
                f"{op.name!r} is not a raw minimize() gradient — "
                "transformed gradients would be silently dropped")

    def hyper(slot_key):
        # hyperparameters must be graph CONSTANTS: an lr schedule
        # (exponential_decay & co.) would be frozen at its step-0
        # value — refuse rather than silently detach the schedule
        t = op0.inputs[spec[slot_key]]
        if t.op.type not in ("Const",):
            raise _exotic(
                f"optimizer input {slot_key}={t.op.name!r} (type "
                f"{t.op.type}) is not a constant — schedules/dynamic "
                "hyperparameters would be frozen at their current "
                "value")
        return float(sess.run(t))

    kind = spec["kind"]
    if kind == "sgd":
        method = opt.SGD(learning_rate=hyper("lr"))
    elif kind == "momentum":
        method = opt.SGD(learning_rate=hyper("lr"),
                         momentum=hyper("momentum"),
                         nesterov=bool(op0.get_attr("use_nesterov")))
    elif kind == "adam":
        method = opt.Adam(lr=hyper("lr"), beta_1=hyper("beta1"),
                          beta_2=hyper("beta2"),
                          epsilon=hyper("epsilon"))
    elif kind == "adagrad":
        kw = {"epsilon": hyper("epsilon")} if "epsilon" in spec else {}
        method = opt.Adagrad(lr=hyper("lr"), **kw)
    else:  # rmsprop
        if hyper("momentum") != 0.0:
            raise _exotic("RMSProp with momentum has no native "
                          "equivalent")
        method = opt.RMSprop(lr=hyper("lr"), decay_rate=hyper("rho"),
                             epsilon=hyper("epsilon"))
    variables = [op.inputs[spec["var"]].op for op in apply_ops]
    return method, variables


# ------------------------------------------------------------- loss head
_LOSS_HEADS = {
    "SparseSoftmaxCrossEntropyWithLogits":
        "sparse_categorical_crossentropy_with_logits",
    "SoftmaxCrossEntropyWithLogits":
        "categorical_crossentropy_with_logits",
}


def split_loss(loss):
    """loss tensor → (logits_tensor, labels_placeholder, criterion
    name) for the canonical heads:

    * ``reduce_mean(sparse_softmax_cross_entropy_with_logits)``
    * ``reduce_mean(softmax_cross_entropy_with_logits)``
    * ``reduce_mean(squared_difference(pred, y))`` (either order)
    """
    op = loss.op
    if op.type != "Mean":
        raise _exotic(f"loss head {op.name!r} (type {op.type}) is not "
                      "a reduce_mean over a recognized criterion")
    inner = op.inputs[0].op
    if inner.type in _LOSS_HEADS:
        # logits at input 0 ("features"), labels at input 1
        return (inner.inputs[0], inner.inputs[1],
                _LOSS_HEADS[inner.type])
    if inner.type == "SquaredDifference":
        a, b = inner.inputs[0], inner.inputs[1]
        if b.op.type == "Placeholder" and a.op.type != "Placeholder":
            return a, b, "mse"
        if a.op.type == "Placeholder" and b.op.type != "Placeholder":
            return b, a, "mse"
        raise _exotic("squared_difference needs exactly one "
                      "placeholder side (the labels)")
    raise _exotic(f"criterion op {inner.name!r} (type {inner.type}) "
                  "is not recognized")


# ---------------------------------------------------------------- emitter
_ACTIVATIONS = {
    "Relu": lambda x: jnp.maximum(x, 0.0),
    "Relu6": lambda x: jnp.clip(x, 0.0, 6.0),
    "Tanh": jnp.tanh,
    "Sigmoid": lambda x: 1.0 / (1.0 + jnp.exp(-x)),
    "Elu": lambda x: jnp.where(x > 0, x, jnp.expm1(x)),
    "Softmax": lambda x: jnp.exp(x - jnp.max(x, -1, keepdims=True))
    / jnp.sum(jnp.exp(x - jnp.max(x, -1, keepdims=True)), -1,
              keepdims=True),
}
_VAR_TYPES = ("VarHandleOp", "VariableV2", "Variable")


class TF1GraphNet(Layer):
    """A TF1 logits subgraph recompiled to jnp, as a trainable Layer
    (the TorchNet pattern for TF1 graphs): variables become params,
    the single non-label Placeholder becomes the layer input."""

    def __init__(self, logits, x_placeholder, values: Dict[str, np.ndarray],
                 constants: Dict[str, np.ndarray] = None, **kwargs):
        super().__init__(**kwargs)
        self._logits = logits
        self._x_name = x_placeholder.op.name
        self._values = values
        # frozen (non-trained) variables: not touched by the train_op,
        # so they embed as constants — same semantics as the TF graph
        self._constants = dict(constants or {})
        self._out_shape = tuple(
            None if d is None else int(d)
            for d in logits.shape.as_list())
        # validate the whole subgraph up front — a refusal at fit()
        # time would be far harder to act on
        self._emit({}, None, dry=True)

    def build(self, rng, input_shape) -> Params:
        return {name: jnp.asarray(v) for name, v in self._values.items()}

    def call(self, params, x, training=False, rng=None):
        return self._emit(params, x)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self._out_shape[1:]

    # ------------------------------------------------------------ internals
    def _emit(self, params, x, dry: bool = False):
        """Evaluate the TF subgraph as jnp at trace time (graph
        metadata is read in Python; only jnp values flow)."""
        import tensorflow as tf

        memo = {}

        def ev(t):
            key = t.ref()
            if key in memo:
                return memo[key]
            op = t.op
            if op.type == "Placeholder":
                if op.name != self._x_name:
                    raise _exotic(
                        f"unexpected extra placeholder {op.name!r} in "
                        "the logits graph")
                val = x
            elif op.type == "Const":
                val = jnp.asarray(
                    tf.make_ndarray(op.get_attr("value")))
            elif op.type in ("Identity", "ReadVariableOp"):
                val = ev(op.inputs[0])
            elif op.type in _VAR_TYPES:
                if op.name in self._constants:
                    val = jnp.asarray(self._constants[op.name])
                elif op.name in self._values:
                    val = jnp.asarray(self._values[op.name]) if dry \
                        else params[op.name]
                else:
                    raise _exotic(
                        f"variable {op.name!r} is neither trained by "
                        "the train_op nor snapshotted as a constant")
            elif op.type == "MatMul":
                if op.get_attr("transpose_a") or \
                        op.get_attr("transpose_b"):
                    raise _exotic(f"MatMul {op.name!r} with transpose")
                val = ev(op.inputs[0]) @ ev(op.inputs[1])
            elif op.type in ("BiasAdd", "Add", "AddV2"):
                val = ev(op.inputs[0]) + ev(op.inputs[1])
            elif op.type == "Sub":
                val = ev(op.inputs[0]) - ev(op.inputs[1])
            elif op.type == "Mul":
                val = ev(op.inputs[0]) * ev(op.inputs[1])
            elif op.type in _ACTIVATIONS:
                val = _ACTIVATIONS[op.type](ev(op.inputs[0]))
            else:
                raise _exotic(
                    f"op {op.name!r} (type {op.type}) in the logits "
                    "graph is outside the canonical whitelist")
            memo[key] = val
            return val

        if dry:
            # shape-only validation pass: substitute zeros for x
            x = jnp.zeros([1] + [int(d) if d is not None else 1
                                 for d in self._x_shape()[1:]],
                          jnp.float32)
        return ev(self._logits)

    def _x_shape(self):
        g = self._logits.graph
        ph = g.get_operation_by_name(self._x_name)
        return tuple(ph.outputs[0].shape.as_list())


def recompile_train_op(train_op, loss, sess):
    """→ (TF1GraphNet, criterion_name, optim_method).

    The one-call façade ``TFOptimizer.from_train_op`` uses: recognize
    the optimizer, split the loss head, recompile the logits subgraph,
    snapshot variable values from the session."""
    method, var_ops = recognize_optimizer(train_op, sess)
    logits, labels, criterion = split_loss(loss)
    if labels.op.type != "Placeholder":
        raise _exotic(
            f"labels {labels.op.name!r} (type {labels.op.type}) must "
            "be a Placeholder")
    values = {op.name: np.asarray(sess.run(op.outputs[0]))
              if op.type != "VarHandleOp"
              else _read_resource_var(op, sess)
              for op in var_ops}
    # find the input placeholder: the one feeding logits that is not
    # the labels; snapshot frozen variables (in the logits graph but
    # not trained by the train_op) as constants along the way
    x_ph, frozen_ops = _scan_logits_graph(logits, labels)
    constants = {op.name: np.asarray(sess.run(op.outputs[0]))
                 if op.type != "VarHandleOp"
                 else _read_resource_var(op, sess)
                 for op in frozen_ops if op.name not in values}
    in_shape = x_ph.shape.as_list()[1:]
    if any(d is None for d in in_shape):
        raise _exotic(
            f"input placeholder {x_ph.op.name!r} has unknown "
            f"non-batch dims {in_shape}")
    net = TF1GraphNet(logits, x_ph, values, constants=constants,
                      input_shape=tuple(int(d) for d in in_shape))
    return net, criterion, method


def _read_resource_var(handle_op, sess):
    """Value of a resource variable given its VarHandleOp."""
    graph = handle_op.graph
    for v in graph.get_collection("variables"):
        if v.op.name == handle_op.name:
            return np.asarray(sess.run(v))
    # fall back to the conventional read op minimize() leaves behind
    try:
        read = graph.get_tensor_by_name(handle_op.name + "/Read/"
                                        "ReadVariableOp:0")
        return np.asarray(sess.run(read))
    except Exception:
        raise _exotic(
            f"cannot read resource variable {handle_op.name!r}")


def _scan_logits_graph(logits, labels):
    """-> (x placeholder tensor, [variable ops in the subgraph])."""
    seen, phs, var_ops, stack = set(), [], [], [logits.op]
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        if op.type == "Placeholder":
            phs.append(op)
        elif op.type in _VAR_TYPES:
            var_ops.append(op)
        stack.extend(t.op for t in op.inputs)
    phs = [p for p in phs if p.name != labels.op.name]
    if len(phs) != 1:
        raise _exotic(
            f"expected exactly one input placeholder, found "
            f"{[p.name for p in phs]}")
    return phs[0].outputs[0], var_ops
