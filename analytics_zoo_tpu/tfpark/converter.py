"""tf.keras → native-layer conversion.

The reference's TFPark trains tf.keras models by exporting the TF graph
and running sessions on every executor under the BigDL optimizer
(tf_optimizer.py:103 TFModel export, TFTrainingHelper.scala:32).  The
TPU-native answer: convert the *architecture* to framework layers and
copy the weights — the converted model then trains on the MXU under the
zoo engine with zero TF in the hot loop.

Covered layer set = what the reference's TFPark examples use (MLPs,
convnets, RNN classifiers): InputLayer, Dense, Conv1D/2D,
(Max/Average/Global)Pooling, Flatten, Dropout, BatchNormalization,
Activation, ReLU/LeakyReLU/ELU/Softmax, Embedding, LSTM, GRU, Add,
Concatenate, Reshape, LayerNormalization, ZeroPadding2D.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from analytics_zoo_tpu.pipeline.api.keras import Sequential
from analytics_zoo_tpu.pipeline.api.keras import layers as L


def _act_name(act) -> str:
    name = getattr(act, "__name__", str(act))
    return {"linear": None}.get(name, name)


def convert_keras_model(tf_model):
    """Convert a *sequential-topology* tf.keras model; returns a native
    Sequential with identical weights."""
    import tensorflow as tf
    model = Sequential()
    first = True

    def input_shape_of(layer):
        shape = layer.get_build_config()["input_shape"]
        return tuple(shape[1:])

    for tfl in tf_model.layers:
        kw = {}
        if first:
            kw["input_shape"] = input_shape_of(tfl)
        cls = type(tfl).__name__
        cfg = tfl.get_config()
        if cls == "InputLayer":
            continue
        elif cls == "Dense":
            nl = L.Dense(cfg["units"],
                         activation=_act_name(cfg["activation"]),
                         bias=cfg["use_bias"], **kw)
        elif cls == "Conv2D":
            nl = L.Convolution2D(
                cfg["filters"], *cfg["kernel_size"],
                subsample=tuple(cfg["strides"]),
                border_mode=cfg["padding"],
                activation=_act_name(cfg["activation"]),
                bias=cfg["use_bias"], **kw)
        elif cls == "Conv1D":
            nl = L.Convolution1D(
                cfg["filters"], cfg["kernel_size"][0],
                strides=tuple(cfg["strides"]),
                border_mode=cfg["padding"],
                activation=_act_name(cfg["activation"]),
                bias=cfg["use_bias"], **kw)
        elif cls == "MaxPooling2D":
            nl = L.MaxPooling2D(pool_size=tuple(cfg["pool_size"]),
                                strides=tuple(cfg["strides"]),
                                border_mode=cfg["padding"], **kw)
        elif cls == "AveragePooling2D":
            nl = L.AveragePooling2D(pool_size=tuple(cfg["pool_size"]),
                                    strides=tuple(cfg["strides"]),
                                    border_mode=cfg["padding"], **kw)
        elif cls == "GlobalAveragePooling2D":
            nl = L.GlobalAveragePooling2D(**kw)
        elif cls == "GlobalMaxPooling2D":
            nl = L.GlobalMaxPooling2D(**kw)
        elif cls == "GlobalAveragePooling1D":
            nl = L.GlobalAveragePooling1D(**kw)
        elif cls == "GlobalMaxPooling1D":
            nl = L.GlobalMaxPooling1D(**kw)
        elif cls == "Flatten":
            nl = L.Flatten(**kw)
        elif cls == "Dropout":
            nl = L.Dropout(cfg["rate"], **kw)
        elif cls == "BatchNormalization":
            nl = L.BatchNormalization(epsilon=cfg["epsilon"],
                                      momentum=cfg["momentum"], **kw)
        elif cls == "LayerNormalization":
            nl = L.LayerNorm(epsilon=cfg["epsilon"], **kw)
        elif cls == "Activation":
            nl = L.Activation(cfg["activation"], **kw)
        elif cls == "ReLU":
            nl = L.Activation("relu", **kw)
        elif cls == "LeakyReLU":
            nl = L.LeakyReLU(cfg.get("negative_slope",
                                     cfg.get("alpha", 0.3)), **kw)
        elif cls == "ELU":
            nl = L.ELU(cfg.get("alpha", 1.0), **kw)
        elif cls == "Softmax":
            nl = L.Softmax(**kw)
        elif cls == "Embedding":
            nl = L.Embedding(cfg["input_dim"], cfg["output_dim"], **kw)
        elif cls == "LSTM":
            nl = L.LSTM(cfg["units"],
                        return_sequences=cfg["return_sequences"], **kw)
        elif cls == "GRU":
            nl = L.GRU(cfg["units"],
                       return_sequences=cfg["return_sequences"], **kw)
        elif cls == "Reshape":
            nl = L.Reshape(cfg["target_shape"], **kw)
        elif cls == "ZeroPadding2D":
            nl = L.ZeroPadding2D(cfg["padding"], **kw)
        else:
            raise NotImplementedError(
                f"tfpark converter: unsupported layer {cls}; extend "
                "convert_keras_model")
        model.add(nl)
        first = False

    _copy_weights(tf_model, model)
    return model


def _copy_weights(tf_model, native: Sequential) -> None:
    """Copy per-layer weights, translating layout conventions."""
    variables = native.init()
    params = variables["params"]
    state = variables["state"]
    native_layers = [l for l in native.layers]
    tf_layers = [l for l in tf_model.layers
                 if type(l).__name__ != "InputLayer"]
    for tfl, nl in zip(tf_layers, native_layers):
        w = [np.asarray(v) for v in tfl.get_weights()]
        cls = type(tfl).__name__
        tgt = params.get(nl.name, {})
        if cls == "Dense" and w:
            tgt["kernel"] = w[0]
            if len(w) > 1:
                tgt["bias"] = w[1]
        elif cls in ("Conv2D", "Conv1D") and w:
            tgt["kernel"] = w[0]      # HWIO already
            if len(w) > 1:
                tgt["bias"] = w[1]
        elif cls == "BatchNormalization" and w:
            tgt["gamma"], tgt["beta"] = w[0], w[1]
            state[nl.name]["moving_mean"] = w[2]
            state[nl.name]["moving_var"] = w[3]
        elif cls == "LayerNormalization" and w:
            tgt["gamma"], tgt["beta"] = w[0], w[1]
        elif cls == "Embedding" and w:
            tgt["embeddings"] = w[0]
        elif cls in ("LSTM", "GRU") and w:
            tgt["kernel"], tgt["recurrent_kernel"] = w[0], w[1]
            if len(w) > 2:
                b = w[2]
                tgt["bias"] = b.sum(0) if b.ndim == 2 else b
    import jax.numpy as jnp
    conv = lambda t: {k: jnp.asarray(v) for k, v in t.items()} \
        if isinstance(t, dict) else jnp.asarray(t)
    variables["params"] = {k: conv(v) for k, v in params.items()}
    variables["state"] = state
    native.set_variables(variables)
