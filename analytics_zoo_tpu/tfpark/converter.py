"""tf.keras → native-layer conversion.

The reference's TFPark trains tf.keras models by exporting the TF graph
and running sessions on every executor under the BigDL optimizer
(tf_optimizer.py:103 TFModel export, TFTrainingHelper.scala:32).  The
TPU-native answer: convert the *architecture* to framework layers and
copy the weights — the converted model then trains on the MXU under the
zoo engine with zero TF in the hot loop.

Two topologies are supported:

* ``tf.keras.Sequential`` → native ``Sequential`` (layer list).
* Functional ``tf.keras.Model`` → native graph ``Model``: the
  ``get_config()`` layer graph is walked node by node
  (``inbound_nodes`` / ``keras_history`` references), with shared
  layers (one native layer instance per tf layer, applied at every
  call node), multi-input/multi-output models, and arbitrary merge
  topology.  This mirrors what the reference gets for free from graph
  export (tf_optimizer.py:537 from_keras handles any Model).

Covered layer set = what the reference's TFPark examples use (MLPs,
convnets, RNN classifiers, two-tower/multi-input models): InputLayer,
Dense, Conv1D/2D, (Max/Average/Global)Pooling, Flatten, Dropout,
BatchNormalization, Activation, ReLU/LeakyReLU/ELU/Softmax, Embedding,
LSTM, GRU, Reshape, LayerNormalization, ZeroPadding2D, and the merge
family (Add/Subtract/Multiply/Average/Maximum/Minimum/Concatenate/Dot).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from analytics_zoo_tpu.pipeline.api.keras import Model, Sequential
from analytics_zoo_tpu.pipeline.api.keras import layers as L
from analytics_zoo_tpu.pipeline.api.keras.engine import Input


def _act_name(act) -> Optional[str]:
    name = getattr(act, "__name__", str(act))
    return {"linear": None}.get(name, name)


_MERGE_MODES = {
    "Add": "sum",
    "Subtract": "sub",
    "Multiply": "mul",
    "Average": "ave",
    "Maximum": "max",
    "Minimum": "min",
}


def _make_layer(cls: str, cfg: dict, kw: dict,
                input_shape=None):
    """Build the native layer for one tf.keras layer config; returns
    None for InputLayer (handled by the caller).  ``input_shape`` is
    the serialized build shape when known (used where conversion
    depends on input rank, e.g. Dot axes)."""
    if cls == "InputLayer":
        return None
    if cls == "Dense":
        return L.Dense(cfg["units"],
                       activation=_act_name(cfg["activation"]),
                       bias=cfg["use_bias"], **kw)
    if cls == "Conv2D":
        return L.Convolution2D(
            cfg["filters"], *cfg["kernel_size"],
            subsample=tuple(cfg["strides"]),
            border_mode=cfg["padding"],
            activation=_act_name(cfg["activation"]),
            bias=cfg["use_bias"], **kw)
    if cls == "Conv1D":
        return L.Convolution1D(
            cfg["filters"], cfg["kernel_size"][0],
            strides=tuple(cfg["strides"]),
            border_mode=cfg["padding"],
            activation=_act_name(cfg["activation"]),
            bias=cfg["use_bias"], **kw)
    if cls == "MaxPooling2D":
        return L.MaxPooling2D(pool_size=tuple(cfg["pool_size"]),
                              strides=tuple(cfg["strides"]),
                              border_mode=cfg["padding"], **kw)
    if cls == "AveragePooling2D":
        return L.AveragePooling2D(pool_size=tuple(cfg["pool_size"]),
                                  strides=tuple(cfg["strides"]),
                                  border_mode=cfg["padding"], **kw)
    if cls == "GlobalAveragePooling2D":
        return L.GlobalAveragePooling2D(**kw)
    if cls == "GlobalMaxPooling2D":
        return L.GlobalMaxPooling2D(**kw)
    if cls == "GlobalAveragePooling1D":
        return L.GlobalAveragePooling1D(**kw)
    if cls == "GlobalMaxPooling1D":
        return L.GlobalMaxPooling1D(**kw)
    if cls == "Flatten":
        return L.Flatten(**kw)
    if cls == "Dropout":
        return L.Dropout(cfg["rate"], **kw)
    if cls == "BatchNormalization":
        return L.BatchNormalization(epsilon=cfg["epsilon"],
                                    momentum=cfg["momentum"],
                                    axis=cfg.get("axis", -1),
                                    scale=cfg.get("scale", True),
                                    center=cfg.get("center", True), **kw)
    if cls == "LayerNormalization":
        return L.LayerNorm(epsilon=cfg["epsilon"], **kw)
    if cls == "Activation":
        return L.Activation(cfg["activation"], **kw)
    if cls == "ReLU":
        return L.Activation("relu", **kw)
    if cls == "LeakyReLU":
        return L.LeakyReLU(cfg.get("negative_slope",
                                   cfg.get("alpha", 0.3)), **kw)
    if cls == "ELU":
        return L.ELU(cfg.get("alpha", 1.0), **kw)
    if cls == "Softmax":
        return L.Softmax(**kw)
    if cls == "Embedding":
        return L.Embedding(cfg["input_dim"], cfg["output_dim"], **kw)
    if cls == "LSTM":
        return L.LSTM(cfg["units"],
                      return_sequences=cfg["return_sequences"], **kw)
    if cls == "GRU":
        return L.GRU(cfg["units"],
                     return_sequences=cfg["return_sequences"], **kw)
    if cls == "Reshape":
        return L.Reshape(cfg["target_shape"], **kw)
    if cls == "ZeroPadding2D":
        return L.ZeroPadding2D(cfg["padding"], **kw)
    if cls == "Concatenate":
        return L.Merge(mode="concat", concat_axis=cfg.get("axis", -1),
                       **kw)
    if cls in _MERGE_MODES:
        return L.Merge(mode=_MERGE_MODES[cls], **kw)
    if cls == "Dot":
        axes = cfg.get("axes", -1)
        ax_set = {axes} if isinstance(axes, int) else set(axes)
        # last axis may be spelled -1 or rank-1 (rank from the build
        # shape of either input when available)
        last_axes = {-1}
        if input_shape:
            shp = input_shape[0] if isinstance(
                input_shape[0], (list, tuple)) else input_shape
            last_axes.add(len(shp) - 1)
        if not ax_set <= last_axes:
            raise NotImplementedError(
                f"tfpark converter: Dot(axes={axes}) — only last-axis "
                "dot products convert")
        return L.Merge(mode="cosine" if cfg.get("normalize") else "dot",
                       **kw)
    raise NotImplementedError(
        f"tfpark converter: unsupported layer {cls}; extend _make_layer")


def convert_keras_model(tf_model):
    """Convert a tf.keras model (Sequential or functional graph) to a
    native model with identical weights."""
    import tensorflow as tf
    if isinstance(tf_model, tf.keras.Sequential):
        return _convert_sequential(tf_model)
    return _convert_functional(tf_model)


# ------------------------------------------------------------- sequential
def _convert_sequential(tf_model) -> Sequential:
    model = Sequential()
    first = True
    pairs = []

    def input_shape_of(layer):
        shape = layer.get_build_config()["input_shape"]
        return tuple(shape[1:])

    for tfl in tf_model.layers:
        kw = {}
        if first:
            kw["input_shape"] = input_shape_of(tfl)
        try:
            build_shape = tfl.get_build_config()["input_shape"]
        except Exception:
            build_shape = None
        nl = _make_layer(type(tfl).__name__, tfl.get_config(), kw,
                         input_shape=build_shape)
        if nl is None:          # InputLayer
            continue
        model.add(nl)
        pairs.append((tfl, nl))
        first = False

    _copy_weights(pairs, model)
    return model


# ------------------------------------------------------------- functional
def _tensor_refs(obj) -> List[Tuple[str, int, int]]:
    """All keras_history references inside one serialized call-arg."""
    refs = []
    if isinstance(obj, dict):
        if obj.get("class_name") == "__keras_tensor__":
            h = obj["config"]["keras_history"]
            refs.append((h[0], int(h[1]), int(h[2])))
        else:
            for v in obj.values():
                refs.extend(_tensor_refs(v))
    elif isinstance(obj, (list, tuple)):
        # keras-2 style inline ref: [layer_name, node_idx, tensor_idx,
        # kwargs?]
        if (len(obj) >= 3 and isinstance(obj[0], str)
                and isinstance(obj[1], int) and isinstance(obj[2], int)):
            refs.append((obj[0], int(obj[1]), int(obj[2])))
        else:
            for v in obj:
                refs.extend(_tensor_refs(v))
    return refs


def _resolve_arg(obj, tensors):
    """Serialized call-arg → KTensor / list / literal."""
    if isinstance(obj, dict):
        if obj.get("class_name") == "__keras_tensor__":
            h = obj["config"]["keras_history"]
            return tensors[(h[0], int(h[1]), int(h[2]))]
        return {k: _resolve_arg(v, tensors) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        if (len(obj) >= 3 and isinstance(obj[0], str)
                and isinstance(obj[1], int) and isinstance(obj[2], int)):
            return tensors[(obj[0], int(obj[1]), int(obj[2]))]
        return [_resolve_arg(v, tensors) for v in obj]
    return obj


def _node_io(node) -> Tuple[list, dict]:
    """Normalise one serialized inbound node to (args, kwargs) across
    keras-3 ({"args": [...], "kwargs": {...}}) and keras-2 (list of
    inline refs) formats."""
    if isinstance(node, dict):
        return list(node.get("args", [])), dict(node.get("kwargs", {}))
    # keras-2: a node is a list of inline refs; multiple refs mean the
    # layer was called on a list of tensors
    return ([list(node)] if len(node) > 1 else [node[0]]), {}


def _norm_spec(spec) -> List[Tuple[str, int, int]]:
    """input_layers/output_layers entry → list of (name, node, idx):
    keras flattens a single spec to ["name", 0, 0]."""
    if not spec:
        return []
    if isinstance(spec[0], str):
        return [(spec[0], int(spec[1]), int(spec[2]))]
    return [(s[0], int(s[1]), int(s[2])) for s in spec]


def _convert_functional(tf_model) -> Model:
    try:
        cfg = tf_model.get_config()
    except Exception as e:
        raise NotImplementedError(
            "tfpark converter: model has no serializable config "
            "(subclassed tf.keras.Model?) — only Sequential and "
            "functional models convert") from e
    if "layers" not in cfg or "input_layers" not in cfg:
        raise NotImplementedError(
            "tfpark converter: expected a functional-model config with "
            f"layers/input_layers, got keys {sorted(cfg)}")

    tensors: Dict[Tuple[str, int, int], object] = {}
    native_by_name: Dict[str, object] = {}

    work = []
    for lc in cfg["layers"]:
        if lc["class_name"] == "InputLayer":
            c = lc["config"]
            shape = c.get("batch_shape") or c.get("batch_input_shape")
            tensors[(lc["name"], 0, 0)] = Input(shape=tuple(shape[1:]),
                                                name=lc["name"])
        else:
            for node_idx, node in enumerate(lc["inbound_nodes"]):
                work.append((lc, node_idx, node))

    # Fixpoint walk: apply every call node whose input tensors exist.
    # A shared layer's later nodes may consume tensors produced after
    # its first node, so a single topological pass over `layers` is not
    # enough.
    while work:
        remaining = []
        progress = False
        for lc, node_idx, node in work:
            args, kwargs = _node_io(node)
            tensor_kwargs = _tensor_refs(kwargs)
            if tensor_kwargs:
                raise NotImplementedError(
                    f"tfpark converter: layer {lc['name']} receives "
                    "tensors via keyword arguments — unsupported call "
                    "signature")
            refs = _tensor_refs(args)
            if not all(r in tensors for r in refs):
                remaining.append((lc, node_idx, node))
                continue
            nl = native_by_name.get(lc["name"])
            if nl is None:
                nl = _make_layer(
                    lc["class_name"], lc["config"], {"name": lc["name"]},
                    input_shape=lc.get("build_config", {}).get(
                        "input_shape"))
                native_by_name[lc["name"]] = nl
            resolved = [_resolve_arg(a, tensors) for a in args]
            if len(resolved) != 1:
                raise NotImplementedError(
                    f"tfpark converter: layer {lc['name']} called with "
                    f"{len(resolved)} positional args — unsupported "
                    "call signature")
            out = nl(resolved[0])
            outs = out if isinstance(out, (list, tuple)) else [out]
            for t_idx, t in enumerate(outs):
                tensors[(lc["name"], node_idx, t_idx)] = t
            progress = True
        if not progress:
            stuck = sorted({lc["name"] for lc, _, _ in remaining})
            raise ValueError(
                "tfpark converter: could not resolve the layer graph "
                f"(unresolvable nodes for layers {stuck}) — cyclic or "
                "truncated model config")
        work = remaining

    inputs = [tensors[r] for r in _norm_spec(cfg["input_layers"])]
    outputs = [tensors[r] for r in _norm_spec(cfg["output_layers"])]
    model = Model(inputs if len(inputs) > 1 else inputs[0],
                  outputs if len(outputs) > 1 else outputs[0])

    pairs = [(tf_model.get_layer(name), nl)
             for name, nl in native_by_name.items()]
    _copy_weights(pairs, model)
    return model


# ----------------------------------------------------------- weight copy
def _copy_weights(pairs, native) -> None:
    """Copy per-layer weights (tf layer, native layer) pairs into the
    native model, translating layout conventions."""
    variables = native.init()
    params = variables["params"]
    state = variables["state"]
    for tfl, nl in pairs:
        w = [np.asarray(v) for v in tfl.get_weights()]
        cls = type(tfl).__name__
        tgt = params.get(nl.name, {})
        if cls == "Dense" and w:
            tgt["kernel"] = w[0]
            if len(w) > 1:
                tgt["bias"] = w[1]
        elif cls in ("Conv2D", "Conv1D") and w:
            tgt["kernel"] = w[0]      # HWIO already
            if len(w) > 1:
                tgt["bias"] = w[1]
        elif cls == "BatchNormalization" and w:
            # weight order shrinks when scale/center are off
            c = tfl.get_config()
            i = 0
            if c.get("scale", True):
                tgt["gamma"] = w[i]
                i += 1
            if c.get("center", True):
                tgt["beta"] = w[i]
                i += 1
            state[nl.name]["moving_mean"] = w[i]
            state[nl.name]["moving_var"] = w[i + 1]
        elif cls == "LayerNormalization" and w:
            tgt["gamma"], tgt["beta"] = w[0], w[1]
        elif cls == "Embedding" and w:
            tgt["embeddings"] = w[0]
        elif cls in ("LSTM", "GRU") and w:
            tgt["kernel"], tgt["recurrent_kernel"] = w[0], w[1]
            if len(w) > 2:
                b = w[2]
                tgt["bias"] = b.sum(0) if b.ndim == 2 else b
    import jax.numpy as jnp
    conv = lambda t: {k: jnp.asarray(v) for k, v in t.items()} \
        if isinstance(t, dict) else jnp.asarray(t)
    variables["params"] = {k: conv(v) for k, v in params.items()}
    variables["state"] = state
    native.set_variables(variables)
