"""TFPredictor: distributed inference driver.

Reference: pyzoo/zoo/tfpark/tf_predictor.py:30 — wraps (session,
outputs, inputs, TFDataset) and predicts partition-wise through TFNet;
``from_outputs`` / ``from_keras`` factories.

TPU version: holds a native model + dataset; predict() batches through
the device with the shared predict path.
"""

from __future__ import annotations


class TFPredictor:
    def __init__(self, model, dataset):
        self.model = model
        self.dataset = dataset

    @classmethod
    def from_outputs(cls, model, dataset) -> "TFPredictor":
        """(ref from_outputs(sess, outputs): the 'outputs' are whatever
        the model's forward produces here.)"""
        return cls(model, dataset)

    @classmethod
    def from_keras(cls, keras_model, dataset) -> "TFPredictor":
        """(ref from_keras(keras_model, dataset))"""
        from analytics_zoo_tpu.tfpark.model import KerasModel
        if not isinstance(keras_model, KerasModel):
            keras_model = KerasModel(keras_model)
        return cls(keras_model.model, dataset)

    def predict(self, batch_per_thread: int = -1):
        from analytics_zoo_tpu.tfpark.tf_optimizer import (
            _dataset_to_featureset)
        fs, batch = _dataset_to_featureset(self.dataset, training=False)
        if batch_per_thread and batch_per_thread > 0:
            batch = batch_per_thread
        return self.model.predict(fs.x, batch_size=batch)
