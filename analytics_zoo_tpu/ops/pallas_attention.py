"""Pallas flash-attention kernel for TPU.

Single-chip long-context attention: O(T·Tb) VMEM instead of the O(T²)
logits matrix XLA materialises for plain attention.  Pairs with
parallel/ring_attention.py (across-chip SP): ring handles the
inter-chip blocks, this kernel is what each chip should run on its
local block.

Grid: (batch·heads, T/block_q).  K/V for the (batch·head) live in VMEM
(fine for T·D up to ~4k·128 at bf16/f32); the kernel streams q blocks
and runs the online-softmax recurrence over k blocks.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:           # pragma: no cover
    _HAS_PALLAS = False


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int,
                  causal: bool, scale: float, block_q: int):
    t = k_ref.shape[0]
    d = q_ref.shape[-1]
    q = q_ref[:] * scale                       # (block_q, d)
    q_idx = pl.program_id(1)

    n_k = t // block_k

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[pl.ds(i * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_pos = q_idx * block_q + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # future k blocks are fully masked for every query row in this
        # q block — skip them instead of computing masked-out matmuls
        n_k = jnp.minimum(
            n_k, ((q_idx + 1) * block_q + block_k - 1) // block_k)
    acc, m, l = jax.lax.fori_loop(0, n_k, body, (acc, m0, l0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256, interpret: bool = False):
    """q,k,v: (B, H, T, D) -> (B, H, T, D)."""
    b, h, t, d = q.shape
    if scale is None:
        scale = d ** -0.5
    if not _HAS_PALLAS:
        from analytics_zoo_tpu.ops.attention import (
            scaled_dot_product_attention)
        return scaled_dot_product_attention(q, k, v, causal=causal,
                                            scale=scale)
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError(
            f"seq len {t} must divide block sizes ({block_q}, {block_k})")

    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)

    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale,
                               block_q=block_q)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d),
                         lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d)
