"""Pallas flash-attention kernels for TPU — forward AND backward.

Part of the fused kernel suite (ops/fused.py holds the elementwise /
reduction half — fused optimizer update, bias→GeLU, LayerNorm→act —
and the shared ``pallas_supported()`` capability probe that gates all
Pallas routing).  Single-chip long-context attention: O(T·Tb) VMEM
instead of the O(T²) logits matrix XLA materialises for plain
attention.  Pairs with parallel/ring_attention.py (across-chip SP):
ring handles the inter-chip blocks, this kernel is what each chip
should run on its local block.

The public ``flash_attention`` is differentiable: a ``custom_vjp``
routes the backward through two Pallas kernels (the standard
flash-attention backward — recompute the probability blocks from the
forward's saved log-sum-exp, then ``dv = PᵀdO``, ``ds = P∘(dOVᵀ - D)``,
``dq = dsK``, ``dk = dsᵀQ``), so the same memory bound holds in
training.

Grid: (batch·heads, T/block).  K/V (and in the backward Q/dO) for one
(batch·head) live in VMEM — fine for T·D up to ~4k·128 at bf16/f32;
the kernels stream the blocked operand.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:
    from jax.experimental import pallas as pl
    _HAS_PALLAS = True
except Exception:           # pragma: no cover
    _HAS_PALLAS = False


def _apply_causal_mask(s, q_start, k_start, block_q: int,
                       block_k: int):
    """Mask future positions in one (block_q, block_k) logits tile —
    the ONE definition shared by the forward and both backward kernels
    so P is recomputed under the identical mask."""
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, -1e30)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                  causal: bool, scale: float, block_q: int):
    t = k_ref.shape[0]
    d = q_ref.shape[-1]
    q = q_ref[:] * scale                       # (block_q, d)
    q_idx = pl.program_id(1)

    n_k = t // block_k

    def body(i, carry):
        acc, m, l = carry
        k_blk = k_ref[pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[pl.ds(i * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            s = _apply_causal_mask(s, q_idx * block_q, i * block_k,
                                   block_q, block_k)
        m_blk = jnp.max(s, axis=1)
        m_new = jnp.maximum(m, m_blk)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + jnp.sum(p, axis=1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    if causal:
        # future k blocks are fully masked for every query row in this
        # q block — skip them instead of computing masked-out matmuls
        n_k = jnp.minimum(
            n_k, ((q_idx + 1) * block_q + block_k - 1) // block_k)
    acc, m, l = jax.lax.fori_loop(0, n_k, body, (acc, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # TPU blocks must be >=2D: lse is stored (block_q, 1)
    lse_ref[:] = (m + jnp.log(l_safe))[:, None].astype(lse_ref.dtype)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, *, block_k: int, causal: bool,
                     scale: float, block_q: int):
    """dq for one q block: stream k blocks, recompute P from lse."""
    t = k_ref.shape[0]
    d = q_ref.shape[-1]
    # recompute logits EXACTLY as the forward did (same dtype for the
    # q*scale product), so exp(s - lse) reproduces the forward's P —
    # a higher-precision recompute would desynchronise from the saved
    # lse under bf16
    q = q_ref[:] * scale                          # (bq, d), input dtype
    do = do_ref[:].astype(jnp.float32)            # (bq, d)
    lse = lse_ref[:][:, 0]                        # (bq,)
    delta = delta_ref[:][:, 0]                    # (bq,)
    q_idx = pl.program_id(1)
    n_k = t // block_k

    def body(i, dq):
        k_blk = k_ref[pl.ds(i * block_k, block_k), :]
        v_blk = v_ref[pl.ds(i * block_k, block_k), :]
        s = jnp.dot(q, k_blk.T,
                    preferred_element_type=jnp.float32)
        if causal:
            s = _apply_causal_mask(s, q_idx * block_q, i * block_k,
                                   block_q, block_k)
        p = jnp.exp(s - lse[:, None])             # (bq, bk)
        dp = jnp.dot(do, v_blk.T.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        return dq + jnp.dot(ds, k_blk.astype(jnp.float32),
                            preferred_element_type=jnp.float32)

    if causal:
        n_k = jnp.minimum(
            n_k, ((q_idx + 1) * block_q + block_k - 1) // block_k)
    dq = jax.lax.fori_loop(
        0, n_k, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, *, block_k: int, causal: bool,
                      scale: float, block_q: int):
    """dk/dv for one (k block, q block) grid cell.  The grid's
    innermost axis walks q blocks while dk/dv REVISIT the same output
    block — TPU pallas executes the grid sequentially per core, so
    accumulating into the output across the q axis is safe, and only
    ONE q block lives in VMEM at a time (the full-T operand layout
    OOM'd scoped vmem at T=8k)."""
    q_idx = pl.program_id(2)
    k_idx = pl.program_id(1)

    @pl.when(q_idx == 0)
    def _init():
        dk_ref[:] = jnp.zeros_like(dk_ref)
        dv_ref[:] = jnp.zeros_like(dv_ref)

    def _compute():
        k_blk = k_ref[:]                          # (bk, d) input dtype
        v_blk = v_ref[:]                          # (bk, d)
        # same-dtype q*scale as the forward (see dq kernel note)
        q_blk = q_ref[:] * scale                  # (bq, d)
        do_blk = do_ref[:].astype(jnp.float32)    # (bq, d)
        lse = lse_ref[:][:, 0]
        delta = delta_ref[:][:, 0]

        s = jnp.dot(q_blk, k_blk.T,
                    preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            s = _apply_causal_mask(s, q_idx * block_q, k_idx * block_k,
                                   block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dv_upd = jnp.dot(p.T, do_blk, preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v_blk.T.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        # dk = Σ ds_ijᵀ (scale·q_i): q_blk enters pre-scaled, so the
        # scale is already in the accumulation
        dk_upd = jnp.dot(ds.T, q_blk.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        dk_ref[:] += dk_upd.astype(dk_ref.dtype)
        dv_ref[:] += dv_upd.astype(dv_ref.dtype)

    if causal:
        # skip fully-masked cells (q block entirely above the diagonal)
        # — ~half the grid at large T would otherwise burn full matmuls
        # on results that are discarded
        pl.when((q_idx + 1) * block_q - 1 >= k_idx * block_k)(_compute)
    else:
        _compute()


def _resolve_blocks(t: int, block_q: int, block_k: int):
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q != 0 or t % block_k != 0:
        raise ValueError(
            f"seq len {t} must divide block sizes ({block_q}, {block_k})")
    return block_q, block_k


def _flash_fwd_impl(q, k, v, cfg):
    causal, scale, block_q, block_k, interpret = cfg
    b, h, t, d = q.shape
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale,
                               block_q=block_q)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32)),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((None, block_q, d),
                                lambda i, j: (i, j, 0)),
                   pl.BlockSpec((None, block_q, 1),
                                lambda i, j: (i, j, 0))),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, t, d), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash(q, k, v, cfg):
    out, _ = _flash_fwd_impl(q, k, v, cfg)
    return out


def _flash_vjp_fwd(q, k, v, cfg):
    out, lse = _flash_fwd_impl(q, k, v, cfg)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(cfg, res, dout):
    causal, scale, block_q, block_k, interpret = cfg
    q, k, v, out, lse = res
    b, h, t, d = q.shape
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    dof = dout.reshape(b * h, t, d)
    of = out.reshape(b * h, t, d)
    # D_i = rowsum(dO_i ∘ O_i) — cheap elementwise, computed by XLA
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)       # (bh, t, 1)

    dq_kernel = functools.partial(_flash_dq_kernel, block_k=block_k,
                                  causal=causal, scale=scale,
                                  block_q=block_q)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        grid=(b * h, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, t, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d),
                               lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dkv_kernel = functools.partial(_flash_dkv_kernel, block_k=block_k,
                                   causal=causal, scale=scale,
                                   block_q=block_q)
    # grid (bh, k blocks, q blocks): dk/dv output blocks are revisited
    # along the innermost q axis (sequential per core → accumulation is
    # safe); dk/dv must be f32 so the += accumulation doesn't round
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=(jax.ShapeDtypeStruct((b * h, t, d), jnp.float32),
                   jax.ShapeDtypeStruct((b * h, t, d), jnp.float32)),
        grid=(b * h, t // block_k, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d),
                         lambda i, jk, jq: (i, jq, 0)),
            pl.BlockSpec((None, block_k, d),
                         lambda i, jk, jq: (i, jk, 0)),
            pl.BlockSpec((None, block_k, d),
                         lambda i, jk, jq: (i, jk, 0)),
            pl.BlockSpec((None, block_q, d),
                         lambda i, jk, jq: (i, jq, 0)),
            pl.BlockSpec((None, block_q, 1),
                         lambda i, jk, jq: (i, jq, 0)),
            pl.BlockSpec((None, block_q, 1),
                         lambda i, jk, jq: (i, jq, 0)),
        ],
        out_specs=(pl.BlockSpec((None, block_k, d),
                                lambda i, jk, jq: (i, jk, 0)),
                   pl.BlockSpec((None, block_k, d),
                                lambda i, jk, jq: (i, jk, 0))),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)
    dk = dk.astype(k.dtype)
    dv = dv.astype(v.dtype)

    return (dq.reshape(b, h, t, d), dk.reshape(b, h, t, d),
            dv.reshape(b, h, t, d))


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False,
                    scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256, interpret: bool = False):
    """q,k,v: (B, H, T, D) -> (B, H, T, D).  Differentiable (flash
    backward kernels); falls back to dense XLA attention without
    Pallas."""
    b, h, t, d = q.shape
    if scale is None:
        scale = d ** -0.5
    if not _HAS_PALLAS:
        from analytics_zoo_tpu.ops.attention import (
            scaled_dot_product_attention)
        return scaled_dot_product_attention(q, k, v, causal=causal,
                                            scale=scale)
    block_q, block_k = _resolve_blocks(t, block_q, block_k)
    return _flash(q, k, v, (causal, scale, block_q, block_k, interpret))
