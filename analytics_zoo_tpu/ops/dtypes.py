"""Mixed-precision policy.

TPU MXU peak throughput needs bfloat16 inputs; parameters and the
optimizer state stay float32 for stable accumulation.  The reference has
no equivalent (MKL float32 everywhere); this is TPU-native design.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from analytics_zoo_tpu.common.config import get_config

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "int8": jnp.int8,
}


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: object
    compute_dtype: object

    def cast_compute(self, x):
        if x.dtype in (jnp.float32, jnp.bfloat16, jnp.float16):
            return x.astype(self.compute_dtype)
        return x


_policy = None


def get_policy() -> Policy:
    global _policy
    if _policy is None:
        cfg = get_config()
        _policy = Policy(
            param_dtype=_DTYPES[str(cfg.get("dtype.param"))],
            compute_dtype=_DTYPES[str(cfg.get("dtype.compute"))],
        )
    return _policy


def set_policy(param_dtype: str = "float32",
               compute_dtype: str = "bfloat16") -> Policy:
    global _policy
    _policy = Policy(param_dtype=_DTYPES[param_dtype],
                     compute_dtype=_DTYPES[compute_dtype])
    return _policy


def restore_policy(policy: Policy) -> None:
    """Put back a Policy captured earlier via get_policy() (scoped
    overrides, e.g. golden tests forcing f32)."""
    global _policy
    _policy = policy
