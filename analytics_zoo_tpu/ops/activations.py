"""Activation functions addressable by Keras-1 string names.

Mirrors the activation set of the reference's keras layer API
(zoo/pipeline/api/keras/layers/ activation handling via
KerasUtils.getActivation / Activation layer).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


def linear(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.minimum(jax.nn.relu(x), 6.0)


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hard_sigmoid(x):
    # Keras-1 definition: clip(0.2 * x + 0.5, 0, 1)
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def hard_sigmoid_torch(x):
    # torch nn.Hardsigmoid: relu6(x + 3) / 6 — DIFFERENT slope from
    # the Keras-1 hard_sigmoid above; MobileNetV3 lineage uses this
    return jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def hard_swish(x):
    # torch nn.Hardswish: x * relu6(x + 3) / 6 (MobileNetV3)
    return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def log_softmax(x):
    return jax.nn.log_softmax(x, axis=-1)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    return jax.nn.gelu(x, approximate=True)


def gelu_erf(x):
    """Exact (erf-based) GELU — the variant published BERT checkpoints
    were trained with (google-research bert modeling.py gelu)."""
    return jax.nn.gelu(x, approximate=False)


def swish(x):
    return jax.nn.silu(x)


def exp(x):
    return jnp.exp(x)


_REGISTRY = {
    "linear": linear, None: linear,
    "relu": relu,
    "relu6": relu6,
    "tanh": tanh,
    "sigmoid": sigmoid,
    "hard_sigmoid": hard_sigmoid,
    "hard_sigmoid_torch": hard_sigmoid_torch,
    "hard_swish": hard_swish,
    "hardswish": hard_swish,
    "softmax": softmax,
    "log_softmax": log_softmax,
    "softplus": softplus,
    "softsign": softsign,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "gelu_erf": gelu_erf,
    "swish": swish,
    "silu": swish,
    "exp": exp,
}


def get(activation) -> Optional[Callable]:
    """Resolve a name/callable; returns None for identity (no-op)."""
    if activation is None:
        return None
    if callable(activation):
        return activation
    name = str(activation).lower()
    if name == "linear":
        return None
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown activation: {activation!r}") from None
