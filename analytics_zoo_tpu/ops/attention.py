"""Attention primitives.

``scaled_dot_product_attention`` is the single-device reference path —
one fused XLA program (two MXU matmuls + softmax).  The ring-parallel
long-context variant lives in ``parallel/ring_attention.py``.

No reference counterpart: the reference's BERT computes full-sequence
attention on one CPU node (keras/layers/BERT.scala:66); long-context
sharding is a new TPU-native capability (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def scaled_dot_product_attention(q, k, v, mask=None, causal: bool = False,
                                 scale: Optional[float] = None):
    """q,k,v: (B, H, T, D). mask: broadcastable to (B, H, Tq, Tk), 1=keep.

    Softmax statistics are computed in f32 even for bf16 inputs.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        idx_q = jnp.arange(tq)[:, None]
        idx_k = jnp.arange(tk)[None, :]
        logits = jnp.where(idx_q >= idx_k, logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def blockwise_attention_step(q, k_blk, v_blk, acc, m, l, scale,
                             logits_bias=None):
    """One online-softmax accumulation step (the flash/ring inner loop).

    q: (B,H,Tq,D); k_blk/v_blk: (B,H,Tb,D);
    acc: (B,H,Tq,D) f32; m,l: (B,H,Tq) f32 running max / normalizer.
    Returns updated (acc, m, l).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk).astype(jnp.float32) * scale
    if logits_bias is not None:
        s = s + logits_bias
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # rescale previous accumulation
    correction = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v_blk.dtype), v_blk).astype(jnp.float32)
    return acc_new, m_new, l_new
