"""Weight initializers, addressable by Keras-1 string names.

The reference exposes init via strings on every layer ("glorot_uniform",
"one", "zero", ... — e.g. Dense init arg, keras/layers/Core.scala) and
BigDL InitializationMethod underneath.
"""

from __future__ import annotations

import math
from typing import Callable, Sequence

import jax
import jax.numpy as jnp


def _fans(shape: Sequence[int]):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels (spatial..., in, out)
    receptive = math.prod(shape[:-2])
    return shape[-2] * receptive, shape[-1] * receptive


def zero(rng, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def one(rng, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def uniform(rng, shape, dtype=jnp.float32, scale=0.05):
    return jax.random.uniform(rng, shape, dtype, -scale, scale)


def normal(rng, shape, dtype=jnp.float32, stddev=0.05):
    return stddev * jax.random.normal(rng, shape, dtype)


def glorot_uniform(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def glorot_normal(rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    stddev = math.sqrt(2.0 / (fan_in + fan_out))
    return stddev * jax.random.normal(rng, shape, dtype)


def he_normal(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    return math.sqrt(2.0 / fan_in) * jax.random.normal(rng, shape, dtype)


def he_uniform(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = math.sqrt(6.0 / fan_in)
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def lecun_uniform(rng, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = math.sqrt(3.0 / fan_in)
    return jax.random.uniform(rng, shape, dtype, -limit, limit)


def orthogonal(rng, shape, dtype=jnp.float32, gain=1.0):
    if len(shape) < 2:
        return normal(rng, shape, dtype)
    rows = math.prod(shape[:-1])
    cols = shape[-1]
    flat = jax.random.normal(rng, (max(rows, cols), min(rows, cols)))
    q, r = jnp.linalg.qr(flat)
    q = q * jnp.sign(jnp.diagonal(r))
    if rows < cols:
        q = q.T
    return (gain * q[:rows, :cols]).reshape(shape).astype(dtype)


_REGISTRY: dict = {
    "zero": zero, "zeros": zero,
    "one": one, "ones": one,
    "uniform": uniform,
    "normal": normal, "gaussian": normal,
    "glorot_uniform": glorot_uniform, "xavier": glorot_uniform,
    "glorot_normal": glorot_normal,
    "he_normal": he_normal, "msra": he_normal,
    "he_uniform": he_uniform,
    "lecun_uniform": lecun_uniform,
    "orthogonal": orthogonal,
}


def get(init) -> Callable:
    """Resolve a string name or callable to an initializer function."""
    if callable(init):
        return init
    try:
        return _REGISTRY[str(init)]
    except KeyError:
        raise ValueError(f"unknown initializer: {init!r}") from None
