from analytics_zoo_tpu.ops import initializers, activations
from analytics_zoo_tpu.ops.dtypes import Policy, get_policy, set_policy
