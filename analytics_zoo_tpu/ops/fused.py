"""Fused Pallas kernel suite — single-HBM-pass hot-path kernels.

Three kernel families, each with a lax fallback behind ONE capability
probe (the ``_int8_conv_supported`` pattern from ``ops/quant.py``):

* **Fused optimizer update** (``build_fused_update``): global-norm
  grad clip + SGD/Adam moment update + parameter apply in ONE pass over
  each leaf.  The optax path the trainer used
  (``optax.global_norm`` → ``tx.update`` → ``optax.apply_updates``)
  materialises a clipped-grads tree, an updates tree, and a new params
  tree — three full HBM sweeps of params+grads per step.  The fused
  path reads each (param, grad, moment) triple once and writes the new
  (param, moment) in place (``input_output_aliases`` on the Pallas
  path; XLA elementwise fusion on the lax path — either way, no
  intermediate trees).  The math REPRODUCES optax op-for-op (same
  order, same dtypes, same bias-correction formulas), so the fused
  step is numerically the optax step — proven by
  ``tests/test_fused_kernels.py`` to the documented tolerance.

* **Epilogue kernels** (``bias_gelu``, ``layernorm_act``): the
  bias-add→GeLU and LayerNorm→activation tails of the dense/attention
  stacks, computed without a round trip of the intermediate activation
  through HBM.

* The flash-attention kernels live in ``ops/pallas_attention.py`` and
  the cross-chip ring schedule in ``parallel/ring_attention.py`` — this
  module is the single-chip elementwise/reduction half of the suite.

Mode selection (``ops.fused`` config key):

* ``auto`` (default) — Pallas kernels when the backend compiles them
  (TPU; decided by one eager probe), lax otherwise.
* ``lax``  — always the lax form (same math, XLA fusion does the work).
* ``off``  — disable the suite; call sites fall back to their
  pre-suite code paths (the trainer runs the optax triple pass).

Every call site sits INSIDE an ``engine_jit`` program (train step,
predict step, bench workloads), so the suite inherits the AOT compile
cache: serving replicas and repeat bench runs load the fused kernels
warm (docs/aot-compile.md).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PALLAS = True
except Exception:           # pragma: no cover
    _HAS_PALLAS = False


# ------------------------------------------------------------------ mode
def _mode() -> str:
    from analytics_zoo_tpu.common.config import get_config
    m = str(get_config().get("ops.fused", "auto") or "auto").lower()
    return m if m in ("auto", "pallas", "lax", "off") else "auto"


def fused_enabled() -> bool:
    """Whether the fused call sites should fire at all."""
    return _mode() != "off"


_PALLAS_OK: Optional[bool] = None


def pallas_supported() -> bool:
    """Probe ONCE, eagerly, whether the backend compiles a
    REPRESENTATIVE suite kernel — SMEM scalar operand + grid +
    ``input_output_aliases``, the exact features the optimizer kernels
    use — outside any trace (backend rejection surfaces at compile
    time; a try/except around a traced call would miss it), mirroring
    ``quant._int8_conv_supported``.  The suite's kernels are
    TPU-Pallas (pltpu memory spaces, TPU tiling), so any other
    backend answers False even where a generic Pallas kernel would
    compile (e.g. the GPU Triton lowering)."""
    global _PALLAS_OK
    if not _HAS_PALLAS:
        return False
    if _PALLAS_OK is None:
        if jax.default_backend() != "tpu":
            _PALLAS_OK = False
            return _PALLAS_OK
        try:
            def k(s_ref, x_ref, o_ref):
                o_ref[:] = x_ref[:] * s_ref[0]

            # ensure_compile_time_eval: the first call may come from a
            # layer/trainer body already under jit tracing — without
            # escaping the trace, the probe jit would be INLINED into
            # the outer program and its backend rejection deferred past
            # the except (observed: probe "succeeds" on CPU, outer
            # lowering then fails)
            with jax.ensure_compile_time_eval():
                x = jnp.zeros((16, 128), jnp.float32)
                s = jnp.ones((4,), jnp.float32)
                blk = pl.BlockSpec((8, 128), lambda i: (i, 0))
                # one-shot backend capability probe, not an engine
                # program: caching its throwaway executable would
                # pollute the store
                # zoolint: disable=COMPILE011 — capability probe, not an engine program
                out = jax.jit(lambda s, a: pl.pallas_call(
                    k,
                    out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
                    grid=(2,),
                    in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                              blk],
                    out_specs=blk,
                    input_output_aliases={1: 0})(s, a))(s, x)
                jax.block_until_ready(out)
            _PALLAS_OK = True
        except Exception:
            _PALLAS_OK = False
    return _PALLAS_OK


def _use_pallas() -> bool:
    m = _mode()
    if m == "lax" or m == "off":
        return False
    if m == "pallas":
        # expert override: trust the caller (e.g. inside a shard_map
        # body, where the per-shard program is single-device again)
        return _HAS_PALLAS
    # auto: pallas_call is not GSPMD-partitionable (the same
    # constraint that keeps flash attention off sharded meshes) — only
    # route to Pallas on a single-device topology; multi-device
    # programs get the lax forms, which XLA fuses and partitions.
    try:
        if len(jax.devices()) != 1:
            return False
    except Exception:
        return False
    return pallas_supported()


def _count_build(kernel: str, path: str) -> None:
    """Trace-time accounting: which kernels were built into the live
    programs, on which path (pallas|lax) — obs_report's kernel-suite
    row reads these."""
    try:
        from analytics_zoo_tpu.observability import get_registry
        get_registry().counter(
            "fused_kernel_builds_total",
            "fused-suite kernels built into traced programs",
            labels=("kernel", "path")).labels(kernel, path).inc()
    except Exception:
        pass


def _leaf_rows(a, min_size: int = 1024) -> Optional[int]:
    """(rows, 128) layout for a Pallas-eligible leaf; None = use lax.
    Eligible: f32, size a multiple of 8*128 (the f32 min tile) and at
    least ``min_size`` elements — below that the kernel-launch overhead
    buys nothing over XLA's own elementwise fusion."""
    n = int(np.prod(a.shape)) if a.shape else 0
    if a.dtype != jnp.float32 or n < min_size or n % (8 * 128):
        return None
    return n // 128


def _row_block(rows: int) -> int:
    for br in (1024, 512, 256, 128, 64, 32, 16, 8):
        if rows % br == 0:
            return br
    return rows


# ===================================================== optimizer kernels
def _adam_kernel(scal_ref, p_ref, g_ref, m_ref, v_ref,
                 po_ref, mo_ref, vo_ref, *, b1: float, b2: float,
                 eps: float, weight_decay: float, clip_lo, clip_hi,
                 use_clip_scale: bool):
    """One fused pass: clip → (wd) → moments → bias-correct → apply.
    scal = [clip_scale, step_size, bias_corr1, bias_corr2] (SMEM)."""
    g = g_ref[:]
    if use_clip_scale:
        g = g * scal_ref[0]
    if clip_lo is not None:
        g = jnp.clip(g, clip_lo, clip_hi)
    if weight_decay:
        g = g + weight_decay * p_ref[:]
    m = (1.0 - b1) * g + b1 * m_ref[:]
    v = (1.0 - b2) * (g ** 2) + b2 * v_ref[:]
    mo_ref[:] = m
    vo_ref[:] = v
    mh = m / scal_ref[2]
    vh = v / scal_ref[3]
    po_ref[:] = p_ref[:] + scal_ref[1] * (mh / (jnp.sqrt(vh) + eps))


def _sgd_kernel(scal_ref, p_ref, g_ref, t_ref, po_ref, to_ref, *,
                momentum: float, nesterov: bool, weight_decay: float,
                clip_lo, clip_hi, use_clip_scale: bool):
    g = g_ref[:]
    if use_clip_scale:
        g = g * scal_ref[0]
    if clip_lo is not None:
        g = jnp.clip(g, clip_lo, clip_hi)
    if weight_decay:
        g = g + weight_decay * p_ref[:]
    tr = g + momentum * t_ref[:]
    to_ref[:] = tr
    u = g + momentum * tr if nesterov else tr
    po_ref[:] = p_ref[:] + scal_ref[1] * u


def _pallas_moment_call(kernel, scal, arrays, n_out: int,
                        interpret: bool):
    """Dispatch a per-leaf optimizer kernel over the (rows, 128)
    re-layout, params/moments aliased in place."""
    rows = _leaf_rows(arrays[0])
    shaped = [a.reshape(rows, 128) for a in arrays]
    br = _row_block(rows)
    grid = (rows // br,)
    blk = pl.BlockSpec((br, 128), lambda i: (i, 0))
    # inputs: scal, p, g, (moments...); outputs alias p + moments —
    # the in-place single sweep (g is the only non-aliased read)
    aliases = {1: 0}
    for j in range(n_out - 1):
        aliases[3 + j] = 1 + j
    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(jax.ShapeDtypeStruct((rows, 128), jnp.float32)
                        for _ in range(n_out)),
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [blk] * len(shaped),
        out_specs=tuple(blk for _ in range(n_out)),
        input_output_aliases=aliases,
        interpret=interpret,
    )(scal, *shaped)
    shape = arrays[0].shape
    return tuple(o.reshape(shape) for o in outs)


def adam_leaf_update(p, g, mu, nu, *, b1: float, b2: float, eps: float,
                     step_size, bias_corr1, bias_corr2,
                     clip_scale=None, weight_decay: float = 0.0,
                     clip_const: Optional[Tuple[float, float]] = None,
                     step_is_schedule: bool = False,
                     interpret: bool = False):
    """One-leaf fused Adam step.  Reproduces
    ``scale_by_adam → scale_by_learning_rate → apply_updates``
    op-for-op; ``bias_corr* = 1 - beta**count_inc`` and ``step_size``
    (the NEGATIVE learning rate) are computed once by the caller.
    Returns ``(new_p, new_mu, new_nu)``."""
    lo, hi = clip_const if clip_const else (None, None)
    if ((interpret or _use_pallas()) and _leaf_rows(p) is not None
            and g.dtype == jnp.float32 and mu.dtype == jnp.float32):
        _count_build("fused_adam", "pallas")
        scal = jnp.stack([
            jnp.asarray(clip_scale if clip_scale is not None else 1.0,
                        jnp.float32),
            jnp.asarray(step_size, jnp.float32),
            jnp.asarray(bias_corr1, jnp.float32),
            jnp.asarray(bias_corr2, jnp.float32)])
        kern = functools.partial(
            _adam_kernel, b1=b1, b2=b2, eps=eps,
            weight_decay=weight_decay, clip_lo=lo, clip_hi=hi,
            use_clip_scale=clip_scale is not None)
        return _pallas_moment_call(kern, scal, [p, g, mu, nu], 3,
                                   interpret)
    _count_build("fused_adam", "lax")
    if clip_scale is not None:
        g = g * clip_scale
    if lo is not None:
        g = jnp.clip(g, lo, hi)
    if weight_decay:
        g = g + weight_decay * p
    # optax.tree_update_moment order: (1-decay)*(g**order) + decay*t
    mu_n = (1.0 - b1) * g + b1 * mu
    nu_n = (1.0 - b2) * (g ** 2) + b2 * nu
    mh = mu_n / jnp.asarray(bias_corr1, mu_n.dtype)
    vh = nu_n / jnp.asarray(bias_corr2, nu_n.dtype)
    u = mh / (jnp.sqrt(vh) + eps)
    u = (jnp.array(step_size, dtype=u.dtype) * u if step_is_schedule
         else step_size * u)
    return ((p + u).astype(p.dtype), mu_n, nu_n)


def sgd_leaf_update(p, g, trace, *, momentum: float, nesterov: bool,
                    step_size, clip_scale=None,
                    weight_decay: float = 0.0,
                    clip_const: Optional[Tuple[float, float]] = None,
                    step_is_schedule: bool = False,
                    interpret: bool = False):
    """One-leaf fused SGD(+momentum) step mirroring
    ``trace → scale`` + ``apply_updates``.  ``trace`` may be None
    (momentum 0).  Returns ``(new_p, new_trace_or_None)``."""
    lo, hi = clip_const if clip_const else (None, None)
    if (trace is not None and (interpret or _use_pallas())
            and _leaf_rows(p) is not None
            and g.dtype == jnp.float32):
        _count_build("fused_sgd", "pallas")
        scal = jnp.stack([
            jnp.asarray(clip_scale if clip_scale is not None else 1.0,
                        jnp.float32),
            jnp.asarray(step_size, jnp.float32),
            jnp.float32(0.0), jnp.float32(0.0)])
        kern = functools.partial(
            _sgd_kernel, momentum=momentum, nesterov=nesterov,
            weight_decay=weight_decay, clip_lo=lo, clip_hi=hi,
            use_clip_scale=clip_scale is not None)
        p_n, t_n = _pallas_moment_call(kern, scal, [p, g, trace], 2,
                                       interpret)
        return p_n, t_n
    _count_build("fused_sgd", "lax")
    if clip_scale is not None:
        g = g * clip_scale
    if lo is not None:
        g = jnp.clip(g, lo, hi)
    if weight_decay:
        g = g + weight_decay * p
    if trace is not None:
        tr = g + momentum * trace           # optax.trace: f(g, t)
        u = g + momentum * tr if nesterov else tr
    else:
        tr, u = None, g
    u = (jnp.array(step_size, dtype=u.dtype) * u if step_is_schedule
         else step_size * u)
    return (p + u).astype(p.dtype), tr


# ------------------------------------------------- optax state plumbing
def _optax_states():
    import optax
    return (optax.TraceState, optax.ScaleByAdamState,
            optax.ScaleByScheduleState)


def _map_states(node, fn):
    """Rebuild an optax state pytree, passing each known state object
    through ``fn`` WHOLE (no recursion into its trees)."""
    if isinstance(node, _optax_states()):
        return fn(node)
    if isinstance(node, tuple):
        if hasattr(node, "_fields"):
            return type(node)(*(_map_states(c, fn) for c in node))
        return tuple(_map_states(c, fn) for c in node)
    if isinstance(node, list):
        return [_map_states(c, fn) for c in node]
    if isinstance(node, dict):
        return {k: _map_states(v, fn) for k, v in node.items()}
    return node


def _collect_states(node, out):
    _map_states(node, lambda s: (out.append(s), s)[1])
    return out


def _safe_inc(count):
    # optax numerics.safe_int32_increment
    return jnp.where(count < jnp.iinfo(jnp.int32).max, count + 1, count)


def build_fused_update(optim, clip=None) -> Optional[Callable]:
    """Return ``update(grads, opt_state, params) -> (new_params,
    new_opt_state)`` fusing clip+moments+apply into one pass per leaf,
    or None when the (optimizer, clip) combination isn't supported —
    the trainer then keeps the optax triple pass.

    Supported: the repo's ``SGD`` (momentum/nesterov/weight_decay,
    float or schedule lr, dampening 0) and ``Adam`` (float or schedule
    lr incl. the Keras ``decay`` form) from
    ``pipeline/api/keras/optimizers.py``; ``clip`` is a trainer
    ``ClipSpec`` (const or l2norm) or None.  The optax state pytree
    structure is preserved exactly (checkpoints, shardings and
    ``init_opt_state`` are unaffected)."""
    import optax
    if optim is None or not fused_enabled():
        return None
    kind = type(optim).__name__
    kw = getattr(optim, "_init_kwargs", None)
    if kind not in ("SGD", "Adam") or kw is None:
        return None
    if kind == "SGD" and kw.get("dampening"):
        return None
    if clip is not None and clip.kind not in ("const", "l2norm"):
        return None
    lr = optim.learning_rate
    has_sched = callable(lr)

    # validate the state layout ONCE on a tiny dummy tree: anything
    # beyond {Trace|ScaleByAdam} + optional ScaleBySchedule + empties
    # means a transformation we don't reproduce — decline.
    probe = _collect_states(optim.tx.init({"w": np.zeros(8, np.float32)}),
                            [])
    traces = [s for s in probe if isinstance(s, optax.TraceState)]
    adams = [s for s in probe if isinstance(s, optax.ScaleByAdamState)]
    scheds = [s for s in probe
              if isinstance(s, optax.ScaleByScheduleState)]
    if kind == "Adam" and (len(adams) != 1 or traces):
        return None
    if kind == "SGD" and (adams or len(traces) > 1):
        return None
    if len(scheds) > (1 if has_sched else 0):
        return None
    has_trace = bool(traces)

    weight_decay = float(kw.get("weight_decay") or 0.0) \
        if kind == "SGD" else 0.0
    momentum = float(kw.get("momentum") or 0.0) if kind == "SGD" else 0.0
    nesterov = bool(kw.get("nesterov")) if kind == "SGD" else False
    b1 = float(kw.get("beta_1", 0.9)) if kind == "Adam" else 0.0
    b2 = float(kw.get("beta_2", 0.999)) if kind == "Adam" else 0.0
    eps = float(kw.get("epsilon", 1e-8)) if kind == "Adam" else 0.0
    clip_const = (float(clip.a), float(clip.b)) \
        if (clip is not None and clip.kind == "const") else None

    def update(grads, opt_state, params):
        # one read sweep for the global norm — the only pre-pass left
        clip_scale = None
        if clip is not None and clip.kind == "l2norm":
            gnorm = optax.global_norm(grads)
            clip_scale = jnp.minimum(1.0, clip.a / (gnorm + 1e-12))

        states = _collect_states(opt_state, [])
        sched_state = next((s for s in states if isinstance(
            s, optax.ScaleByScheduleState)), None)
        if has_sched:
            if sched_state is None:
                raise ValueError("schedule lr without schedule state")
            # scale_by_schedule: step_size = fn(count) PRE-increment
            step_size = -1 * lr(sched_state.count)
        else:
            step_size = -1 * float(lr)

        if kind == "Adam":
            st = next(s for s in states
                      if isinstance(s, optax.ScaleByAdamState))
            count_inc = _safe_inc(st.count)
            bc1 = 1 - b1 ** count_inc
            bc2 = 1 - b2 ** count_inc

            flat_p, treedef = jax.tree_util.tree_flatten(params)
            flat_g = treedef.flatten_up_to(grads)
            flat_m = treedef.flatten_up_to(st.mu)
            flat_v = treedef.flatten_up_to(st.nu)
            out = [adam_leaf_update(
                p, g, m, v, b1=b1, b2=b2, eps=eps,
                step_size=step_size, bias_corr1=bc1, bias_corr2=bc2,
                clip_scale=clip_scale, clip_const=clip_const,
                step_is_schedule=has_sched)
                for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
            new_params = jax.tree_util.tree_unflatten(
                treedef, [o[0] for o in out])
            new_mu = jax.tree_util.tree_unflatten(
                treedef, [o[1] for o in out])
            new_nu = jax.tree_util.tree_unflatten(
                treedef, [o[2] for o in out])

            def rebuild(s):
                if isinstance(s, optax.ScaleByAdamState):
                    return optax.ScaleByAdamState(
                        count=count_inc, mu=new_mu, nu=new_nu)
                if isinstance(s, optax.ScaleByScheduleState):
                    return optax.ScaleByScheduleState(
                        count=_safe_inc(s.count))
                return s
            return new_params, _map_states(opt_state, rebuild)

        # SGD
        trace_state = next(
            (s for s in states if isinstance(s, optax.TraceState)),
            None) if has_trace else None
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_t = (treedef.flatten_up_to(trace_state.trace)
                  if trace_state is not None
                  else [None] * len(flat_p))
        out = [sgd_leaf_update(
            p, g, t, momentum=momentum, nesterov=nesterov,
            step_size=step_size, clip_scale=clip_scale,
            weight_decay=weight_decay, clip_const=clip_const,
            step_is_schedule=has_sched)
            for p, g, t in zip(flat_p, flat_g, flat_t)]
        new_params = jax.tree_util.tree_unflatten(
            treedef, [o[0] for o in out])
        new_trace = (jax.tree_util.tree_unflatten(
            treedef, [o[1] for o in out])
            if trace_state is not None else None)

        def rebuild(s):
            if isinstance(s, optax.TraceState):
                return optax.TraceState(trace=new_trace)
            if isinstance(s, optax.ScaleByScheduleState):
                return optax.ScaleByScheduleState(
                    count=_safe_inc(s.count))
            return s
        return new_params, _map_states(opt_state, rebuild)

    return update


# ====================================================== epilogue kernels
def _epilogue_rows(x, d: int) -> Optional[int]:
    """(rows, d) layout for an epilogue-eligible activation; None = lax.
    The last dim must be a 128-lane multiple and the collapsed leading
    dims an 8-sublane multiple (f32 tile)."""
    if x.dtype not in (jnp.float32,) or x.ndim < 2 or d % 128:
        return None
    rows = int(np.prod(x.shape[:-1]))
    if rows % 8:
        return None
    return rows


def _bias_gelu_kernel(x_ref, b_ref, o_ref, *, approximate: bool):
    o_ref[:] = jax.nn.gelu(x_ref[:] + b_ref[:],
                           approximate=approximate)


def bias_gelu(x, bias, approximate: bool = True,
              interpret: bool = False):
    """Fused bias-add→GeLU epilogue (the dense/FFN tail).  Lax path is
    literally ``gelu(x + bias)`` — identical numerics to the unfused
    call sites it replaces."""
    d = x.shape[-1]
    rows = _epilogue_rows(x, d)
    if (interpret or _use_pallas()) and rows is not None \
            and bias.shape == (d,) and bias.dtype == x.dtype:
        _count_build("bias_gelu", "pallas")
        xr = x.reshape(rows, d)
        br = _row_block(rows)
        out = pl.pallas_call(
            functools.partial(_bias_gelu_kernel,
                              approximate=approximate),
            out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
            grid=(rows // br,),
            in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0)),
                      pl.BlockSpec((1, d), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
            interpret=interpret,
        )(xr, bias.reshape(1, d))
        return out.reshape(x.shape)
    _count_build("bias_gelu", "lax")
    return jax.nn.gelu(x + bias, approximate=approximate)


def _layernorm_act_kernel(x_ref, g_ref, b_ref, o_ref, *, eps: float,
                          activation):
    x = x_ref[:]
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    y = y * g_ref[:] + b_ref[:]
    if activation is not None:
        y = activation(y)
    o_ref[:] = y.astype(o_ref.dtype)


def layernorm_act(x, gamma, beta, eps: float = 1e-5,
                  activation: Optional[Callable] = None,
                  interpret: bool = False):
    """Fused LayerNorm→activation.  Lax path mirrors
    ``layers.normalization.LayerNorm.call`` exactly (biased variance,
    same op order) followed by the activation."""
    d = x.shape[-1]
    rows = _epilogue_rows(x, d)
    if (interpret or _use_pallas()) and rows is not None \
            and gamma.shape == (d,) and gamma.dtype == x.dtype:
        _count_build("layernorm_act", "pallas")
        xr = x.reshape(rows, d)
        br = _row_block(rows)
        out = pl.pallas_call(
            functools.partial(_layernorm_act_kernel, eps=eps,
                              activation=activation),
            out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
            grid=(rows // br,),
            in_specs=[pl.BlockSpec((br, d), lambda i: (i, 0)),
                      pl.BlockSpec((1, d), lambda i: (0, 0)),
                      pl.BlockSpec((1, d), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((br, d), lambda i: (i, 0)),
            interpret=interpret,
        )(xr, gamma.reshape(1, d), beta.reshape(1, d))
        return out.reshape(x.shape)
    _count_build("layernorm_act", "lax")
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mean) / jnp.sqrt(var + eps)
    y = (y * gamma + beta).astype(x.dtype)
    if activation is not None:
        y = activation(y)
    return y
