"""Calibrated int8 kernels.

Reference: InferenceModel.scala:400-421 — TF models are calibrated and
converted to int8 OpenVINO IR (activation ranges recorded over a
calibration set, then int8 execution).

TPU-native version: symmetric per-tensor ACTIVATION scales (recorded by
a calibration pass) + per-output-channel WEIGHT scales; matmul/conv run
int8 x int8 -> int32 on the MXU (v5e int8 peak is 2x bf16) and rescale
to f32 in the epilogue.  The quantized path is params-driven: a layer
whose params carry ``kernel_scale``/``act_scale`` (with an int8
``kernel``) executes quantized — no layer-class mutation, the same
model object serves f32 and int8.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def quantize_activation(x, act_scale):
    """Symmetric int8 quantization with a calibrated scale."""
    return jnp.clip(jnp.round(x.astype(jnp.float32) / act_scale),
                    -127, 127).astype(jnp.int8)


def quantized_matmul(x, kernel_q, kernel_scale, act_scale):
    """int8 x int8 -> int32 contraction over the last/first dims, f32
    rescale epilogue.  ``kernel_scale`` has keepdims shape
    (1, ..., out)."""
    xq = quantize_activation(x, act_scale)
    acc = jax.lax.dot_general(
        xq, kernel_q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    scale = act_scale * kernel_scale.reshape(
        (1,) * (acc.ndim - 1) + (-1,))
    return acc.astype(jnp.float32) * scale


_INT8_CONV_OK = None


def _int8_conv_supported() -> bool:
    """Probe ONCE, eagerly, whether the backend compiles s8xs8->s32
    convolution.  The probe must happen outside any jit trace: a
    try/except around the traced call would only guard abstract
    evaluation — backend rejection surfaces at compile time, outside
    the except."""
    global _INT8_CONV_OK
    if _INT8_CONV_OK is None:
        try:
            x = jnp.zeros((1, 4, 4, 1), jnp.int8)
            k = jnp.zeros((2, 2, 1, 1), jnp.int8)
            # one-shot backend capability probe, not an engine program:
            # caching its throwaway executable would pollute the store
            # zoolint: disable=COMPILE011 — capability probe, not an engine program
            out = jax.jit(lambda a, b: jax.lax.conv_general_dilated(
                a, b, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.int32))(x, k)
            jax.block_until_ready(out)
            _INT8_CONV_OK = True
        except Exception:
            _INT8_CONV_OK = False
    return _INT8_CONV_OK


def quantized_conv(x, kernel_q, kernel_scale, act_scale, *, strides,
                   padding, rhs_dilation, dimension_numbers,
                   feature_group_count=1):
    """int8 conv -> int32 accumulation, f32 rescale epilogue.  Uses the
    dequantized-f32 form (same rounding, same numbers) when the backend
    cannot compile integer convolution — decided by an eager probe, not
    in-trace."""
    xq = quantize_activation(x, act_scale)
    if _int8_conv_supported():
        acc = jax.lax.conv_general_dilated(
            xq, kernel_q, window_strides=strides, padding=padding,
            rhs_dilation=rhs_dilation,
            dimension_numbers=dimension_numbers,
            feature_group_count=feature_group_count,
            preferred_element_type=jnp.int32)
        scale = act_scale * kernel_scale.reshape(
            (1,) * (acc.ndim - 1) + (-1,))
        return acc.astype(jnp.float32) * scale
    # fake-quant fallback: numerically identical rounding, f32 math
    xdq = xq.astype(jnp.float32) * act_scale
    kdq = kernel_q.astype(jnp.float32) * kernel_scale
    return jax.lax.conv_general_dilated(
        xdq, kdq, window_strides=strides, padding=padding,
        rhs_dilation=rhs_dilation,
        dimension_numbers=dimension_numbers,
        feature_group_count=feature_group_count)
