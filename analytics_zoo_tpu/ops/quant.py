"""Calibrated int8 kernels.

Reference: InferenceModel.scala:400-421 — TF models are calibrated and
converted to int8 OpenVINO IR (activation ranges recorded over a
calibration set, then int8 execution).

TPU-native version: symmetric per-tensor ACTIVATION scales (recorded by
a calibration pass) + per-output-channel WEIGHT scales; matmul/conv run
int8 x int8 -> int32 on the MXU (v5e int8 peak is 2x bf16) and rescale
to f32 in the epilogue.  The quantized path is params-driven: a layer
whose params carry ``kernel_scale``/``act_scale`` (with an int8
``kernel``) executes quantized — no layer-class mutation, the same
model object serves f32 and int8.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def quantize_activation(x, act_scale):
    """Symmetric int8 quantization with a calibrated scale."""
    return jnp.clip(jnp.round(x.astype(jnp.float32) / act_scale),
                    -127, 127).astype(jnp.int8)


def quantized_matmul(x, kernel_q, kernel_scale, act_scale):
    """int8 x int8 -> int32 contraction over the last/first dims, f32
    rescale epilogue.  ``kernel_scale`` has keepdims shape
    (1, ..., out)."""
    xq = quantize_activation(x, act_scale)
    acc = jax.lax.dot_general(
        xq, kernel_q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    scale = act_scale * kernel_scale.reshape(
        (1,) * (acc.ndim - 1) + (-1,))
    return acc.astype(jnp.float32) * scale


_INT8_CONV_OK = None


def _int8_conv_supported() -> bool:
    """Probe ONCE, eagerly, whether the backend compiles s8xs8->s32
    convolution.  The probe must happen outside any jit trace: a
    try/except around the traced call would only guard abstract
    evaluation — backend rejection surfaces at compile time, outside
    the except."""
    global _INT8_CONV_OK
    if _INT8_CONV_OK is None:
        try:
            x = jnp.zeros((1, 4, 4, 1), jnp.int8)
            k = jnp.zeros((2, 2, 1, 1), jnp.int8)
            # one-shot backend capability probe, not an engine program:
            # caching its throwaway executable would pollute the store
            # zoolint: disable=COMPILE011 — capability probe, not an engine program
            out = jax.jit(lambda a, b: jax.lax.conv_general_dilated(
                a, b, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.int32))(x, k)
            jax.block_until_ready(out)
            _INT8_CONV_OK = True
        except Exception:
            _INT8_CONV_OK = False
    return _INT8_CONV_OK


def quantized_conv(x, kernel_q, kernel_scale, act_scale, *, strides,
                   padding, rhs_dilation, dimension_numbers,
                   feature_group_count=1):
    """int8 conv -> int32 accumulation, f32 rescale epilogue.  Uses the
    dequantized-f32 form (same rounding, same numbers) when the backend
    cannot compile integer convolution — decided by an eager probe, not
    in-trace."""
    xq = quantize_activation(x, act_scale)
    if _int8_conv_supported():
        acc = jax.lax.conv_general_dilated(
            xq, kernel_q, window_strides=strides, padding=padding,
            rhs_dilation=rhs_dilation,
            dimension_numbers=dimension_numbers,
            feature_group_count=feature_group_count,
            preferred_element_type=jnp.int32)
        scale = act_scale * kernel_scale.reshape(
            (1,) * (acc.ndim - 1) + (-1,))
        return acc.astype(jnp.float32) * scale
    # fake-quant fallback: numerically identical rounding, f32 math
    xdq = xq.astype(jnp.float32) * act_scale
    kdq = kernel_q.astype(jnp.float32) * kernel_scale
    return jax.lax.conv_general_dilated(
        xdq, kdq, window_strides=strides, padding=padding,
        rhs_dilation=rhs_dilation,
        dimension_numbers=dimension_numbers,
        feature_group_count=feature_group_count)


# -------------------------------------------------- model-level workflow
def calibrate_model(model, calib_data, batch_size: int = 32,
                    max_batches: int = 8) -> Dict[str, float]:
    """Calibration pass: run eager forwards over ``calib_data``
    recording each layer's input absmax via the engine's activation
    taps (ref InferenceModel.scala:400-421's OpenVINO calibration
    role).  ``calib_data`` is an ndarray/pytree-of-columns or a
    FeatureSet; returns ``{layer_name: max |input|}``."""
    from analytics_zoo_tpu.feature.feature_set import FeatureSet
    from analytics_zoo_tpu.pipeline.api.keras.engine import (
        record_activations)
    variables = model.get_variables()
    if isinstance(calib_data, FeatureSet):
        batches = (b[0] for b in calib_data.epoch_batches(
            0, batch_size, train=False))
    else:
        n = len(jax.tree_util.tree_leaves(calib_data)[0])
        batches = (jax.tree_util.tree_map(
            lambda a: a[i:i + batch_size], calib_data)
            for i in range(0, n, batch_size))
    ranges: Dict[str, float] = {}
    with record_activations() as taps:
        for i, xb in enumerate(batches):
            if i >= max_batches:
                break
            model.apply(variables["params"], xb,
                        state=variables["state"], training=False)
        ranges.update(taps)
    return ranges


def quantize_model(variables, act_ranges, min_size: int = 1024):
    """Produce the params-driven int8 layout from calibrated ranges:
    per-layer int8 ``kernel`` + per-output-channel ``kernel_scale``
    (keepdims — shape ``(1, ..., out)``) + symmetric scalar
    ``act_scale``.  Layers whose params carry those keys execute
    ``quantized_matmul``/``quantized_conv`` natively (Dense/conv
    ``call``); everything else is untouched — the same model object
    serves f32 and int8."""
    params = variables["params"]
    qparams = {}
    for lname, p in params.items():
        qp = dict(p) if isinstance(p, dict) else p
        k = p.get("kernel") if isinstance(p, dict) else None
        rng_max = act_ranges.get(lname, 0.0)
        if k is not None and rng_max > 0.0:
            arr = np.asarray(k)
            if (arr.dtype == np.float32 and arr.ndim >= 2
                    and arr.size >= min_size):
                axes = tuple(range(arr.ndim - 1))
                w_scale = np.maximum(
                    np.max(np.abs(arr), axis=axes, keepdims=True)
                    / 127.0, 1e-12).astype(np.float32)
                qp["kernel"] = np.clip(
                    np.round(arr / w_scale), -127, 127).astype(np.int8)
                qp["kernel_scale"] = w_scale
                qp["act_scale"] = np.float32(max(rng_max / 127.0, 1e-12))
        qparams[lname] = qp
    return {"params": qparams, "state": variables["state"]}
