"""Distributed Inception-v1 via the TFPark adapter (BASELINE.md
config 4: "Distributed Inception-v1 via the TFPark-equivalent
adapter"; reference recipe examples/inception/Train.scala:31 over the
TFPark path pyzoo/zoo/tfpark/model.py:34).

The measured path is the USER path end to end: the model is *defined
in tf.keras* (functional API, the real Inception-v1 topology with its
9 concatenation blocks), converted to native layers by
``tfpark.KerasModel``, and trained by the distributed engine over the
context mesh.  Throughput is the median steady-state epoch from the
fit history (the first epoch, which pays the one-time jit compile, is
excluded) and INCLUDES per-batch host→device transfer — this
benchmark measures the adapter pipeline, not peak MXU (that is the
resnet50 workload's job).
"""

from __future__ import annotations

import time


def _inception_block(tf, x, c1, c3r, c3, c5r, c5, pp, name):
    """One Inception-v1 mixed block (1x1 / 3x3 / 5x5 / pool towers)."""
    L = tf.keras.layers
    b1 = L.Conv2D(c1, 1, activation="relu", padding="same",
                  name=name + "_1x1")(x)
    b3 = L.Conv2D(c3r, 1, activation="relu", padding="same",
                  name=name + "_3x3r")(x)
    b3 = L.Conv2D(c3, 3, activation="relu", padding="same",
                  name=name + "_3x3")(b3)
    b5 = L.Conv2D(c5r, 1, activation="relu", padding="same",
                  name=name + "_5x5r")(x)
    b5 = L.Conv2D(c5, 5, activation="relu", padding="same",
                  name=name + "_5x5")(b5)
    bp = L.MaxPooling2D(3, strides=1, padding="same",
                        name=name + "_pool")(x)
    bp = L.Conv2D(pp, 1, activation="relu", padding="same",
                  name=name + "_poolproj")(bp)
    return L.Concatenate(name=name + "_concat")([b1, b3, b5, bp])


def build_tf_inception_v1(num_classes: int = 1000,
                          image_size: int = 224):
    """Inception-v1 (GoogLeNet, no aux classifiers — the reference
    trains Inception_v1_NoAuxClassifier) in tf.keras functional API."""
    import tensorflow as tf
    L = tf.keras.layers
    inp = L.Input((image_size, image_size, 3))
    x = L.Conv2D(64, 7, strides=2, padding="same",
                 activation="relu", name="conv1")(inp)
    x = L.MaxPooling2D(3, strides=2, padding="same")(x)
    x = L.Conv2D(64, 1, activation="relu", name="conv2r")(x)
    x = L.Conv2D(192, 3, padding="same", activation="relu",
                 name="conv2")(x)
    x = L.MaxPooling2D(3, strides=2, padding="same")(x)
    x = _inception_block(tf, x, 64, 96, 128, 16, 32, 32, "mixed3a")
    x = _inception_block(tf, x, 128, 128, 192, 32, 96, 64, "mixed3b")
    x = L.MaxPooling2D(3, strides=2, padding="same")(x)
    x = _inception_block(tf, x, 192, 96, 208, 16, 48, 64, "mixed4a")
    x = _inception_block(tf, x, 160, 112, 224, 24, 64, 64, "mixed4b")
    x = _inception_block(tf, x, 128, 128, 256, 24, 64, 64, "mixed4c")
    x = _inception_block(tf, x, 112, 144, 288, 32, 64, 64, "mixed4d")
    x = _inception_block(tf, x, 256, 160, 320, 32, 128, 128, "mixed4e")
    x = L.MaxPooling2D(3, strides=2, padding="same")(x)
    x = _inception_block(tf, x, 256, 160, 320, 32, 128, 128, "mixed5a")
    x = _inception_block(tf, x, 384, 192, 384, 48, 128, 128, "mixed5b")
    x = L.GlobalAveragePooling2D()(x)
    x = L.Dropout(0.4)(x)
    out = L.Dense(num_classes, activation="softmax", name="logits")(x)
    m = tf.keras.Model(inp, out)
    m.compile(optimizer=tf.keras.optimizers.SGD(0.0898, momentum=0.9),
              loss="sparse_categorical_crossentropy")
    return m


def run_inception_bench(device, image_size: int = 224,
                        num_classes: int = 1000, batch_size: int = 64,
                        rows: int = 512, timed_epochs: int = 3,
                        warm_epochs: int = 1):
    import numpy as np

    from analytics_zoo_tpu.tfpark import KerasModel

    rs = np.random.RandomState(0)
    x = rs.rand(rows, image_size, image_size, 3).astype(np.float32)
    y = rs.randint(0, num_classes, (rows, 1))

    t0 = time.time()
    tfm = build_tf_inception_v1(num_classes, image_size)
    model = KerasModel(tfm)
    convert_s = time.time() - t0
    n_layers = len(tfm.layers)

    t0 = time.time()
    history = model.fit(x, y, batch_size=batch_size,
                        epochs=warm_epochs + timed_epochs)
    fit_wall = time.time() - t0

    steps = rows // batch_size
    epoch_samples = steps * batch_size
    # per-epoch history; the first warm_epochs pay the jit compile
    steady = sorted(r["throughput"] for r in history[warm_epochs:])
    tput = steady[len(steady) // 2]

    return {
        "metric": "inception_v1_tfpark_train_throughput",
        "value": round(tput, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": None,
        "workload": "inception",
        "image_size": image_size,
        "batch_size": batch_size,
        "rows": rows,
        "timed_epochs": timed_epochs,
        "tf_layers_converted": n_layers,
        "convert_time_s": round(convert_s, 2),
        "fit_wall_s": round(fit_wall, 2),
        "epoch_throughputs": [round(r["throughput"], 1)
                              for r in history],
        "epoch_time_s": round(epoch_samples / tput, 3),
        "includes_h2d": True,
        "device": str(device),
        "device_kind": getattr(device, "device_kind", "?"),
    }
