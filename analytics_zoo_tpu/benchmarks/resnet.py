"""ResNet-50 synthetic-ImageNet training benchmark (BASELINE.md
config 3; reference recipe examples/resnet/TrainImageNet.scala +
examples/inception/Train.scala:75-99 — SGD momentum 0.9, poly(0.5) LR
decay with warmup).

TPU recipe: bf16 compute / f32 master weights (``dtype.compute``),
donated buffers, and the trainer's device-resident ``lax.scan`` epoch
path — ``scan_steps`` training steps compile into ONE XLA program with
zero per-step host involvement, so the number measures the chip, not
the Python dispatch latency (which dominates over a tunneled backend).

Timing discipline: every wall-clock measurement ends with a host read
of the scalar loss (D2H transfer).  ``block_until_ready`` alone proved
unreliable over the experimental tunneled backend (it intermittently
returned before the dispatched chain completed, yielding physically
impossible step times); a device→host copy of a value that depends on
the final step cannot return early.

MFU is computed from XLA's own cost analysis of the compiled epoch
program (not an analytic estimate — the published "4.1 GFLOPs" ResNet
figure counts multiply-adds once and underestimates FLOPs 2x).
"""

from __future__ import annotations

import time


def run_resnet_bench(device, batch_size: int = 128, image_size: int = 224,
                     num_classes: int = 1000, scan_steps: int = 48,
                     repeats: int = 3, compute_dtype: str = "bfloat16",
                     stem: str = "space_to_depth", unroll: int = 1,
                     trace_dir: str = None):
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.benchmarks import (
        calibrate_chip, cost_of_compiled, mfu_estimate)
    from analytics_zoo_tpu.models.image.imageclassification import resnet
    from analytics_zoo_tpu.ops import dtypes
    from analytics_zoo_tpu.parallel import mesh as mesh_lib
    from analytics_zoo_tpu.parallel.trainer import DistributedTrainer
    from analytics_zoo_tpu.pipeline.api.keras import objectives
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
        SGD, poly, warmup_then)

    dtypes.set_policy(param_dtype="float32", compute_dtype=compute_dtype)

    model = resnet(50, num_classes=num_classes,
                   input_shape=(image_size, image_size, 3), stem=stem)
    # reference ImageNet recipe: warmup into poly(0.5) decay
    sched = warmup_then(0.1, 5, poly(0.1, 0.5, max_iteration=10_000))
    optim = SGD(learning_rate=0.1, momentum=0.9, schedule=sched)
    loss_fn = objectives.get("sparse_categorical_crossentropy_with_logits")
    trainer = DistributedTrainer(model, loss_fn, optim_method=optim)

    variables = model.init()
    params = trainer.place_params(variables["params"])
    state = trainer.replicate(variables["state"])
    opt_state = trainer.init_opt_state(params)
    rng = jax.random.PRNGKey(0)

    # Synthetic epoch generated ON DEVICE (no 5 GB H2D over the tunnel),
    # bf16 images sharded on the data axis — the HBM tier of the
    # FeatureSet cache hierarchy holding `scan_steps` batches.
    # epoch_scan_fn treats batch_size as PER-HOST: each scan step
    # slices global_batch_rows(...) rows, so size the epoch to match.
    n_rows = scan_steps * mesh_lib.global_batch_rows(trainer.mesh,
                                                     batch_size)
    x_shard = mesh_lib.data_sharding(trainer.mesh, 4)
    y_shard = mesh_lib.data_sharding(trainer.mesh, 2)
    from analytics_zoo_tpu.compile import engine_jit
    gen = engine_jit(
        lambda k: (
            jax.random.uniform(
                k, (n_rows, image_size, image_size, 3), jnp.bfloat16),
            jax.random.randint(
                jax.random.fold_in(k, 1), (n_rows, 1), 0, num_classes),
        ),
        out_shardings=(x_shard, y_shard), key_hint="resnet_synth_epoch")
    x_dev, y_dev = gen(jax.random.PRNGKey(1))
    jax.block_until_ready((x_dev, y_dev))

    epoch_fn = trainer.epoch_scan_fn(scan_steps, batch_size,
                                     unroll=unroll)

    # AOT-compile ONCE through the engine chokepoint; the compiled
    # object serves every execution AND the FLOPs query (lowering via
    # the jit dispatch path would compile the multi-minute epoch
    # program a second time).  With ZOO_TPU_COMPILE_CACHE set (bench
    # --compile-cache), THIS is the 141s program that round-trips the
    # persistent cache: the first round compiles + persists, every
    # later round deserializes in seconds — t_compile below is the
    # number bench_metrics.json's compile_cache provenance explains.
    t_compile = time.time()
    compiled = epoch_fn.aot(params, opt_state, state, x_dev, y_dev,
                            rng)

    flops, hbm_bytes = cost_of_compiled(compiled)
    if flops:
        flops /= unroll        # unrolled scan body holds `unroll` steps
    if hbm_bytes:
        hbm_bytes /= unroll

    # first execution (donates params/opt_state/state); the first
    # post-compile run over the tunneled backend is ~10x slower than
    # steady state, so it is not timed
    params, opt_state, state, mloss = compiled(
        params, opt_state, state, x_dev, y_dev, rng)
    float(mloss)                       # D2H sync — see module docstring
    compile_s = time.time() - t_compile

    # Repeat discipline (BENCH_r05 showed a 2.3s/2.3s/5.4s tail
    # outlier — deferred work billed to whichever repeat ran last):
    # every repeat window is SYMMETRIC — block_until_ready on the full
    # output tree before t0 (nothing from the previous dispatch can
    # leak in) AND before the window closes (nothing this repeat
    # started can leak out), with the float(mloss) D2H read kept as the
    # can't-return-early anchor (block_until_ready alone proved
    # unreliable over the tunneled backend, see module docstring).  One
    # extra WARMUP repeat runs first and is discarded — it absorbs
    # one-time tails (executable-cache writes, allocator warm-up) the
    # post-compile run doesn't fully drain.
    walls = []
    for r in range(repeats + 1):
        jax.block_until_ready((params, opt_state, state))
        t0 = time.time()
        params, opt_state, state, mloss = compiled(
            params, opt_state, state, x_dev, y_dev,
            jax.random.fold_in(rng, r))
        loss_val = float(mloss)        # D2H sync
        jax.block_until_ready((params, opt_state, state))
        walls.append(time.time() - t0)
    warmup_wall, walls = walls[0], walls[1:]
    wall = min(walls)

    if trace_dir:
        # one profiled epoch AFTER the timed window (profiling adds
        # overhead; it must never contaminate the recorded walls) —
        # feeds dev/trace-summary's MXU/HBM/infeed split
        jax.profiler.start_trace(trace_dir)
        try:
            params, opt_state, state, mloss = compiled(
                params, opt_state, state, x_dev, y_dev,
                jax.random.fold_in(rng, repeats + 1))
            float(mloss)
        finally:
            jax.profiler.stop_trace()

    imgs_per_sec = scan_steps * batch_size / wall
    step_ms = wall / scan_steps * 1e3
    mfu = mfu_estimate(flops, wall / scan_steps, device)

    # Calibrate what the chip delivers RIGHT NOW (shared/tunneled
    # hardware can throttle well below nominal peak), then place the
    # measured step on the chip's own roofline: nominal MFU alone
    # cannot distinguish "model leaves the MXU idle" from "the
    # platform only delivers half its spec sheet".

    calib = calibrate_chip()
    mfu_deliverable = roofline_ms = roofline_frac = None
    if not calib.get("error"):
        if flops and calib.get("deliverable_tflops"):
            mfu_deliverable = round(
                flops / (wall / scan_steps)
                / (calib["deliverable_tflops"] * 1e12), 3)
        if hbm_bytes and calib.get("hbm_gbps"):
            # bandwidth-roofline step time: every byte the compiled
            # program touches (XLA's own counter), streamed at the
            # measured rate — the floor for an HBM-bound program
            roofline_ms = round(
                hbm_bytes / (calib["hbm_gbps"] * 1e9) * 1e3, 2)
            roofline_frac = round(roofline_ms / step_ms, 3)

    return {
        "metric": "resnet50_imagenet_train_throughput",
        "value": round(imgs_per_sec, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": None,
        "workload": "resnet50",
        "batch_size": batch_size,
        "image_size": image_size,
        "step_time_ms": round(step_ms, 2),
        "scan_steps": scan_steps,
        "repeats": repeats,
        "wall_s_per_repeat": [round(w, 3) for w in walls],
        "warmup_repeat_wall_s": round(warmup_wall, 3),
        "compile_time_s": round(compile_s, 2),
        "compute_dtype": compute_dtype,
        "stem": stem,
        "final_loss": loss_val,
        "xla_flops_per_step": flops,
        "xla_bytes_per_step": hbm_bytes,
        "mfu_est": mfu,
        "calibration": calib,
        "mfu_vs_deliverable": mfu_deliverable,
        "hbm_roofline_step_ms": roofline_ms,
        "roofline_attainment": roofline_frac,
        "device": str(device),
        "device_kind": getattr(device, "device_kind", "?"),
    }
