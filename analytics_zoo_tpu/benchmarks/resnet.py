"""ResNet-50 synthetic-ImageNet training benchmark (BASELINE.md
config 3; reference recipe examples/resnet/TrainImageNet.scala +
examples/inception/Train.scala:75-99 — SGD momentum 0.9, poly(0.5) LR
decay with warmup).

TPU recipe: bf16 compute / f32 master weights (``dtype.compute``),
donated buffers, a handful of synthetic batches cycled device-resident
so the number measures the training step, not the synthetic-data
generator."""

from __future__ import annotations

import time

import numpy as np


def run_resnet_bench(device, batch_size: int = 128, image_size: int = 224,
                     num_classes: int = 1000, warmup_steps: int = 5,
                     timed_steps: int = 30,
                     compute_dtype: str = "bfloat16"):
    import jax

    from analytics_zoo_tpu.models.image.imageclassification import resnet
    from analytics_zoo_tpu.ops import dtypes
    from analytics_zoo_tpu.parallel.trainer import DistributedTrainer
    from analytics_zoo_tpu.pipeline.api.keras import objectives
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import (
        SGD, poly, warmup_then)

    dtypes.set_policy(param_dtype="float32", compute_dtype=compute_dtype)

    model = resnet(50, num_classes=num_classes,
                   input_shape=(image_size, image_size, 3))
    # reference ImageNet recipe: warmup into poly(0.5) decay
    sched = warmup_then(0.1, warmup_steps,
                        poly(0.1, 0.5, max_iteration=10_000))
    optim = SGD(learning_rate=0.1, momentum=0.9, schedule=sched)
    loss_fn = objectives.get("sparse_categorical_crossentropy_with_logits")
    trainer = DistributedTrainer(model, loss_fn, optim_method=optim)

    variables = model.init()
    params = trainer.place_params(variables["params"])
    state = trainer.replicate(variables["state"])
    opt_state = trainer.init_opt_state(params)
    rng = jax.random.PRNGKey(0)

    # a few synthetic batches, placed once and cycled (device-resident)
    rs = np.random.RandomState(0)
    n_host_batches = 4
    batches = [
        trainer.put_batch((
            rs.rand(batch_size, image_size, image_size, 3)
            .astype(np.float32),
            rs.randint(0, num_classes, size=(batch_size, 1)),
        ))
        for _ in range(n_host_batches)
    ]

    t_compile = time.time()
    for i in range(warmup_steps):
        params, opt_state, state, loss = trainer.train_step(
            params, opt_state, state, batches[i % n_host_batches], rng)
        if i == 0:
            jax.block_until_ready(loss)
            compile_s = time.time() - t_compile
    jax.block_until_ready(loss)

    t0 = time.time()
    for i in range(timed_steps):
        params, opt_state, state, loss = trainer.train_step(
            params, opt_state, state, batches[i % n_host_batches], rng)
    jax.block_until_ready(loss)
    wall = time.time() - t0

    imgs_per_sec = timed_steps * batch_size / wall
    step_ms = wall / timed_steps * 1e3

    # FLOP estimate: ResNet-50 fwd ≈ 4.1 GFLOPs/img @224 (standard
    # published figure, scaled for image size), training ≈ 3x fwd.
    fwd_flops = 4.1e9 * (image_size / 224.0) ** 2
    train_flops = 3.0 * fwd_flops * batch_size
    from analytics_zoo_tpu.benchmarks import mfu_estimate
    mfu = mfu_estimate(train_flops, wall / timed_steps, device)

    return {
        "metric": "resnet50_imagenet_train_throughput",
        "value": round(imgs_per_sec, 1),
        "unit": "imgs/sec/chip",
        "vs_baseline": None,
        "workload": "resnet50",
        "batch_size": batch_size,
        "image_size": image_size,
        "step_time_ms": round(step_ms, 2),
        "timed_steps": timed_steps,
        "compile_time_s": round(compile_s, 2),
        "compute_dtype": compute_dtype,
        "final_loss": float(loss),
        "mfu_est": mfu,
        "device": str(device),
        "device_kind": getattr(device, "device_kind", "?"),
    }
