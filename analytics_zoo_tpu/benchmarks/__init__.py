"""Benchmark harnesses behind ``bench.py`` (BASELINE.md configs)."""

# bf16 peak FLOP/s per chip for known TPU generations (public specs);
# used only for informational MFU estimates.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def mfu_estimate(flops_per_step, step_time_s, device):
    """Model FLOPs utilisation vs the chip's bf16 peak; None when the
    chip generation (or the FLOP count) is unknown."""
    peak = None
    kind = getattr(device, "device_kind", "")
    for name, val in PEAK_FLOPS.items():
        if name.lower() in str(kind).lower():
            peak = val
            break
    if peak is None or not flops_per_step or step_time_s <= 0:
        return None
    return round(flops_per_step / step_time_s / peak, 6)


def compiled_flops(jitted, *args):
    """FLOPs of a compiled jit program via XLA cost analysis; None when
    the backend doesn't expose it.

    NOTE: XLA counts a while/scan BODY once, not multiplied by the trip
    count — for a whole-epoch scan program this is (approximately) the
    FLOPs of one step (times any ``unroll`` factor)."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) or None
    except Exception:
        return None
