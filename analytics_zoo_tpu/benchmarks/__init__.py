"""Benchmark harnesses behind ``bench.py`` (BASELINE.md configs)."""

# bf16 peak FLOP/s per chip for known TPU generations (public specs);
# used only for informational MFU estimates.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def _nominal_peak(kind) -> float | None:
    """bf16 peak FLOP/s for a device_kind string; None if unknown."""
    for name, val in PEAK_FLOPS.items():
        if name.lower() in str(kind).lower():
            return val
    return None


def mfu_estimate(flops_per_step, step_time_s, device, peak=None):
    """Model FLOPs utilisation vs the chip's bf16 peak; None when the
    chip generation (or the FLOP count) is unknown.  ``peak`` (FLOP/s)
    overrides the device-kind lookup — the knob for backends whose
    nominal peak is unknown (CPU smoke runs) or calibrated hardware
    (``calibrate_chip``'s ``deliverable_tflops``)."""
    if peak is None:
        peak = _nominal_peak(getattr(device, "device_kind", ""))
    if not peak or not flops_per_step or step_time_s <= 0:
        return None
    return round(flops_per_step / step_time_s / peak, 6)


def cost_of_compiled(compiled):
    """(flops, hbm_bytes) of an already-compiled XLA program via its
    cost analysis; (None, None) when the backend doesn't expose it.

    NOTE: XLA counts a while/scan BODY once, not multiplied by the trip
    count — for a whole-epoch scan program this is (approximately) the
    cost of one step (times any ``unroll`` factor)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return (float(cost.get("flops", 0.0)) or None,
                float(cost.get("bytes accessed", 0.0)) or None)
    except Exception:
        return None, None


def compiled_flops(jitted, *args):
    """FLOPs of a jitted program via XLA cost analysis (compiles it);
    None when the backend doesn't expose cost analysis."""
    try:
        return cost_of_compiled(jitted.lower(*args).compile())[0]
    except Exception:
        return None


def calibrate_chip(repeats: int = 4, matmul_n: int = 8192,
                   matmul_iters: int = 32, bw_mb: int = 1024,
                   bw_iters: int = 256):
    """Measure what THIS chip actually delivers right now — the honest
    MFU denominator on shared/tunneled hardware.

    Nominal peak (PEAK_FLOPS) assumes an idle, unthrottled chip; a
    tunneled or multi-tenant chip can deliver a fraction of that even
    on ideal kernels (observed: 48-65% of nominal on a pure bf16
    matmul chain).  Reporting model MFU only against nominal peak
    conflates model inefficiency with platform throttling, so the
    bench also records:

    * ``deliverable_tflops`` — best-of-``repeats`` bf16 matmul-chain
      rate (``matmul_iters`` dependent NxN matmuls inside one jit, so
      dispatch amortises away);
    * ``hbm_gbps`` — best-of-``repeats`` streaming bandwidth from a
      read+write triad over a ``bw_mb``-MB f32 array.

    Each timed window ends with a D2H read of a dependent scalar (see
    resnet.py's timing-discipline note).  Returns a dict; on any
    failure returns ``{"error": ...}`` — calibration must never take
    down the workload that asked for it.
    """
    import time

    import jax
    import jax.numpy as jnp

    try:
        if jax.default_backend() != "tpu":
            # CPU rehearsal of the bench: measure the same quantities
            # at toy sizes so the code path runs in seconds (a CPU
            # would take ~20 min on the TPU-sized matmul chain)
            matmul_n, matmul_iters = min(matmul_n, 1024), min(matmul_iters, 4)
            bw_mb, bw_iters = min(bw_mb, 64), min(bw_iters, 4)
        k = jax.random.PRNGKey(0)
        a = jax.random.normal(k, (matmul_n, matmul_n), jnp.bfloat16)
        b = jax.random.normal(jax.random.fold_in(k, 1),
                              (matmul_n, matmul_n), jnp.bfloat16)

        from analytics_zoo_tpu.compile import engine_jit

        def mm_chain_fn(a, b):
            def body(c, _):
                return jax.lax.dot_general(
                    a, c, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.bfloat16), None
            out, _ = jax.lax.scan(body, b, None, length=matmul_iters)
            return out[0, 0].astype(jnp.float32)

        mm_chain = engine_jit(mm_chain_fn, key_hint="calibrate_mm_chain")

        float(mm_chain(a, b))              # compile + warm
        mm_flops = 2.0 * matmul_n ** 3 * matmul_iters
        best_tf = 0.0
        for _ in range(repeats):
            t0 = time.time()
            float(mm_chain(a, b))          # D2H sync
            best_tf = max(best_tf, mm_flops / (time.time() - t0) / 1e12)

        n_elem = bw_mb * (1 << 20) // 4
        x = jnp.ones((n_elem,), jnp.float32)

        def triad_fn(x):
            def body(c, _):
                return c * jnp.float32(1.0000001) + jnp.float32(1e-9), None
            out, _ = jax.lax.scan(body, x, None, length=bw_iters)
            return out[0]

        triad = engine_jit(triad_fn, key_hint="calibrate_triad")

        float(triad(x))
        bw_bytes = 2.0 * n_elem * 4 * bw_iters      # read + write
        best_bw = 0.0
        for _ in range(repeats):
            t0 = time.time()
            float(triad(x))
            best_bw = max(best_bw, bw_bytes / (time.time() - t0) / 1e9)

        dev = jax.devices()[0]
        nominal = _nominal_peak(getattr(dev, "device_kind", ""))
        return {
            "deliverable_tflops": round(best_tf, 3),
            "hbm_gbps": round(best_bw, 1),
            "nominal_tflops": nominal and nominal / 1e12,
            "deliverable_frac_of_nominal":
                nominal and round(best_tf * 1e12 / nominal, 3),
        }
    except Exception as e:            # noqa: BLE001 — diagnostic path
        return {"error": f"calibration failed: {e!r}"}
