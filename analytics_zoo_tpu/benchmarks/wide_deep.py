"""Wide&Deep Census training benchmark via the NNFrames estimator
(BASELINE.md config 2: "Wide&Deep on Census/Criteo via the
NNFrames-equivalent estimator"; reference model
models/recommendation/WideAndDeep.scala:101, estimator path
pipeline/nnframes/NNEstimator.scala:198).

The measured path is the USER path: a pandas DataFrame with a packed
``features`` column → ``SplitColumns`` preprocessing → multi-input
WideAndDeep → ``NNClassifier.fit``.  Throughput comes from the fitted
estimator's per-epoch history with the first epoch excluded (it pays
the one-time jit compile); the headline is the median steady-state
epoch.
"""

from __future__ import annotations

import time


def run_wide_deep_bench(device, rows: int = 1 << 19,
                        batch_size: int = 8192, timed_epochs: int = 3,
                        warm_epochs: int = 1):
    import numpy as np
    import pandas as pd

    from analytics_zoo_tpu.feature.common import SplitColumns
    from analytics_zoo_tpu.models.recommendation import (
        ColumnFeatureInfo, WideAndDeep)
    from analytics_zoo_tpu.pipeline.api.keras.optimizers import Adam
    from analytics_zoo_tpu.pipeline.nnframes import NNClassifier

    info = ColumnFeatureInfo(
        wide_base_cols=["gender", "age_bucket", "education"],
        wide_base_dims=[3, 10, 16],
        wide_cross_cols=["gender_age", "edu_age"],
        wide_cross_dims=[30, 160],
        embed_cols=["occupation", "relationship"],
        embed_in_dims=[48, 8], embed_out_dims=[16, 8],
        continuous_cols=["hours_per_week", "capital_gain"])

    rs = np.random.RandomState(0)
    gender = rs.randint(0, 3, rows)
    age = rs.randint(0, 10, rows)
    edu = rs.randint(0, 16, rows)
    occ = rs.randint(0, 48, rows)
    rel = rs.randint(0, 8, rows)
    hours = rs.rand(rows).astype(np.float32)
    gain = rs.rand(rows).astype(np.float32)
    cols = {"gender": gender, "age_bucket": age, "education": edu,
            "gender_age": gender * 10 + age, "edu_age": edu * 10 + age,
            "occupation": occ, "relationship": rel,
            "hours_per_week": hours, "capital_gain": gain}
    logit = (((gender == 1) & (age >= 5)) * 1.2
             + np.sin(occ / 48 * np.pi) + hours + gain - 1.8)
    label = (logit + 0.3 * rs.randn(rows) > 0).astype(np.int64)

    model = WideAndDeep(2, info, model_type="wide_n_deep",
                        hidden_layers=(64, 32, 16))
    feats = model.features_from_columns(cols)
    sizes = [f.shape[1] for f in feats]
    packed = np.concatenate(
        [f.astype(np.float32) for f in feats], axis=1)
    df = pd.DataFrame({"features": list(packed), "label": label})

    clf = (NNClassifier(model.model,
                        "sparse_categorical_crossentropy_with_logits",
                        feature_preprocessing=SplitColumns(sizes))
           .set_batch_size(batch_size)
           .set_max_epoch(warm_epochs + timed_epochs)
           .set_optim_method(Adam(lr=1e-3)))
    t0 = time.time()
    nn_model = clf.fit(df)
    fit_wall = time.time() - t0

    steps_per_epoch = rows // batch_size
    epoch_samples = steps_per_epoch * batch_size
    # per-epoch history; epoch 1 pays the jit compile — exclude it
    history = clf.fitted_estimator.history
    steady = sorted(r["throughput"] for r in history[warm_epochs:])
    tput = steady[len(steady) // 2]

    # the Transformer half: one batched inference pass over the frame
    t0 = time.time()
    out = nn_model.transform(df)
    infer_wall = time.time() - t0
    acc = float(np.mean(out["prediction"].to_numpy() == label))

    return {
        "metric": "wide_deep_census_train_throughput",
        "value": round(tput, 1),
        "unit": "samples/sec/chip",
        "vs_baseline": None,
        "workload": "wide_deep",
        "rows": rows,
        "batch_size": batch_size,
        "timed_epochs": timed_epochs,
        "epoch_time_s": round(epoch_samples / tput, 3),
        "fit_wall_s": round(fit_wall, 2),
        "epoch_throughputs": [round(r["throughput"], 1)
                              for r in history],
        "transform_rps": round(rows / infer_wall, 1),
        "train_accuracy": round(acc, 4),
        "device": str(device),
        "device_kind": getattr(device, "device_kind", "?"),
    }
