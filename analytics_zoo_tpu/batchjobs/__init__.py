"""Batch analytics tier — distributed, preemption-tolerant offline
scoring (the NNFrames/NNEstimator batch-inference analog, SURVEY.md
L7; docs/batch.md).

A :class:`BatchJobSpec` binds a PR 2 ``Source`` to a model and an
output directory; :class:`BatchCoordinator` partitions it into a
persisted shard manifest, leases shards to a supervised worker fleet
with heartbeat/lease expiry, and commits every output shard
exactly-once (atomic write-then-rename keyed on shard id + input
fingerprint) — a preempted worker's shard is reclaimed and recomputed
to bit-identical bytes.  Jobs end with a PR 13-shaped capacity report
(rows/sec/chip → chips needed at a target deadline).

Import layering: ``spec``/``manifest``/``report`` are stdlib-only and
file-path loadable (``zoo-batch``/``obs_report`` stay jax-free);
``coordinator`` is supervisor-grade (imports the package, no device
work); ``worker`` is the jax side.  This ``__init__`` therefore only
re-exports the light tier eagerly.
"""

from .spec import BatchJobSpec, ENV_BATCH_JOB  # noqa: F401
from .manifest import (  # noqa: F401
    LeaseClient, LeaseLost, ShardManifest)
from .report import build_report, load_report, render_report  # noqa: F401


def __getattr__(name):
    # heavy tiers on demand, keeping `import analytics_zoo_tpu.
    # batchjobs` cheap for control-plane callers
    if name in ("BatchCoordinator", "run_job"):
        from . import coordinator
        return getattr(coordinator, name)
    if name == "BatchWorker":
        from .worker import BatchWorker
        return BatchWorker
    raise AttributeError(name)


__all__ = [
    "BatchJobSpec", "ENV_BATCH_JOB", "LeaseClient", "LeaseLost",
    "ShardManifest", "BatchCoordinator", "BatchWorker", "run_job",
    "build_report", "load_report", "render_report",
]
