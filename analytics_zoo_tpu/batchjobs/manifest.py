"""Shard manifest + lease ledger: the exactly-once commit protocol.

The coordinator partitions the dataset ONCE into a persisted
``manifest.json`` (shard id → row range → input fingerprint).  From
then on all coordination is files under ``<run_dir>/job/``:

* ``leases/shard-<id>.json`` — a worker's claim on a shard.  Created
  with ``O_CREAT|O_EXCL`` (the filesystem is the arbiter: exactly one
  creator wins).  Renewed every batch by atomic replace; a lease whose
  ``renewed_at`` is older than ``lease_timeout_s`` belongs to a dead
  or preempted worker and may be *stolen* — again by atomic replace,
  so two stealers racing still converge on one owner (renewal reads
  the file back and detects loss).
* ``commits/shard-<id>.json`` — the exactly-once marker, created with
  ``O_EXCL`` **after** the output shard's atomic write-then-rename.
  First creator wins; a racing duplicate sees ``FileExistsError``,
  counts itself as a duplicate, and releases.  Because scoring is
  deterministic, the loser's already-renamed output bytes are
  identical to the winner's — last-rename-wins never changes content.

Crash windows, audited:

* die holding a lease → lease lapses, shard is stolen, recompute.
* die after output rename, before marker → recompute produces
  byte-identical output; the rename is a no-op content-wise; marker
  then lands.  Never a torn or half shard visible (rename is atomic).
* marker exists but fingerprint ≠ manifest (spec changed between
  runs) → marker is ignored and the shard recomputed: a commit is
  only trusted for the exact (shard_id, input fingerprint) it names.

CONTRACT: stdlib-only, loadable by file path (obs_report/zoo-batch).
"""

from __future__ import annotations

import json
import os
import socket
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import spec as _spec

__all__ = [
    "ShardManifest", "LeaseClient", "LeaseLost", "shard_lease_path",
    "shard_commit_path", "shard_output_path", "read_leases",
    "read_commits",
]


class LeaseLost(RuntimeError):
    """Raised when a renewal discovers the lease was stolen — the
    worker must abandon the shard (the thief recomputes it)."""


def _flight(kind: str, **detail) -> None:
    """Best-effort flight-recorder event.  Guarded lazy import: this
    module's stdlib-only file-path-loadable contract (obs_report /
    zoo-batch) must keep working with no package on sys.path."""
    try:
        from analytics_zoo_tpu.observability.flightrec import (
            record_event)
        record_event(kind, **detail)
    except Exception:   # noqa: BLE001 — forensics never blocks leasing
        pass


def shard_lease_path(run_dir: str, shard_id: int) -> str:
    return os.path.join(
        _spec.job_dir(run_dir), _spec.LEASE_DIR, f"shard-{shard_id:05d}.json")


def shard_commit_path(run_dir: str, shard_id: int) -> str:
    return os.path.join(
        _spec.job_dir(run_dir), _spec.COMMIT_DIR, f"shard-{shard_id:05d}.json")


def shard_output_path(output_dir: str, shard_id: int) -> str:
    return os.path.join(output_dir, f"shard-{shard_id:05d}.npy")


def _write_json_atomic(path: str, doc: Dict[str, Any]) -> None:
    # local twin of common.fsutil.atomic_write_text, hand-rolled on
    # purpose: this module is stdlib-only/file-path-loadable (no
    # package on sys.path), and lease/commit markers additionally
    # fsync before the rename — the exactly-once protocol trusts the
    # marker only if its bytes are durable
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        # a concurrent atomic replace never leaves a torn file, but the
        # file may vanish (release) between listdir and open
        return None


class ShardManifest:
    """The persisted partition of a job: the ground truth every
    incarnation of every worker and the coordinator agree on."""

    def __init__(self, doc: Dict[str, Any], run_dir: str):
        self.doc = doc
        self.run_dir = run_dir

    # ------------------------------------------------------------- create
    @classmethod
    def create(cls, job: "_spec.BatchJobSpec", run_dir: str) -> "ShardManifest":
        """Partition ``job`` and persist the manifest (idempotent: an
        existing manifest for the same job geometry is reused so a
        resumed coordinator sees the SAME partition)."""
        jdir = _spec.job_dir(run_dir)
        os.makedirs(os.path.join(jdir, _spec.LEASE_DIR), exist_ok=True)
        os.makedirs(os.path.join(jdir, _spec.COMMIT_DIR), exist_ok=True)
        if job.output_dir:
            os.makedirs(job.output_dir, exist_ok=True)

        path = os.path.join(jdir, _spec.MANIFEST_FILE)
        shards = []
        for sid in range(job.num_shards()):
            start, end = job.shard_range(sid)
            shards.append({
                "shard_id": sid, "start": start, "end": end,
                "fingerprint": job.shard_fingerprint(sid),
            })
        doc = {
            "job": job.name,
            "num_rows": job.resolved_rows(),
            "rows_per_shard": job.rows_per_shard,
            "lease_timeout_s": job.lease_timeout_s,
            "output_dir": job.output_dir,
            "shards": shards,
        }
        existing = _read_json(path)
        if existing is not None:
            if existing.get("shards") != shards:
                raise RuntimeError(
                    f"{path}: existing manifest partitions a different job "
                    "— refusing to mix output shards (use a fresh run dir)")
            doc = existing
        else:
            _write_json_atomic(path, doc)
        _write_json_atomic(os.path.join(jdir, _spec.JOB_FILE), job.to_dict())
        return cls(doc, run_dir)

    @classmethod
    def load(cls, run_dir: str) -> "ShardManifest":
        path = os.path.join(_spec.job_dir(run_dir), _spec.MANIFEST_FILE)
        doc = _read_json(path)
        if doc is None:
            raise FileNotFoundError(f"no shard manifest at {path}")
        return cls(doc, run_dir)

    # ------------------------------------------------------------ queries
    @property
    def shards(self) -> List[Dict[str, Any]]:
        return self.doc["shards"]

    @property
    def lease_timeout_s(self) -> float:
        return float(self.doc.get("lease_timeout_s", 30.0))

    def shard(self, shard_id: int) -> Dict[str, Any]:
        return self.shards[shard_id]

    def committed(self) -> Dict[int, Dict[str, Any]]:
        """shard_id → commit marker, for markers whose fingerprint
        still matches the manifest (stale markers are not trusted)."""
        out = {}
        for s in self.shards:
            marker = _read_json(shard_commit_path(self.run_dir, s["shard_id"]))
            if marker and marker.get("fingerprint") == s["fingerprint"]:
                out[s["shard_id"]] = marker
        return out

    def pending(self) -> List[Dict[str, Any]]:
        done = self.committed()
        return [s for s in self.shards if s["shard_id"] not in done]

    def progress(self) -> Dict[str, Any]:
        done = self.committed()
        rows_done = sum(m.get("rows", 0) for m in done.values())
        return {
            "shards_total": len(self.shards),
            "shards_committed": len(done),
            "rows_total": int(self.doc["num_rows"]),
            "rows_committed": rows_done,
            "rows_recomputed": sum(
                m.get("recomputed_rows", 0) for m in done.values()),
            "duplicates": sum(
                int(m.get("duplicates", 0)) for m in done.values()),
            "complete": len(done) == len(self.shards),
        }


class LeaseClient:
    """One worker's handle on the shard ledger.

    The claim→settle loop it supports is the same obligation shape the
    serving consumer carries (zoolint ACK013): every shard returned by
    :meth:`claim_shards` MUST reach exactly one of ``commit_shard``,
    ``release_shard``, or a propagated raise — the lint now checks
    that statically for ``batchjobs/`` too (docs/static-analysis.md).
    """

    def __init__(self, run_dir: str, owner: str = None, *,
                 timeout_s: float = None,
                 clock: Callable[[], float] = time.time):
        self.run_dir = run_dir
        self.manifest = ShardManifest.load(run_dir)
        self.owner = owner or f"{socket.gethostname()}:{os.getpid()}"
        self.timeout_s = (self.manifest.lease_timeout_s
                          if timeout_s is None else float(timeout_s))
        self._clock = clock
        # resume bookkeeping: rows a stolen lease's victim had already
        # scored — the recompute cost this incarnation is paying
        self._stolen_rows: Dict[int, int] = {}

    # ------------------------------------------------------------- claim
    def claim_shards(self, limit: int = 1) -> List[Tuple[int, Dict[str, Any]]]:
        """Claim up to ``limit`` uncommitted, unleased (or
        expired-lease) shards.  Returns ``(shard_id, shard)`` pairs;
        every returned shard carries the settle obligation above."""
        claimed: List[Tuple[int, Dict[str, Any]]] = []
        for s in self.manifest.pending():
            if len(claimed) >= limit:
                break
            sid = s["shard_id"]
            if self._try_acquire(sid):
                claimed.append((sid, s))
        return claimed

    def _lease_doc(self, shard_id: int, rows_done: int = 0) -> Dict[str, Any]:
        now = self._clock()
        return {
            "shard_id": shard_id, "owner": self.owner,
            "created_at": now, "renewed_at": now, "rows_done": rows_done,
        }

    def _try_acquire(self, shard_id: int) -> bool:
        path = shard_lease_path(self.run_dir, shard_id)
        doc = self._lease_doc(shard_id)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return self._try_steal(shard_id, path)
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        _flight("lease.claim", shard=shard_id, owner=self.owner)
        return True

    def _try_steal(self, shard_id: int, path: str) -> bool:
        held = _read_json(path)
        if held is None:
            # released between listdir and read — retry the O_EXCL path
            # on the next claim round rather than spinning here
            return False
        if held.get("owner") == self.owner:
            return True  # our own (e.g. re-claim after coordinator restart)
        age = self._clock() - float(held.get("renewed_at", 0.0))
        if age <= self.timeout_s:
            return False  # live lease — someone else is scoring it
        # expired: the owner is dead or preempted.  Steal by atomic
        # replace; the victim's rows_done is the recompute debt.
        self._stolen_rows[shard_id] = int(held.get("rows_done", 0))
        _write_json_atomic(path, self._lease_doc(shard_id))
        _flight("lease.steal", shard=shard_id, owner=self.owner,
                victim=str(held.get("owner", "")),
                stolen_rows=self._stolen_rows[shard_id],
                age_s=round(age, 3))
        return True

    # ------------------------------------------------------------- renew
    def renew(self, shard_id: int, rows_done: int = 0) -> None:
        """Refresh the lease (call every batch).  Raises
        :class:`LeaseLost` if the lease was stolen — the caller must
        stop scoring this shard and claim another."""
        path = shard_lease_path(self.run_dir, shard_id)
        held = _read_json(path)
        if held is None or held.get("owner") != self.owner:
            thief = held.get("owner") if held else "release"
            _flight("lease.lost", shard=shard_id, owner=self.owner,
                    to=str(thief))
            raise LeaseLost(f"shard {shard_id}: lease lost to {thief}")
        held["renewed_at"] = self._clock()
        held["rows_done"] = int(rows_done)
        _write_json_atomic(path, held)

    # ------------------------------------------------------------ settle
    def commit_shard(self, shard_id: int, *, fingerprint: str,
                     rows: int, seconds: float = 0.0) -> bool:
        """Settle a claim as done: write the exactly-once marker and
        drop the lease.  Returns True if THIS call created the marker,
        False if a racing duplicate got there first (either way the
        obligation is discharged and the shard is committed)."""
        path = shard_commit_path(self.run_dir, shard_id)
        doc = {
            "shard_id": shard_id, "fingerprint": fingerprint,
            "rows": int(rows), "seconds": float(seconds),
            "owner": self.owner, "committed_at": self._clock(),
            "recomputed_rows": int(self._stolen_rows.pop(shard_id, 0)),
            "duplicates": 0,
        }
        created = True
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
        except FileExistsError:
            created = False
            existing = _read_json(path)
            if existing is not None:
                existing["duplicates"] = int(existing.get("duplicates", 0)) + 1
                _write_json_atomic(path, existing)
        self.release_shard(shard_id)
        return created

    def release_shard(self, shard_id: int) -> None:
        """Settle a claim as abandoned: drop the lease so another
        worker can claim immediately (no timeout wait)."""
        path = shard_lease_path(self.run_dir, shard_id)
        held = _read_json(path)
        if held is not None and held.get("owner") == self.owner:
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass


# --------------------------------------------------------------- reports
def read_leases(run_dir: str) -> List[Dict[str, Any]]:
    ldir = os.path.join(_spec.job_dir(run_dir), _spec.LEASE_DIR)
    out = []
    try:
        names = sorted(os.listdir(ldir))
    except FileNotFoundError:
        return out
    for name in names:
        doc = _read_json(os.path.join(ldir, name))
        if doc is not None:
            out.append(doc)
    return out


def read_commits(run_dir: str) -> List[Dict[str, Any]]:
    cdir = os.path.join(_spec.job_dir(run_dir), _spec.COMMIT_DIR)
    out = []
    try:
        names = sorted(os.listdir(cdir))
    except FileNotFoundError:
        return out
    for name in names:
        doc = _read_json(os.path.join(cdir, name))
        if doc is not None:
            out.append(doc)
    return out
