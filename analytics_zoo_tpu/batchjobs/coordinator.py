"""Batch job coordinator — the jax-free supervisor of a scoring fleet.

Composes the existing control planes instead of inventing new ones:

* **launcher (PR 4/9)**: a ``ZooCluster`` run dir gives every worker
  slot its ``host-<k>/`` metrics dir, a pre-allocated metrics port, a
  shared clock anchor and the ``cluster.json`` manifest — so batch
  fleets are first-class citizens of ``obs_report --merge-hosts``;
* **detector (PR 6)**: worker deaths are classified by exit code;
  preemption-like deaths (SIGKILL/SIGTERM) respawn under a per-slot
  ``RetryBudget``, real errors too — budget exhaustion ends the job
  with the structured degraded record (exit 17 via the CLI), never a
  silent hang;
* **compile farm (PR 8)**: the run dir IS the executable cache —
  ZOO_TPU_RUN_DIR rides the worker env, process 0 pays the compiles,
  replacement incarnations deserialize warm;
* **ledger (this PR)**: completion is a property of the manifest
  (every shard committed), NOT of worker exit codes — a worker that
  dies after its last commit changes nothing, a worker that exits 0
  early is caught by the ledger staying incomplete.

Like the serving supervisor, a ``worker_factory(index, incarnation)``
hook decides each life's argv+env — chaos plans arm incarnation 0
only, so the kill-and-resume drill murders the first life and lets
the replacement finish clean.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from .spec import BatchJobSpec, ENV_BATCH_JOB
from .manifest import ShardManifest
from . import report as report_lib

log = logging.getLogger("analytics_zoo_tpu.batchjobs.coordinator")

WORKER_MODULE = "analytics_zoo_tpu.batchjobs.worker"


class _Slot:
    def __init__(self, index: int, budget):
        self.index = index
        self.budget = budget
        self.proc: Optional[subprocess.Popen] = None
        self.incarnation = 0
        self.done = False
        self.last_exit: Optional[int] = None
        self.next_spawn_at: Optional[float] = None


class BatchCoordinator:
    """Partition, lease, supervise, report — one offline job end to
    end.  jax-free: safe on a CPU-only control node.

    Args:
        job: the :class:`BatchJobSpec`.
        run_dir: fleet run dir (ledger lives in ``<run_dir>/job/``).
        num_workers: fleet width (the "chips" of the capacity report).
        chaos: optional :class:`ChaosPlan`/JSON armed for incarnation
            0 of each slot (fault drills).
        env: extra env for workers (e.g. PYTHONPATH in tests).
        worker_factory: override ``(index, incarnation) -> (argv,
            env)`` — the supervisor's test seam.
    """

    def __init__(self, job: BatchJobSpec, run_dir: str, *,
                 num_workers: int = 1, chaos=None,
                 env: Optional[Dict[str, str]] = None,
                 worker_factory: Optional[Callable] = None,
                 retry_times: int = 3, retry_window_s: float = 60.0,
                 backoff_base_s: float = 0.1,
                 backoff_max_s: float = 2.0):
        from analytics_zoo_tpu.observability.flightrec import (
            FlightRecorder)
        from analytics_zoo_tpu.parallel.launcher import ZooCluster
        from analytics_zoo_tpu.resilience.policy import RetryBudget

        self.job = job
        self.run_dir = run_dir
        self.num_workers = int(num_workers)
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.restarts_total = 0
        self._deaths: List[Dict] = []
        self._respawns: List[Dict] = []
        # a PRIVATE recorder into the run-level events.jsonl: the
        # process-wide slot belongs to workers (each journals into its
        # own host-<k>/), the coordinator is the fleet's control plane
        self._flightrec = FlightRecorder(run_dir, role="coordinator")

        # run-dir plumbing (host slots, ports, clock anchor,
        # cluster.json) + chaos env — reuse the launcher wholesale
        self.cluster = ZooCluster(
            num_processes=self.num_workers, env=env or {},
            run_dir=run_dir, chaos=chaos)
        self.manifest = ShardManifest.create(job, run_dir)
        self.worker_factory = worker_factory or self._default_factory
        self._slots = [
            _Slot(i, RetryBudget(retry_times=retry_times,
                                 window_s=retry_window_s))
            for i in range(self.num_workers)]

    # ------------------------------------------------------------- spawn
    def _default_factory(self, index: int,
                         incarnation: int) -> Tuple[List[str], Dict]:
        from analytics_zoo_tpu.resilience.chaos import ENV_CHAOS
        env = self.cluster.worker_env(index)
        env[ENV_BATCH_JOB] = self.run_dir
        if incarnation > 0:
            # chaos arms the FIRST life only: the drill is "worker
            # dies once", not "slot dies forever"
            env.pop(ENV_CHAOS, None)
        argv = [sys.executable, "-m", WORKER_MODULE]
        return argv, env

    def _spawn(self, slot: _Slot) -> None:
        from analytics_zoo_tpu.parallel.launcher import _set_pdeathsig
        argv, env = self.worker_factory(slot.index, slot.incarnation)
        # drop the dead incarnation's heartbeat (launcher/supervisor
        # contamination guard): the replacement's first beat lands
        # after model load, and a predecessor's stale timestamp would
        # make stale_hosts condemn every slow-starting respawn
        try:
            os.remove(os.path.join(
                self.run_dir, f"host-{slot.index}", "heartbeat.json"))
        except OSError:
            pass
        slot.proc = subprocess.Popen(
            argv, env=env, preexec_fn=_set_pdeathsig)
        self.cluster.monitor.register(slot.proc, index=slot.index)
        slot.incarnation += 1
        slot.next_spawn_at = None
        log.info("batch worker %d spawned (incarnation %d, pid %d)",
                 slot.index, slot.incarnation, slot.proc.pid)

    # --------------------------------------------------------- supervision
    def _handle_exit(self, slot: _Slot, code: int,
                     complete: bool) -> None:
        from analytics_zoo_tpu.resilience.detector import classify_exit
        slot.proc = None
        slot.last_exit = code
        cls = classify_exit(code)
        if code == 0:
            if complete:
                slot.done = True
                log.info("batch worker %d drained (exit 0)", slot.index)
                return
            # exit 0 with shards still uncommitted: either it raced
            # the last commit (ledger will show complete next poll) or
            # it wrongly concluded the job was done — respawn through
            # the budget either way; an idle respawn exits 0 cheaply
            log.warning("batch worker %d exited 0 with the ledger "
                        "incomplete; respawning", slot.index)
        self._deaths.append({"process_index": slot.index, "code": code,
                             "classification": cls})
        if not slot.budget.consume():
            self._flightrec.record(
                "fleet.degraded", component="batchjobs",
                worker=slot.index, exit=cls,
                reason="restart budget exhausted")
            self._persist_respawns()
            raise _BudgetExhausted(slot, code, cls)
        self.restarts_total += 1
        delay = min(self.backoff_max_s,
                    self.backoff_base_s * (2 ** max(
                        0, slot.incarnation - 1)))
        slot.next_spawn_at = time.time() + delay
        self._flightrec.record(
            "worker.respawn", worker=slot.index, exit=cls, code=code,
            incarnation=slot.incarnation, delay_s=round(delay, 3),
            budget_left=slot.budget.remaining)
        self._respawns.append({
            "process_index": slot.index, "code": code,
            "classification": cls, "incarnation": slot.incarnation,
            "delay_s": round(delay, 3),
            "budget_left": slot.budget.remaining,
            "time_unix": round(time.time(), 3)})
        # persisted AT DECISION TIME, not at job end: a coordinator
        # that is itself killed later leaves the respawn ledger behind
        # for zoo-doctor
        self._persist_respawns()
        log.warning("batch worker %d died (%s); respawn in %.2fs "
                    "(%d budget left)", slot.index, cls, delay,
                    slot.budget.remaining)

    def run(self, timeout_s: Optional[float] = None,
            poll_s: float = 0.05) -> Dict:
        """Run the job to completion.  Returns the capacity report;
        raises :class:`DegradedTraining` when a slot's restart budget
        exhausts with the ledger incomplete."""
        from analytics_zoo_tpu.resilience.policy import DegradedTraining

        t0 = time.time()
        deadline = None if timeout_s is None else t0 + timeout_s
        for slot in self._slots:
            self._spawn(slot)
        try:
            while True:
                progress = self.manifest.progress()
                if progress["complete"]:
                    break
                now = time.time()
                if deadline is not None and now > deadline:
                    raise TimeoutError(
                        f"batch job {self.job.name!r} incomplete after "
                        f"{timeout_s}s: {progress}")
                for slot in self._slots:
                    if slot.done:
                        continue
                    if slot.proc is None:
                        if slot.next_spawn_at is not None \
                                and now >= slot.next_spawn_at:
                            self._spawn(slot)
                        continue
                    code = slot.proc.poll()
                    if code is not None:
                        self._handle_exit(
                            slot, code, progress["complete"])
                if all(s.done or (s.proc is None
                                  and s.next_spawn_at is None)
                       for s in self._slots):
                    raise RuntimeError(
                        f"batch job {self.job.name!r} stalled: no "
                        f"live or respawnable workers, {progress}")
                time.sleep(poll_s)
        except _BudgetExhausted as exc:
            self.stop()
            elapsed = time.time() - t0
            report = report_lib.build_report(
                self.run_dir, num_chips=self.num_workers,
                elapsed_s=elapsed, status="degraded",
                restarts=self.restarts_total)
            record = {
                "status": "degraded", "component": "batchjobs",
                "reason": (f"worker {exc.slot.index} exhausted its "
                           "restart budget"),
                "exit_code": exc.code,
                "classification": exc.classification,
                "deaths": self._deaths,
                "report": report,
            }
            self._write_degraded(record)
            raise DegradedTraining(record["reason"], result=record) \
                from None
        # ledger complete: let drained workers exit 0, then report
        codes = self._drain()
        elapsed = time.time() - t0
        report = report_lib.build_report(
            self.run_dir, num_chips=self.num_workers,
            elapsed_s=elapsed, status="complete",
            restarts=self.restarts_total)
        report["worker_exit_codes"] = codes
        log.info("batch job %r complete: %.0f rows in %.2fs "
                 "(%d restarts)", self.job.name,
                 report["rows_committed"], elapsed,
                 self.restarts_total)
        return report

    def _drain(self, timeout_s: float = 60.0) -> List[int]:
        codes: Dict[int, int] = {}
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            live = False
            for slot in self._slots:
                if slot.proc is None:
                    if slot.last_exit is not None:
                        codes[slot.index] = slot.last_exit
                    continue
                code = slot.proc.poll()
                if code is None:
                    live = True
                else:
                    slot.proc = None
                    slot.last_exit = code
                    codes[slot.index] = code
            if not live:
                break
            time.sleep(0.05)
        self.stop()
        return [codes.get(i, -1) for i in range(self.num_workers)]

    def _persist_respawns(self) -> None:
        """Atomic snapshot of the death/respawn ledger
        (``<run_dir>/job/respawns.json``) — one of zoo-doctor's join
        inputs.  Best-effort: supervision never fails on forensics."""
        import json
        from analytics_zoo_tpu.common.fsutil import atomic_write_text
        path = os.path.join(self.run_dir, "job", "respawns.json")
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write_text(path, json.dumps({
                "written_unix": round(time.time(), 3),
                "restarts_total": self.restarts_total,
                "deaths": self._deaths,
                "respawns": self._respawns,
            }, indent=2, sort_keys=True))
        except OSError:
            log.exception("could not persist respawns.json")

    def _write_degraded(self, record: Dict) -> None:
        import json
        from analytics_zoo_tpu.common.fsutil import atomic_write_text
        atomic_write_text(os.path.join(self.run_dir, "degraded.json"),
                          json.dumps(record, indent=2, sort_keys=True))

    def stop(self) -> None:
        self.cluster.stop()
        for slot in self._slots:
            slot.proc = None
        try:
            self._flightrec.close()
        except Exception:   # noqa: BLE001 — teardown best-effort
            pass


class _BudgetExhausted(Exception):
    def __init__(self, slot: _Slot, code: int, classification: str):
        super().__init__(f"slot {slot.index} budget exhausted")
        self.slot = slot
        self.code = code
        self.classification = classification


def run_job(job: BatchJobSpec, run_dir: str, *, num_workers: int = 1,
            chaos=None, env: Optional[Dict[str, str]] = None,
            timeout_s: Optional[float] = None, **kw) -> Dict:
    """One-call convenience: partition, run, report."""
    coord = BatchCoordinator(job, run_dir, num_workers=num_workers,
                             chaos=chaos, env=env, **kw)
    try:
        return coord.run(timeout_s=timeout_s)
    finally:
        coord.stop()
