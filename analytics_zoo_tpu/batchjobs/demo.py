"""Canned builders for batch jobs — the demo/CI/test fixtures.

Builder refs in a :class:`~analytics_zoo_tpu.batchjobs.spec.BatchJobSpec`
name functions by ``module:attr``; these are the stock ones.  All are
deterministic by construction (fixed seeds, no wall-clock input) —
the property the exactly-once protocol's bit-identical guarantee is
stated against.

``zoo-batch demo`` and the Jenkinsfile 'Batch scoring' stage run
``demo_job`` end to end; the kill-and-resume acceptance test runs the
same builders with a chaos plan armed.
"""

from __future__ import annotations

import numpy as np

from .spec import BatchJobSpec


def demo_data(num_rows: int = 1024, dim: int = 8,
              seed: int = 7) -> np.ndarray:
    return np.asarray(
        np.random.RandomState(seed).randn(num_rows, dim),
        dtype=np.float32)


def demo_source(num_rows: int = 1024, dim: int = 8, seed: int = 7):
    """ArraySource over a fixed random matrix."""
    from analytics_zoo_tpu.data.source import ArraySource
    return ArraySource(demo_data(num_rows, dim, seed))


class LinearModel:
    """Deterministic numpy predictor: ``y = relu(x @ W + b)``.

    The fast stand-in for tests and the CI demo job — per-batch
    ``delay_s`` stretches shard wall time so chaos drills can land a
    kill mid-shard reliably."""

    def __init__(self, w: np.ndarray, b: np.ndarray,
                 delay_s: float = 0.0):
        self.w = w
        self.b = b
        self.delay_s = float(delay_s)

    def predict(self, x, batch_size=None):
        if self.delay_s > 0:
            import time
            time.sleep(self.delay_s)
        x = np.asarray(x, dtype=np.float32)
        return np.maximum(x @ self.w + self.b, 0.0)


def demo_model(dim: int = 8, out_dim: int = 4, seed: int = 7,
               delay_s: float = 0.0) -> LinearModel:
    rng = np.random.RandomState(seed + 1)
    return LinearModel(
        np.asarray(rng.randn(dim, out_dim), dtype=np.float32),
        np.asarray(rng.randn(out_dim), dtype=np.float32),
        delay_s=delay_s)


def demo_keras_model(dim: int = 8, out_dim: int = 4):
    """The real jax path: a KerasNet behind ``InferenceModel`` — its
    ``warm()`` runs under the PR 8 compile farm when the worker env
    carries ZOO_TPU_RUN_DIR, so a replacement incarnation deserializes
    the warm executable instead of recompiling."""
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.inference import InferenceModel
    model = Sequential()
    # Explicit name: auto-naming uses a process-global counter and the
    # init rng folds in the name, so an unnamed layer would get fresh
    # weights on every build — replacement incarnations must score
    # bit-identically.
    model.add(Dense(out_dim, input_shape=(dim,), name="demo_dense"))
    model.compile("adam", "mse")
    return InferenceModel().load_zoo(model)


def write_demo_npy(path: str, num_rows: int = 1024, dim: int = 8,
                   seed: int = 7) -> str:
    """Materialize the demo matrix as an ``NpyDirSource`` directory
    (the zero-copy memory-mapped input path)."""
    import os
    os.makedirs(path, exist_ok=True)
    x = demo_data(num_rows, dim, seed)
    np.save(os.path.join(path, "x.npy"), x)
    return path


def demo_job(output_dir: str, *, num_rows: int = 1024, dim: int = 8,
             rows_per_shard: int = 128, batch_size: int = 32,
             seed: int = 7, delay_s: float = 0.0,
             lease_timeout_s: float = 5.0,
             keras: bool = False) -> BatchJobSpec:
    model_ref = ("analytics_zoo_tpu.batchjobs.demo:demo_keras_model"
                 if keras else
                 "analytics_zoo_tpu.batchjobs.demo:demo_model")
    model_args = ({"dim": dim} if keras
                  else {"dim": dim, "seed": seed, "delay_s": delay_s})
    return BatchJobSpec(
        name="demo-batch-scoring",
        source={"kind": "builder",
                "ref": "analytics_zoo_tpu.batchjobs.demo:demo_source",
                "args": {"num_rows": num_rows, "dim": dim,
                         "seed": seed}},
        model={"kind": "builder", "ref": model_ref,
               "args": model_args},
        output_dir=output_dir,
        num_rows=num_rows,
        rows_per_shard=rows_per_shard,
        batch_size=batch_size,
        lease_timeout_s=lease_timeout_s,
        target_deadline_s=60.0,
    )
