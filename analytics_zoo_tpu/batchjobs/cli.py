"""zoo-batch — run, demo, and report offline batch scoring jobs.

    zoo-batch run --spec job.json --run-dir RUN --workers 4
    zoo-batch demo --run-dir RUN --output-dir OUT --report-out cap.json
    zoo-batch report RUN            # jax-free (handled by the shim)

``run``/``demo`` exit 0 on a complete ledger and speak the launcher's
degraded protocol on restart-budget exhaustion: the structured record
prints as one JSON line and the process exits
:data:`~analytics_zoo_tpu.resilience.policy.DEGRADED_EXIT_CODE` (17)
— CI can tell "the fleet died of preemption pressure" from "the job
has a bug" by exit code alone.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys


def _finish(report: dict, run_dir: str, report_out: str = None) -> int:
    from . import report as report_lib
    from .spec import job_dir, REPORT_FILE
    print(report_lib.render_report(report))
    src = os.path.join(job_dir(run_dir), REPORT_FILE)
    if report_out:
        shutil.copyfile(src, report_out)
        print(f"capacity report -> {report_out}")
    return 0 if report.get("status") == "complete" else 1


def cmd_run(args) -> int:
    from .coordinator import run_job
    from .spec import BatchJobSpec
    with open(args.spec) as f:
        job = BatchJobSpec.from_dict(json.load(f))
    report = run_job(job, args.run_dir, num_workers=args.workers,
                     timeout_s=args.timeout)
    return _finish(report, args.run_dir, args.report_out)


def cmd_demo(args) -> int:
    from .coordinator import run_job
    from .demo import demo_job
    job = demo_job(args.output_dir, num_rows=args.rows,
                   rows_per_shard=args.rows_per_shard,
                   batch_size=args.batch_size, keras=args.keras)
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    report = run_job(job, args.run_dir, num_workers=args.workers,
                     env=env, timeout_s=args.timeout)
    return _finish(report, args.run_dir, args.report_out)


def cmd_report(args) -> int:
    # the shim serves `report` jax-free; this path exists so
    # `python -m analytics_zoo_tpu.batchjobs.cli report` works too
    from . import report as report_lib
    print(report_lib.render_job_section(args.run_dir))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="zoo-batch",
        description="distributed offline batch scoring (docs/batch.md)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_run = sub.add_parser("run", help="run a job from a spec JSON")
    p_run.add_argument("--spec", required=True,
                       help="BatchJobSpec JSON file")
    p_run.add_argument("--run-dir", required=True)
    p_run.add_argument("--workers", type=int, default=1)
    p_run.add_argument("--timeout", type=float, default=None)
    p_run.add_argument("--report-out", default=None,
                       help="also copy the capacity report JSON here")
    p_run.set_defaults(fn=cmd_run)

    p_demo = sub.add_parser(
        "demo", help="run the canned demo job end to end")
    p_demo.add_argument("--run-dir", required=True)
    p_demo.add_argument("--output-dir", required=True)
    p_demo.add_argument("--workers", type=int, default=2)
    p_demo.add_argument("--rows", type=int, default=1024)
    p_demo.add_argument("--rows-per-shard", type=int, default=128)
    p_demo.add_argument("--batch-size", type=int, default=32)
    p_demo.add_argument("--keras", action="store_true",
                        help="score through a jitted KerasNet (warms "
                             "the run-dir compile farm) instead of "
                             "the numpy stand-in")
    p_demo.add_argument("--timeout", type=float, default=300.0)
    p_demo.add_argument("--report-out", default=None)
    p_demo.set_defaults(fn=cmd_demo)

    p_rep = sub.add_parser("report",
                           help="render a job ledger + capacity report")
    p_rep.add_argument("run_dir")
    p_rep.set_defaults(fn=cmd_report)

    args = ap.parse_args(argv)
    from analytics_zoo_tpu.resilience.policy import degraded_exit
    with degraded_exit(stream=sys.stderr):
        return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
