"""BatchJobSpec — the declarative description of one offline scoring job.

Reference: NNFrames/NNEstimator ``transform``-style batch inference
(SURVEY.md L7; BigDL arXiv 1804.05839, BigDL 2.0 arXiv 2204.01715) —
"score this dataset with this model, write the results" as a *job*,
not a serving request stream.  The TPU rebuild expresses that job as a
JSON document binding three things:

* an **input**: a :class:`~analytics_zoo_tpu.data.source.Source`
  builder (``module:function`` or ``/path/to/file.py:function``) or an
  ``NpyDirSource`` directory — the PR 2 random-access contract is what
  makes shard partitioning trivial and deterministic;
* a **model**: a builder returning anything with ``.predict(x)``
  (an ``InferenceModel``, a zoo ``KerasNet``, or a PR 10 serving
  ``Endpoint`` — the worker unwraps/warms each);
* an **output sink**: a directory of committed ``shard-<id>.npy``
  files whose in-order concatenation IS the scored dataset.

The spec is the single artifact that crosses the coordinator/worker
boundary: the jax-free coordinator partitions and supervises from it,
workers reconstruct source+model from it.  CONTRACT: this module is
stdlib-only and loadable by file path with no package context
(``scripts/zoo-batch report`` and ``obs_report.py --job`` load it that
way, like resilience/chaos.py and observability/aggregator.py).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import struct
import zlib
from typing import Any, Dict, Optional

SPEC_VERSION = 1

#: file names under ``<run_dir>/job/``
JOB_DIR = "job"
JOB_FILE = "job.json"
MANIFEST_FILE = "manifest.json"
REPORT_FILE = "report.json"
LEASE_DIR = "leases"
COMMIT_DIR = "commits"

ENV_BATCH_JOB = "ZOO_TPU_BATCH_JOB"


def job_dir(run_dir: str) -> str:
    return os.path.join(run_dir, JOB_DIR)


@dataclasses.dataclass
class BatchJobSpec:
    """One offline scoring/transform job.

    Args:
        name: job label (rides metric labels and the report).
        source: input binding — ``{"kind": "builder", "ref":
            "module:fn" | "/path.py:fn", "args": {...}}`` or
            ``{"kind": "npy_dir", "path": DIR}``.
        model: model binding — ``{"kind": "builder", "ref": ...,
            "args": {...}}``.
        output_dir: committed output shards land here as
            ``shard-<id>.npy`` (created if absent).
        num_rows: dataset length.  Required for builder sources (the
            jax-free coordinator cannot construct the source to ask);
            derived from the ``x.npy`` header for ``npy_dir``.
        rows_per_shard: partition granularity — also the resume
            granularity bound: a preempted worker loses AT MOST one
            shard of work.
        batch_size: rows per device batch inside a shard.
        lease_timeout_s: a lease not renewed for this long is
            reclaimable — renewal happens every batch, so this is the
            preemption-detection latency at the shard ledger.
        target_deadline_s: the capacity report answers "how many chips
            to finish a dataset like this inside this deadline".
    """

    name: str = "batch-job"
    source: Dict[str, Any] = dataclasses.field(default_factory=dict)
    model: Dict[str, Any] = dataclasses.field(default_factory=dict)
    output_dir: str = ""
    num_rows: Optional[int] = None
    rows_per_shard: int = 1024
    batch_size: int = 128
    lease_timeout_s: float = 30.0
    target_deadline_s: float = 3600.0

    def __post_init__(self):
        self.rows_per_shard = int(self.rows_per_shard)
        self.batch_size = int(self.batch_size)
        if self.rows_per_shard <= 0:
            raise ValueError("rows_per_shard must be positive")
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")

    # ------------------------------------------------------------ geometry
    def resolved_rows(self) -> int:
        """Dataset length, from the spec or (npy_dir) the npy header —
        header-only, so the jax-free coordinator never maps the data."""
        if self.num_rows is not None:
            return int(self.num_rows)
        if self.source.get("kind") == "npy_dir":
            return npy_rows(os.path.join(self.source["path"], "x.npy"))
        raise ValueError(
            "num_rows is required for builder sources (the coordinator "
            "partitions without constructing the source)")

    def num_shards(self) -> int:
        rows = self.resolved_rows()
        return (rows + self.rows_per_shard - 1) // self.rows_per_shard

    def shard_range(self, shard_id: int) -> tuple:
        rows = self.resolved_rows()
        start = shard_id * self.rows_per_shard
        return start, min(start + self.rows_per_shard, rows)

    # --------------------------------------------------------- fingerprint
    def shard_fingerprint(self, shard_id: int) -> str:
        """Content key of one shard's INPUT: the source/model identity
        plus the exact row range.  A commit marker carries this; on
        resume a marker whose fingerprint no longer matches the
        manifest describes a DIFFERENT computation and is recomputed
        instead of trusted."""
        start, end = self.shard_range(shard_id)
        doc = json.dumps({
            "source": self.source, "model": self.model,
            "batch_size": self.batch_size,
            "shard_id": shard_id, "start": start, "end": end,
        }, sort_keys=True)
        return hashlib.sha256(doc.encode()).hexdigest()[:32]

    # -------------------------------------------------------- serialization
    def to_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["version"] = SPEC_VERSION
        return out

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "BatchJobSpec":
        d = dict(d)
        version = int(d.pop("version", SPEC_VERSION))
        if version != SPEC_VERSION:
            raise ValueError(
                f"batch job spec version {version} != {SPEC_VERSION}")
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, raw: str) -> "BatchJobSpec":
        return cls.from_dict(json.loads(raw))

    @classmethod
    def load(cls, run_dir: str) -> "BatchJobSpec":
        with open(os.path.join(job_dir(run_dir), JOB_FILE)) as f:
            return cls.from_dict(json.load(f))


def npy_rows(path: str) -> int:
    """Leading-axis length of a ``.npy`` file from its HEADER alone
    (stdlib: magic + struct + ast.literal_eval) — no numpy import, no
    data mapping, so the coordinator stays jax/numpy-free."""
    with open(path, "rb") as f:
        magic = f.read(6)
        if magic != b"\x93NUMPY":
            raise ValueError(f"{path}: not an npy file")
        major, _minor = f.read(1)[0], f.read(1)[0]
        if major == 1:
            (hlen,) = struct.unpack("<H", f.read(2))
        else:
            (hlen,) = struct.unpack("<I", f.read(4))
        header = ast.literal_eval(f.read(hlen).decode("latin1"))
    shape = header.get("shape", ())
    if not shape:
        raise ValueError(f"{path}: scalar npy has no row axis")
    return int(shape[0])


def input_crc(path: str, max_bytes: int = 1 << 20) -> int:
    """Cheap content check over a file head (crc32) — used by the
    demo/test sources to make fingerprints content-sensitive without
    hashing terabytes."""
    crc = 0
    with open(path, "rb") as f:
        chunk = f.read(max_bytes)
        crc = zlib.crc32(chunk, crc)
    return crc
