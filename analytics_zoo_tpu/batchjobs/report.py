"""Job-end capacity/cost report + shard-progress rendering.

The batch twin of the PR 13 loadgen verdict's capacity section: where
serving answers "replicas needed at a target p99", batch answers
"chips needed at a target deadline".  Built entirely from the job
ledger (manifest + commit markers + live leases) so it can be rendered
offline by ``obs_report.py --job RUN_DIR`` or ``zoo-batch report``
long after the fleet is gone.

Shape (mirrors ``serving.loadgen.verdict.capacity_report``):

* measured throughput → ``rows_per_sec_per_chip`` (the headline
  bench.py's ``batch_scoring`` workload also reports);
* a ``chips_for`` table keyed by deadline seconds — ``ceil(rows /
  (rows_per_sec_per_chip * deadline))`` — the deployment-sizing
  artifact CI archives;
* a ``resume`` block: recomputed rows, duplicate commit races, and
  the resume-overhead fraction the kill-and-resume acceptance bounds
  (< 1 shard of recompute per preemption).

CONTRACT: stdlib-only, loadable by file path (scripts load the
batchjobs modules as a synthetic package without importing jax).
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional

from . import spec as _spec
from .manifest import ShardManifest, read_commits, read_leases

__all__ = ["build_report", "render_report", "render_shard_table",
           "load_report", "render_job_section"]


def _deadline_ladder(target_s: float) -> List[float]:
    """target plus the neighbouring rungs — the "what if the deadline
    halves" question answered in the same artifact."""
    rungs = sorted({target_s * m for m in (0.25, 0.5, 1.0, 2.0, 4.0)})
    return [r for r in rungs if r > 0]


def build_report(run_dir: str, *, num_chips: int,
                 elapsed_s: float, status: str = "complete",
                 restarts: int = 0) -> Dict[str, Any]:
    """Assemble the job report from the ledger and persist it as
    ``<run_dir>/job/report.json``."""
    job = _spec.BatchJobSpec.load(run_dir)
    manifest = ShardManifest.load(run_dir)
    progress = manifest.progress()
    commits = read_commits(run_dir)

    rows = progress["rows_committed"]
    recomputed = progress["rows_recomputed"]
    rows_per_sec = rows / elapsed_s if elapsed_s > 0 else 0.0
    per_chip = rows_per_sec / num_chips if num_chips else 0.0

    per_host: Dict[str, Dict[str, float]] = {}
    for m in commits:
        host = str(m.get("owner", "?")).split(":")[0]
        h = per_host.setdefault(
            host, {"shards": 0, "rows": 0, "seconds": 0.0})
        h["shards"] += 1
        h["rows"] += int(m.get("rows", 0))
        h["seconds"] += float(m.get("seconds", 0.0))

    # straggler: the host whose mean shard time most exceeds the
    # fleet mean (same spirit as observability.straggler_report, but
    # computable from the ledger alone)
    straggler = None
    means = {h: v["seconds"] / v["shards"]
             for h, v in per_host.items() if v["shards"]}
    if len(means) > 1:
        fleet_mean = sum(means.values()) / len(means)
        worst = max(means, key=lambda h: means[h])
        if fleet_mean > 0 and means[worst] > 1.5 * fleet_mean:
            straggler = {"host": worst,
                         "mean_shard_s": round(means[worst], 4),
                         "fleet_mean_shard_s": round(fleet_mean, 4)}

    target = float(job.target_deadline_s)
    chips_for = {}
    if per_chip > 0:
        total_rows = progress["rows_total"]
        for d in _deadline_ladder(target):
            chips_for[f"{d:g}"] = int(
                math.ceil(total_rows / (per_chip * d)))

    report = {
        "job": job.name,
        "status": status,
        "num_chips": int(num_chips),
        "restarts": int(restarts),
        "elapsed_s": round(float(elapsed_s), 4),
        "rows_total": progress["rows_total"],
        "rows_committed": rows,
        "shards_total": progress["shards_total"],
        "shards_committed": progress["shards_committed"],
        "rows_per_sec": round(rows_per_sec, 4),
        "rows_per_sec_per_chip": round(per_chip, 4),
        "target_deadline_s": target,
        # job-level SLO (ISSUE 18): the deadline is the batch plane's
        # objective; "budget remaining" is the unspent fraction of it,
        # the same vocabulary the serving SLO engine publishes
        "slo": {
            "deadline_met": (bool(elapsed_s <= target)
                             if target > 0 else None),
            "deadline_budget_remaining": (
                round(1.0 - float(elapsed_s) / target, 4)
                if target > 0 else None),
        },
        "chips_for": chips_for,
        "resume": {
            "rows_recomputed": recomputed,
            "duplicate_commits": progress["duplicates"],
            "resume_overhead_fraction": round(
                recomputed / rows, 6) if rows else 0.0,
        },
        "per_host": per_host,
        "straggler": straggler,
    }
    out = os.path.join(_spec.job_dir(run_dir), _spec.REPORT_FILE)
    # hand-rolled atomic write: stdlib-only file-path-loadable module,
    # so it cannot import common.fsutil (same carve-out as manifest.py)
    tmp = f"{out}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    os.replace(tmp, out)
    return report


def load_report(run_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(_spec.job_dir(run_dir), _spec.REPORT_FILE)
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


# -------------------------------------------------------------- rendering
def render_shard_table(run_dir: str, max_rows: int = 40) -> str:
    """The shard progress table: one line per shard — committed (by
    whom, how fast), leased (age), or pending."""
    manifest = ShardManifest.load(run_dir)
    committed = manifest.committed()
    leases = {l["shard_id"]: l for l in read_leases(run_dir)}
    lines = [f"{'shard':>6} {'rows':>7}  state"]
    shown = 0
    for s in manifest.shards:
        if shown >= max_rows:
            lines.append(f"  ... {len(manifest.shards) - shown} more")
            break
        sid = s["shard_id"]
        rows = s["end"] - s["start"]
        if sid in committed:
            m = committed[sid]
            extra = ""
            if m.get("recomputed_rows"):
                extra = f" (+{m['recomputed_rows']} recomputed)"
            if m.get("duplicates"):
                extra += f" ({m['duplicates']} dup races)"
            lines.append(
                f"{sid:>6} {rows:>7}  COMMITTED by {m.get('owner', '?')}"
                f" in {m.get('seconds', 0.0):.2f}s{extra}")
        elif sid in leases:
            l = leases[sid]
            lines.append(
                f"{sid:>6} {rows:>7}  leased by {l.get('owner', '?')}"
                f" ({l.get('rows_done', 0)}/{rows} rows)")
        else:
            lines.append(f"{sid:>6} {rows:>7}  pending")
        shown += 1
    return "\n".join(lines)


def render_report(report: Dict[str, Any]) -> str:
    lines = []
    lines.append(f"batch job: {report['job']}  [{report['status']}]")
    lines.append(
        f"  shards {report['shards_committed']}/{report['shards_total']}"
        f"  rows {report['rows_committed']}/{report['rows_total']}"
        f"  elapsed {report['elapsed_s']:.2f}s"
        f"  restarts {report['restarts']}")
    lines.append(
        f"  throughput: {report['rows_per_sec']:.1f} rows/s"
        f" on {report['num_chips']} chip(s)"
        f" = {report['rows_per_sec_per_chip']:.1f} rows/s/chip")
    slo = report.get("slo") or {}
    if slo.get("deadline_met") is not None:
        lines.append(
            f"  job SLO: deadline {report['target_deadline_s']:g}s — "
            + (f"MET with {100 * slo['deadline_budget_remaining']:.0f}%"
               f" budget remaining" if slo["deadline_met"]
               else f"MISSED by "
                    f"{-100 * slo['deadline_budget_remaining']:.0f}%"
                    f" of the deadline"))
    res = report.get("resume", {})
    lines.append(
        f"  resume overhead: {res.get('rows_recomputed', 0)} rows"
        f" recomputed ({100 * res.get('resume_overhead_fraction', 0.0):.2f}%),"
        f" {res.get('duplicate_commits', 0)} duplicate commit race(s)")
    if report.get("chips_for"):
        lines.append(
            f"  capacity at target deadline"
            f" {report['target_deadline_s']:g}s:")
        for d in sorted(report["chips_for"], key=float):
            mark = " <- target" if float(d) == float(
                report["target_deadline_s"]) else ""
            lines.append(
                f"    finish in {float(d):>10g}s: "
                f"{report['chips_for'][d]:>4} chip(s){mark}")
    per_host = report.get("per_host") or {}
    if per_host:
        lines.append("  per-host:")
        for h in sorted(per_host):
            v = per_host[h]
            lines.append(
                f"    {h}: {v['shards']} shard(s), {v['rows']} rows,"
                f" {v['seconds']:.2f}s scoring")
    s = report.get("straggler")
    if s:
        lines.append(
            f"  STRAGGLER: {s['host']} mean shard"
            f" {s['mean_shard_s']:.2f}s vs fleet"
            f" {s['fleet_mean_shard_s']:.2f}s")
    return "\n".join(lines)


def render_job_section(run_dir: str) -> str:
    """The ``obs_report.py --job RUN_DIR`` section: progress table +
    (when the job has ended) the capacity/cost report."""
    parts = [f"batch job ledger: {run_dir}", ""]
    parts.append(render_shard_table(run_dir))
    report = load_report(run_dir)
    if report is not None:
        parts.append("")
        parts.append(render_report(report))
    else:
        manifest = ShardManifest.load(run_dir)
        p = manifest.progress()
        parts.append("")
        parts.append(
            f"job still running: {p['shards_committed']}/"
            f"{p['shards_total']} shards committed"
            f" ({p['rows_committed']}/{p['rows_total']} rows)")
    return "\n".join(parts)
