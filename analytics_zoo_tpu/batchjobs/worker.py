"""Batch scoring worker — the jax side of the batchjobs fleet.

Launched per host by the coordinator (``python -m
analytics_zoo_tpu.batchjobs.worker``) with the launcher env contract
(ZOO_TPU_RUN_DIR / PROCESS_ID / METRICS_* / CLOCK_ANCHOR, plus
ZOO_TPU_CHAOS for fault drills).  Each incarnation:

* joins the PR 4 observability plane (``init_worker_observability``)
  and beats the PR 6 heartbeat every batch — the heartbeat is what
  lets the coordinator's detector distinguish "slow" from "dead",
  while the *lease* renewal is what fences the shard ledger;
* rebuilds source + model from the job spec.  Model warm-up happens
  under the PR 8 compile farm automatically: the coordinator exports
  ZOO_TPU_RUN_DIR, so ``engine_jit`` resolves ``<run_dir>/
  compile-cache`` with process 0 writing and replacements/other hosts
  deserializing warm executables instead of recompiling;
* runs the claim→score→commit loop.  The loop carries the same
  exactly-once obligation the serving consumer does (zoolint ACK013,
  now scoped over ``batchjobs/``): every claimed shard is committed,
  released, or the raise propagates out of the loop.

Chaos: every device batch is a ``worker.step`` site trip
(resilience/chaos.py SITE_WORKER_STEP) — the kill-and-resume
acceptance test murders a worker mid-shard here and asserts the
replacement produces bit-identical committed output.
"""

from __future__ import annotations

import logging
import os
import sys
import time

import numpy as np

from .spec import BatchJobSpec
from .manifest import (
    LeaseClient, LeaseLost, shard_output_path)

log = logging.getLogger("analytics_zoo_tpu.batchjobs.worker")

#: how long an idle worker waits before re-polling the ledger when
#: every pending shard is leased by someone else
IDLE_POLL_S = 0.2


# ------------------------------------------------------------- builders
def resolve_ref(ref: str):
    """Resolve ``module:attr`` or ``/path/to/file.py:attr``."""
    mod_part, _, attr = ref.rpartition(":")
    if not mod_part or not attr:
        raise ValueError(f"builder ref {ref!r} is not 'module:attr'")
    if mod_part.endswith(".py") or os.sep in mod_part:
        import importlib.util
        name = "_zoo_batch_builder_" + os.path.basename(mod_part)[:-3]
        spec = importlib.util.spec_from_file_location(name, mod_part)
        module = importlib.util.module_from_spec(spec)
        sys.modules[name] = module
        spec.loader.exec_module(module)
    else:
        import importlib
        module = importlib.import_module(mod_part)
    return getattr(module, attr)


def build_source(job: BatchJobSpec):
    kind = job.source.get("kind")
    if kind == "npy_dir":
        from analytics_zoo_tpu.data.source import NpyDirSource
        return NpyDirSource(job.source["path"])
    if kind == "builder":
        src = resolve_ref(job.source["ref"])(**job.source.get("args", {}))
        from analytics_zoo_tpu.data.source import as_source
        return as_source(src)
    raise ValueError(f"unknown source kind {kind!r}")


def build_model(job: BatchJobSpec):
    """Build and unwrap the model into a ``.predict(x)`` callable
    holder.  Accepts an ``InferenceModel``/``KerasNet`` directly, or a
    PR 10 serving ``Endpoint`` (uses its model + warms its ladder)."""
    obj = resolve_ref(job.model["ref"])(**job.model.get("args", {}))
    if hasattr(obj, "predict"):
        return obj
    inner = getattr(obj, "model", None)
    if inner is not None and hasattr(inner, "predict"):
        return inner
    raise TypeError(
        f"model builder {job.model.get('ref')} returned "
        f"{type(obj).__name__} with no .predict")


def _rows_only(gathered):
    """A Source's ``gather`` mirrors its item structure —
    ``ArraySource``/``NpyDirSource`` return ``(x, y_or_None)``; batch
    scoring consumes the features."""
    if isinstance(gathered, tuple) and len(gathered) == 2:
        return gathered[0]
    return gathered


class BatchWorker:
    """One incarnation's claim→score→commit loop over the ledger."""

    def __init__(self, job: BatchJobSpec, run_dir: str, *,
                 process_id: int = 0, source=None, model=None,
                 heartbeat=None, chaos=None):
        self.job = job
        self.run_dir = run_dir
        self.process_id = process_id
        self.source = source if source is not None else build_source(job)
        self.model = model if model is not None else build_model(job)
        self.heartbeat = heartbeat
        self.chaos = chaos
        self._lease = LeaseClient(
            run_dir, owner=f"host-{process_id}:{os.getpid()}")
        self.step = 0               # global batch counter (chaos site)
        self.shards_done = 0
        self.rows_done = 0

        from analytics_zoo_tpu.observability import get_registry
        reg = get_registry()
        self._m_rows = reg.counter(
            "batch_rows_total", "rows scored and committed",
            labels=("job",))
        self._m_shard_s = reg.histogram(
            "batch_shard_seconds", "wall seconds per committed shard",
            labels=("job",))
        self._m_shards = reg.counter(
            "batch_shards_committed_total", "output shards committed",
            labels=("job",))
        self._m_recomputed = reg.counter(
            "batch_rows_recomputed_total",
            "rows recomputed after a lease steal (resume overhead)",
            labels=("job",))
        self._m_dup = reg.counter(
            "batch_duplicate_commits_total",
            "commit races lost to an already-present marker",
            labels=("job",))
        self._m_lost = reg.counter(
            "batch_lease_lost_total",
            "shards abandoned because the lease was stolen mid-score",
            labels=("job",))

    # ------------------------------------------------------------ scoring
    def _score_shard(self, shard_id: int, shard: dict) -> np.ndarray:
        """Score one shard's row range batch-by-batch.  Deterministic
        by construction: fixed row order, fixed batch size, no RNG —
        so ANY incarnation produces the same bytes for a shard."""
        start, end = int(shard["start"]), int(shard["end"])
        bs = self.job.batch_size
        outs = []
        rows_done = 0
        for lo in range(start, end, bs):
            hi = min(lo + bs, end)
            if self.chaos is not None:
                # the acceptance test's murder site: a "kill" fault
                # here dies between renewals, mid-shard
                self.chaos.trip("worker.step", self.step)
            x = _rows_only(self.source.gather(np.arange(lo, hi)))
            y = self.model.predict(x)
            outs.append(np.asarray(y))
            rows_done += hi - lo
            self.step += 1
            self._lease.renew(shard_id, rows_done=rows_done)
            if self.heartbeat is not None:
                self.heartbeat.beat(self.step)
        return np.concatenate(outs, axis=0) if outs else np.zeros((0,))

    def _commit_shard(self, shard_id: int, shard: dict) -> None:
        """Score + atomically publish one claimed shard.  Output goes
        write-then-rename BEFORE the exactly-once marker: a crash
        between the two recomputes to identical bytes, so the rename
        replay is content-neutral."""
        t0 = time.perf_counter()
        result = self._score_shard(shard_id, shard)
        out_path = shard_output_path(self.job.output_dir, shard_id)
        # hand-rolled (not common.fsutil): np.save STREAMS the array
        # into the tmp file — a bytes-twin call would buffer the whole
        # shard in memory — and the commit protocol needs the fsync
        # ordered before the rename
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.save(f, result)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, out_path)
        rows = int(shard["end"]) - int(shard["start"])
        recomputed = self._lease._stolen_rows.get(shard_id, 0)
        created = self._lease.commit_shard(
            shard_id, fingerprint=shard["fingerprint"], rows=rows,
            seconds=time.perf_counter() - t0)
        job = self.job.name
        if created:
            self._m_rows.labels(job).inc(rows)
            self._m_shards.labels(job).inc()
            self._m_shard_s.labels(job).observe(time.perf_counter() - t0)
            if recomputed:
                self._m_recomputed.labels(job).inc(recomputed)
            self.shards_done += 1
            self.rows_done += rows
        else:
            self._m_dup.labels(job).inc()

    # --------------------------------------------------------------- loop
    def run(self) -> dict:
        """Drain the ledger: claim, score, commit, repeat until every
        shard in the manifest is committed."""
        while True:
            shards = self._lease.claim_shards(limit=1)
            if not shards:
                progress = self._lease.manifest.progress()
                if progress["complete"]:
                    break
                # everything pending is validly leased elsewhere —
                # poll; an expired lease becomes claimable above
                if self.heartbeat is not None:
                    self.heartbeat.beat(self.step, force=True)
                time.sleep(IDLE_POLL_S)
                continue
            for shard_id, shard in shards:
                try:
                    self._commit_shard(shard_id, shard)
                except LeaseLost:
                    # stolen mid-score: the thief owns the obligation
                    # now; drop ours and move on
                    self._m_lost.labels(self.job.name).inc()
                    self._lease.release_shard(shard_id)
                except BaseException:
                    self._lease.release_shard(shard_id)
                    raise
        return {"shards": self.shards_done, "rows": self.rows_done,
                "steps": self.step}


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    pid = int(os.environ.get("ZOO_TPU_PROCESS_ID", "0"))
    run_dir = os.environ.get("ZOO_TPU_BATCH_JOB") \
        or os.environ.get("ZOO_TPU_RUN_DIR")
    if not run_dir:
        print("batch worker: ZOO_TPU_BATCH_JOB / ZOO_TPU_RUN_DIR not set",
              file=sys.stderr)
        return 2

    from analytics_zoo_tpu.observability import (
        flush_worker_observability, init_worker_observability)
    from analytics_zoo_tpu.resilience.chaos import active_chaos
    from analytics_zoo_tpu.resilience.detector import HostHeartbeat

    init_worker_observability(process_index=pid)
    job = BatchJobSpec.load(run_dir)
    heartbeat = HostHeartbeat.from_env()
    chaos = active_chaos()

    model = build_model(job)
    worker = BatchWorker(job, run_dir, process_id=pid, model=model,
                         heartbeat=heartbeat, chaos=chaos)
    # best-effort AOT warm through the compile farm (PR 8): with
    # ZOO_TPU_RUN_DIR set the executable cache lives in the run dir,
    # process 0 writes, replacements deserialize warm
    warm = getattr(model, "warm", None)
    if callable(warm):
        try:
            probe = _rows_only(worker.source.gather(np.arange(
                0, min(job.batch_size, len(worker.source)))))
            warm(probe.shape[1:], job.batch_size, dtype=probe.dtype)
        except Exception:
            log.info("model warm() probe skipped", exc_info=True)

    summary = worker.run()
    flush_worker_observability()
    log.info("batch worker %d done: %s", pid, summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
