"""Data sources — random-access record stores feeding the pipeline.

Reference: the FeatureSet/DataSet backends (zoo/feature/FeatureSet.scala
partition caches; pyzoo tf_dataset.py factory matrix).  A ``Source`` is
the TPU-native analogue of Grain's ``RandomAccessDataSource``: a finite,
indexable store whose row order NEVER changes, so a (seed, epoch, step)
triple fully determines every batch — the property the checkpointable
:class:`~analytics_zoo_tpu.data.pipeline.DataPipeline` is built on.

Contract::

    len(source)          -> number of records
    source[i]            -> one sample pytree (row i)
    source.gather(idx)   -> batched pytree for an int array of rows
                            (columnar sources override with a single
                            vectorised take; the default stacks rows)

Samples are ``(x, y)`` tuples (``y`` may be ``None``) or any pytree a
model's step accepts; ``gather`` must return the same structure with a
leading batch axis on every leaf.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import jax


def _tree_rows(tree) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return len(leaves[0]) if leaves else 0


def _tree_take(tree, idx: np.ndarray):
    from analytics_zoo_tpu import native

    def take(a):
        if isinstance(a, np.ndarray) and a.ndim >= 1:
            return native.gather_rows(a, idx)
        return a[idx]

    return jax.tree_util.tree_map(take, tree)


class Source:
    """Base class / protocol for random-access record stores."""

    def __len__(self) -> int:
        raise NotImplementedError

    def __getitem__(self, i: int):
        raise NotImplementedError

    def gather(self, idx: np.ndarray):
        """Batched row gather — default stacks per-row samples."""
        rows = [self[int(i)] for i in idx]
        return jax.tree_util.tree_map(
            lambda *leaves: np.stack([np.asarray(l) for l in leaves]),
            *rows)


class ArraySource(Source):
    """Columnar in-memory (or memory-mapped) source: ``x``/``y`` are
    numpy pytrees with a shared leading sample axis — a minibatch is one
    zero-copy vectorised take per leaf (``native.gather_rows``)."""

    def __init__(self, x, y=None):
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t) \
            if t is not None else None
        self.x = to_np(x)
        self.y = to_np(y)
        self._n = _tree_rows(self.x)
        if self.y is not None and _tree_rows(self.y) != self._n:
            raise ValueError(
                f"x has {self._n} rows, y has {_tree_rows(self.y)}")

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, i: int):
        take = lambda t: jax.tree_util.tree_map(lambda a: a[i], t)
        return (take(self.x), take(self.y) if self.y is not None else None)

    def gather(self, idx: np.ndarray):
        return (_tree_take(self.x, idx),
                _tree_take(self.y, idx) if self.y is not None else None)

    def nbytes(self) -> int:
        return sum(a.nbytes for a in
                   jax.tree_util.tree_leaves((self.x, self.y)))


class NpyDirSource(ArraySource):
    """``x.npy`` (+ optional ``y.npy``) directory, memory-mapped by
    default so bigger-than-RAM data pages on demand — the PMEM tier of
    the reference's cache hierarchy (FeatureSet.scala:585-662)."""

    def __init__(self, path: str, memory_map: bool = True):
        mmap = "r" if memory_map else None
        x = np.load(os.path.join(path, "x.npy"), mmap_mode=mmap)
        ypath = os.path.join(path, "y.npy")
        y = np.load(ypath, mmap_mode=mmap) if os.path.exists(ypath) \
            else None
        super().__init__(x, y)
        self.path = path


class TFRecordSource(Source):
    """TFRecord-backed source with random access by byte offset.

    One sequential header scan (``index_tfrecord`` — lengths + crc
    checks only, no payload parse) builds a ``(file, offset)`` index;
    ``__getitem__`` then seeks straight to a record, so a shuffled epoch
    costs one seek+read per record instead of a full-file decode pass.

    ``decode`` maps the raw record bytes to a sample; the default
    parses a ``tf.train.Example`` into a feature dict (reusing
    ``feature/tfrecord.py``).
    """

    def __init__(self, paths, decode: Optional[Callable[[bytes], Any]]
                 = None, check_crc: bool = True):
        import glob as _glob
        import threading
        from analytics_zoo_tpu.feature.tfrecord import parse_example
        if isinstance(paths, (str, os.PathLike)):
            paths = sorted(_glob.glob(str(paths))) or [str(paths)]
        self.paths: List[str] = [str(p) for p in paths]
        self.decode = decode if decode is not None else parse_example
        self.check_crc = check_crc
        from analytics_zoo_tpu.feature.tfrecord import index_tfrecord
        self._index: List[tuple] = []   # (path_idx, offset, length)
        for pi, p in enumerate(self.paths):
            for off, length in index_tfrecord(p, check_crc=check_crc):
                self._index.append((pi, off, length))
        # handles are PER THREAD: reads are seek+read on a shared
        # position, so one handle used from the WorkerPool's threads
        # would interleave seeks and hand records across offsets
        self._local = threading.local()
        self._all_handles: List[Any] = []
        self._handles_lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._index)

    def _file(self, pi: int):
        handles: Dict[int, Any] = getattr(self._local, "handles", None)
        if handles is None:
            handles = self._local.handles = {}
        f = handles.get(pi)
        if f is None or f.closed:
            f = open(self.paths[pi], "rb")
            handles[pi] = f
            with self._handles_lock:
                self._all_handles.append(f)
        return f

    def read_record(self, i: int) -> bytes:
        from analytics_zoo_tpu.feature.tfrecord import read_record_at
        pi, off, _length = self._index[i]
        return read_record_at(self._file(pi), off,
                              check_crc=self.check_crc,
                              path=self.paths[pi])

    def __getitem__(self, i: int):
        return self.decode(self.read_record(i))

    def close(self) -> None:
        with self._handles_lock:
            handles, self._all_handles = self._all_handles, []
        for f in handles:
            try:
                f.close()
            except OSError:
                pass

    def __del__(self):  # best-effort handle cleanup
        self.close()


def as_source(data, y=None) -> Source:
    """Coerce ndarrays / pytrees / an existing Source into a Source."""
    if isinstance(data, Source):
        return data
    return ArraySource(data, y)
