"""DeviceLoader — double-buffered host→device feeding.

Reference: the prefetch queue bolted onto ``DistributedTrainer``
(``Trainer.prefetch``, the MTSampleToMiniBatch analogue), promoted to a
first-class pipeline component: a background thread pulls host batches
from a :class:`DataPipeline` (a PURE read — no position movement),
places them on device (``put_fn`` — ``DistributedTrainer.put_batch``
when training on a mesh, sharded ``jax.device_put`` otherwise) and
keeps ``depth`` batches in flight, so H2D transfer overlaps device
compute.  The loader feeds the existing
``train_prefetch_queue_depth`` gauge (PR 1) and commits the pipeline
position ONLY as batches are handed to the caller — the property that
makes a mid-epoch checkpoint exact even with batches in flight.
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Optional

import jax

from analytics_zoo_tpu.data.pipeline import DataPipeline
from analytics_zoo_tpu.data.stages import PrefetchIterator
from analytics_zoo_tpu.observability import get_registry
from analytics_zoo_tpu.observability.diagnostics import (
    step_attribution_histogram)
from analytics_zoo_tpu.resilience.chaos import (
    SITE_DATA_BATCH, active_chaos)


def _default_put(batch):
    """Sharded single-host placement: shard on the data axis of the
    current mesh when one exists, else plain device_put."""
    try:
        from analytics_zoo_tpu.common.zoo_context import get_zoo_context
        from analytics_zoo_tpu.parallel import mesh as mesh_lib
        mesh = get_zoo_context().mesh
    except Exception:
        return jax.device_put(batch)
    import numpy as np

    dp = mesh.shape[mesh_lib.DATA_AXIS] * mesh.shape[mesh_lib.FSDP_AXIS]

    def put(a):
        if a is None:
            return None
        if np.ndim(a) == 0 or np.shape(a)[0] % dp != 0:
            return jax.device_put(a, mesh_lib.replicated(mesh))
        return jax.device_put(
            a, mesh_lib.data_sharding(mesh, np.ndim(a)))

    return jax.tree_util.tree_map(put, batch,
                                  is_leaf=lambda v: v is None)


class DeviceLoader:
    """Iterate a pipeline's epochs as DEVICE-resident batches.

    ``depth=2`` is classic double buffering: batch ``k+1`` transfers
    while batch ``k`` computes.  Deeper helps only when host batch
    assembly is burstier than the step time.
    """

    def __init__(self, pipeline: DataPipeline,
                 put_fn: Optional[Callable] = None,
                 depth: Optional[int] = None):
        if depth is None:
            from analytics_zoo_tpu.common.config import get_config
            depth = int(get_config().get("data.prefetch"))
        self.pipeline = pipeline
        self.put_fn = put_fn if put_fn is not None else _default_put
        self.depth = max(int(depth), 0)
        self._m_depth = get_registry().gauge(
            "train_prefetch_queue_depth",
            "device-placed batches waiting in the prefetch queue")
        # step-time attribution: the loader is the training loop's
        # data_wait producer on the DataPipeline path
        self._m_wait = step_attribution_histogram().labels("data_wait")

    def epoch(self) -> Iterator[Any]:
        """Yield device batches for the pipeline's current epoch from
        its current step; the pipeline position commits per yielded
        batch (exact-resume contract) and rolls to the next epoch at
        the end."""
        pipe = self.pipeline
        epoch, start = pipe.epoch, pipe.step

        def place(pair):
            step, batch = pair
            return step, self.put_fn(batch)

        if self.depth <= 0:   # synchronous fallback
            placed: Iterator = map(place, pipe.iter_epoch(epoch, start))
        else:
            placed = PrefetchIterator(
                pipe.iter_epoch(epoch, start), self.depth, fn=place,
                on_depth=self._m_depth.set)
        import time
        t0 = time.perf_counter()
        chaos = active_chaos()
        try:
            for step, batch in placed:
                if chaos is not None:
                    # fault-injection site, keyed on the pipeline's
                    # epoch step index, tripped BEFORE the position
                    # commits: an injected input-side failure never
                    # skips the batch it interrupted
                    chaos.trip(SITE_DATA_BATCH, step)
                # feed the pipeline's own batch counter / wait
                # histogram — device-fed consumption is still pipeline
                # consumption — plus the step-attribution data_wait
                # component the diagnostics report reads
                wait = time.perf_counter() - t0
                pipe._m["wait"].observe(wait)
                self._m_wait.observe(wait)
                pipe._m["batches"].inc()
                pipe.commit(epoch, step + 1)
                yield batch
                t0 = time.perf_counter()
        finally:
            # a consumer stopping mid-epoch (end trigger, retry
            # restore, exception) must release the prefetch thread and
            # the device batches it buffered — without this they stay
            # pinned in HBM for the life of the process
            if isinstance(placed, PrefetchIterator):
                placed.close()

    def __iter__(self) -> Iterator[Any]:
        return self.epoch()
