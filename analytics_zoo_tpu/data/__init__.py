"""``analytics_zoo_tpu.data`` — the deterministic, checkpointable,
sharded input-pipeline engine (docs/data.md).

Layers, bottom-up::

    Source        random-access records (ArraySource / NpyDirSource /
                  TFRecordSource)
    IndexSampler  pure (seed, epoch, step) -> per-shard batch indices
    Stage         composable host batch transforms (+ WorkerPool)
    DataPipeline  source + sampler + stages + an explicit, checkpoint-
                  able (epoch, step) position
    DeviceLoader  double-buffered H2D placement feeding the trainer

Quick use::

    from analytics_zoo_tpu.data import DataPipeline

    pipe = DataPipeline(x, y, batch_size=128, seed=7).map(normalize)
    est.train(pipe, "mse", end_trigger=MaxEpoch(5))   # resumable

A checkpointed training run restores mid-epoch on the exact next batch
(``pipe.state_dict()`` rides inside the Estimator snapshot).
"""

from analytics_zoo_tpu.data.source import (
    ArraySource,
    NpyDirSource,
    Source,
    TFRecordSource,
    as_source,
)
from analytics_zoo_tpu.data.sampler import IndexSampler
from analytics_zoo_tpu.data.stages import (
    BatchStage,
    MapStage,
    PrefetchIterator,
    Stage,
    TransformStage,
    WorkerPool,
    pad_to_batch,
    run_stages,
)
from analytics_zoo_tpu.data.pipeline import DataPipeline
from analytics_zoo_tpu.data.device_loader import DeviceLoader
from analytics_zoo_tpu.data.adapters import (
    as_data_pipeline,
    from_feature_set,
)

__all__ = [
    "ArraySource",
    "NpyDirSource",
    "Source",
    "TFRecordSource",
    "as_source",
    "IndexSampler",
    "BatchStage",
    "MapStage",
    "PrefetchIterator",
    "Stage",
    "TransformStage",
    "WorkerPool",
    "pad_to_batch",
    "run_stages",
    "DataPipeline",
    "DeviceLoader",
    "as_data_pipeline",
    "from_feature_set",
]
