"""IndexSampler — the deterministic heart of the pipeline.

Reference: the per-partition index shuffle of
``CachedDistributedFeatureSet`` (FeatureSet.scala:229-329), rebuilt the
way Grain's ``IndexSampler`` does it: every host derives the SAME
global permutation from ``(seed, epoch)``, then takes only its own
shard of every batch.  Because the map ``(seed, epoch, step) ->
record indices`` is a pure function, the sampler needs no mutable
iterator state at all — a resumed run simply asks for step ``k+1``.

Sharding layout: global batch ``b`` is the contiguous permutation slice
``perm[b*G : (b+1)*G]`` (``G`` = batch_size x shard_count) and shard
``h`` owns rows ``[h*B : (h+1)*B]`` of it.  This matches the multi-host
placement convention of ``DistributedTrainer.put_batch`` (each
process's rows are one contiguous slice of the global batch, in process
order), so concatenating every shard's batch ``b`` reproduces the
single-host stream bit-for-bit — the cross-shard-count determinism
contract tier-1 asserts.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np


class IndexSampler:
    """Deterministic, sharded, batched index generator.

    Args:
        num_records: size of the underlying source.
        batch_size: PER-SHARD batch size (rows this host consumes per
            step) — the same convention as ``Estimator.train``.
        shuffle: deterministic per-epoch shuffle when True, source
            order when False.
        seed: permutation seed (default: ``data.shuffle_seed`` config).
        shard_index / shard_count: this host's shard (defaults:
            ``jax.process_index()`` / ``jax.process_count()``).
        remainder: ``"drop"`` discards the trailing rows that cannot
            fill a whole global batch (training — the global batch must
            tile the mesh); ``"pad"`` emits a final short batch padded
            by repeating index 0, with a mask marking real rows (eval).
    """

    def __init__(self, num_records: int, batch_size: int, *,
                 shuffle: bool = True, seed: Optional[int] = None,
                 shard_index: Optional[int] = None,
                 shard_count: Optional[int] = None,
                 remainder: str = "drop"):
        if remainder not in ("drop", "pad"):
            raise ValueError(
                f"remainder {remainder!r}: expected 'drop'|'pad'")
        if shard_count is None or shard_index is None:
            import jax
            shard_count = jax.process_count() if shard_count is None \
                else shard_count
            shard_index = jax.process_index() if shard_index is None \
                else shard_index
        if not 0 <= shard_index < shard_count:
            raise ValueError(
                f"shard_index {shard_index} out of range for "
                f"shard_count {shard_count}")
        if seed is None:
            from analytics_zoo_tpu.common.config import get_config
            seed = int(get_config().get("data.shuffle_seed"))
        self.num_records = int(num_records)
        self.batch_size = int(batch_size)
        self.shuffle = bool(shuffle)
        self.seed = int(seed)
        self.shard_index = int(shard_index)
        self.shard_count = int(shard_count)
        self.remainder = remainder
        self.global_batch = self.batch_size * self.shard_count
        if self.num_records < self.global_batch and remainder == "drop":
            raise ValueError(
                f"{self.num_records} records cannot fill one global "
                f"batch of {self.global_batch} "
                f"({self.batch_size} x {self.shard_count} shards)")

    # ------------------------------------------------------------ geometry
    @property
    def num_batches(self) -> int:
        """Per-epoch steps every shard takes (identical across shards —
        SPMD programs must stay in step)."""
        if self.remainder == "drop":
            return self.num_records // self.global_batch
        return -(-self.num_records // self.global_batch)

    def epoch_perm(self, epoch: int) -> np.ndarray:
        """The GLOBAL record permutation for one epoch — same on every
        shard (same multiplier idiom as ``FeatureSet._epoch_perm`` so
        the two layers' epoch streams stay independently seeded but
        equally reproducible)."""
        if not self.shuffle:
            return np.arange(self.num_records)
        rng = np.random.default_rng(self.seed * 1_000_003 + epoch)
        return rng.permutation(self.num_records)

    # ------------------------------------------------------------- indexing
    def _slice_step(self, perm: np.ndarray, step: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
        """This shard's indices + real-row mask for one step of an
        epoch permutation — the ONE place the shard slice bounds and
        tail padding live (batch_indices and iter_epoch must never
        diverge: one is the resume primitive, the other the stream)."""
        g0 = step * self.global_batch
        lo = g0 + self.shard_index * self.batch_size
        hi = lo + self.batch_size
        sel = perm[lo:min(hi, self.num_records)]
        mask = np.ones(len(sel), np.float32)
        if len(sel) < self.batch_size:   # "pad" tail batch
            pad = self.batch_size - len(sel)
            sel = np.concatenate([sel, np.zeros(pad, sel.dtype)])
            mask = np.concatenate([mask, np.zeros(pad, np.float32)])
        return sel, mask

    def batch_indices(self, epoch: int, step: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
        """Record indices + real-row mask for this shard's batch at
        ``(epoch, step)`` — a pure function, the resume primitive."""
        nb = self.num_batches
        if not 0 <= step < nb:
            raise IndexError(
                f"step {step} out of range for epoch of {nb} batches")
        return self._slice_step(self.epoch_perm(epoch), step)

    def iter_epoch(self, epoch: int, start_step: int = 0
                   ) -> Iterator[Tuple[int, np.ndarray, np.ndarray]]:
        """Yield ``(step, indices, mask)`` from ``start_step`` to the
        end of ``epoch``.  The permutation is computed once and sliced
        per step (not re-derived per batch)."""
        nb = self.num_batches
        if start_step >= nb:
            return
        perm = self.epoch_perm(epoch)
        for step in range(start_step, nb):
            sel, mask = self._slice_step(perm, step)
            yield step, sel, mask
