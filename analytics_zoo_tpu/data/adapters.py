"""Legacy-FeatureSet → DataPipeline shims.

``Estimator.train`` / ``LocalEstimator.fit`` / ``KerasNet.fit`` accept
either layer; these helpers are the one place the two meet, so the
migration path (docs/data.md) is a one-line change per call site.
"""

from __future__ import annotations

from typing import Optional

from analytics_zoo_tpu.data.pipeline import DataPipeline
from analytics_zoo_tpu.data.source import ArraySource


def from_feature_set(feature_set, batch_size: int, *,
                     remainder: str = "drop",
                     shard_index: Optional[int] = None,
                     shard_count: Optional[int] = None,
                     num_workers: int = 0,
                     name: str = "train") -> DataPipeline:
    """Wrap an eager ``FeatureSet`` in a ``DataPipeline``.

    The pipeline reuses the FeatureSet's columnar arrays zero-copy and
    its ``shuffle``/``seed`` settings, but note the STREAMS DIFFER: the
    pipeline shards per host and its sampler draws an independent
    permutation, so this is a migration adapter, not a bit-exact
    re-encoding of ``FeatureSet.epoch_batches``.
    """
    return DataPipeline(
        ArraySource(feature_set.x, feature_set.y),
        batch_size=batch_size, shuffle=feature_set.shuffle,
        seed=feature_set.seed, remainder=remainder,
        shard_index=shard_index, shard_count=shard_count,
        num_workers=num_workers, name=name)


def as_data_pipeline(data, y=None, batch_size: int = 32,
                     **kwargs) -> DataPipeline:
    """Coerce a DataPipeline / FeatureSet / ndarray pytree into a
    DataPipeline (pass-through for an existing pipeline)."""
    if isinstance(data, DataPipeline):
        return data
    from analytics_zoo_tpu.feature.feature_set import FeatureSet
    if isinstance(data, FeatureSet):
        return from_feature_set(data, batch_size, **kwargs)
    return DataPipeline(data, y, batch_size=batch_size, **kwargs)
