"""DataPipeline — deterministic, checkpointable, sharded input engine.

Reference: the FeatureSet/DataSet layer feeding the distributed
optimizer (SURVEY L1/L2), rebuilt Grain-style: a random-access
:class:`~analytics_zoo_tpu.data.source.Source`, a pure-function
:class:`~analytics_zoo_tpu.data.sampler.IndexSampler`, composable host
stages, and an explicit ``(epoch, step)`` POSITION that
``state_dict()``/``load_state_dict()`` checkpoint — so a restored run
resumes on the exact next batch instead of replaying the epoch.

Determinism contract:

* same ``(source order, seed)`` => identical batch stream, across runs
  and across processes;
* shard ``h`` of ``S`` sees rows ``[h*B:(h+1)*B]`` of every global
  batch — concatenating all shards' step-``k`` batches reproduces the
  unsharded step-``k`` batch exactly;
* the position advances ONLY when a batch is handed to the consumer
  (``__iter__`` / ``DeviceLoader``), never when a worker merely built
  it ahead — so a checkpoint taken between steps is exact even with
  prefetch in flight.

The position is intentionally NOT buried in a live iterator:
``iter_epoch`` is a pure read (resumable from any ``(epoch, step)``),
``commit`` moves the position, and the consuming loop decides when.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.sampler import IndexSampler
from analytics_zoo_tpu.data.source import Source, as_source
from analytics_zoo_tpu.data.stages import (
    MapStage, Stage, TransformStage, WorkerPool, run_stages)
from analytics_zoo_tpu.observability import get_registry

STATE_VERSION = 1


def _pipeline_metrics(name: str):
    reg = get_registry()
    return {
        "batches": reg.counter(
            "data_batches_total",
            "host batches produced by the data pipeline",
            labels=("pipeline",)).labels(name),
        "wait": reg.histogram(
            "data_batch_wait_seconds",
            "consumer wait for the next host batch (0 ≈ the workers "
            "are keeping up)", labels=("pipeline",)).labels(name),
        "qdepth": reg.gauge(
            "data_worker_queue_depth",
            "batches built ahead by the pipeline worker pool",
            labels=("pipeline",)).labels(name),
    }


class DataPipeline:
    """Deterministic sharded batch pipeline over a random-access source.

    Args:
        source: a :class:`Source`, or arrays/pytrees (coerced via
            :class:`ArraySource`; pass ``y=...`` for labels).
        batch_size: rows PER SHARD per step.
        shuffle / seed: deterministic per-epoch shuffling.
        shard_index / shard_count: this host's shard — defaults to
            ``jax.process_index()/process_count()`` so the same script
            shards itself per host.
        remainder: ``"drop"`` (training) or ``"pad"`` (a masked short
            tail batch; the mask is appended to the batch tuple).
        stages: host-side :class:`Stage` chain applied to each batch.
        num_workers: >0 builds batches in a thread pool, ``num_workers``
            wide, pulling ahead of the consumer (ordered — parallelism
            never reorders the stream).
    """

    def __init__(self, source, y=None, *, batch_size: int = 32,
                 shuffle: bool = True, seed: Optional[int] = None,
                 shard_index: Optional[int] = None,
                 shard_count: Optional[int] = None,
                 remainder: str = "drop",
                 stages: Sequence[Stage] = (),
                 num_workers: int = 0,
                 name: str = "train"):
        self.source: Source = as_source(source, y)
        self.sampler = IndexSampler(
            len(self.source), batch_size, shuffle=shuffle, seed=seed,
            shard_index=shard_index, shard_count=shard_count,
            remainder=remainder)
        self.stages = list(stages)
        self.num_workers = int(num_workers)
        self.name = name
        self._epoch = 0
        self._step = 0   # next batch to hand out
        self._pool: Optional[WorkerPool] = None
        self._m = _pipeline_metrics(name)

    # ------------------------------------------------------------ geometry
    @property
    def batch_size(self) -> int:
        return self.sampler.batch_size

    @property
    def num_batches(self) -> int:
        """Steps per epoch (identical on every shard)."""
        return self.sampler.num_batches

    @property
    def size(self) -> int:
        """Records in the underlying source (pre-shard)."""
        return len(self.source)

    @property
    def seed(self) -> int:
        return self.sampler.seed

    @property
    def shuffle(self) -> bool:
        return self.sampler.shuffle

    # ----------------------------------------------------------- builders
    def _derive(self, extra_stage: Stage) -> "DataPipeline":
        return DataPipeline(
            self.source, batch_size=self.sampler.batch_size,
            shuffle=self.sampler.shuffle, seed=self.sampler.seed,
            shard_index=self.sampler.shard_index,
            shard_count=self.sampler.shard_count,
            remainder=self.sampler.remainder,
            stages=self.stages + [extra_stage],
            num_workers=self.num_workers, name=self.name)

    def map(self, fn: Callable, per_leaf: bool = False) -> "DataPipeline":
        """Append a batch-level map stage (``fn(batch) -> batch``)."""
        return self._derive(MapStage(fn, per_leaf=per_leaf))

    def transform(self, preprocessing) -> "DataPipeline":
        """Append a Preprocessing / callable over the X half — the
        ``FeatureSet.transform`` migration hook."""
        return self._derive(TransformStage(preprocessing))

    __rshift__ = transform

    def workers(self, num_workers: int) -> "DataPipeline":
        """Set the stage worker-pool width (chainable)."""
        self.num_workers = int(num_workers)
        return self

    # ------------------------------------------------------- batch assembly
    def _build_batch(self, sel_mask: Tuple[np.ndarray, np.ndarray]):
        sel, mask = sel_mask
        batch = run_stages(self.source.gather(sel), self.stages)
        if self.sampler.remainder == "pad":
            if isinstance(batch, tuple):
                return batch + (mask,)
            return (batch, mask)
        return batch

    def iter_epoch(self, epoch: int, start_step: int = 0
                   ) -> Iterator[Tuple[int, Any]]:
        """Pure read of ``(step, batch)`` pairs for one epoch — does
        NOT move the pipeline position (``commit`` does).  Resumable
        from any step; with ``num_workers`` the batches are assembled
        in the pool, ordered."""
        steps = self.sampler.iter_epoch(epoch, start_step)
        if self.num_workers > 0:
            if self._pool is None:
                self._pool = WorkerPool(self.num_workers,
                                        name=f"data-{self.name}")
            pairs = ((step, (sel, mask)) for step, sel, mask in steps)

            def build(pair):
                step, sel_mask = pair
                return step, self._build_batch(sel_mask)

            yield from self._pool.imap(
                build, pairs, on_depth=self._m["qdepth"].set)
        else:
            for step, sel, mask in steps:
                yield step, self._build_batch((sel, mask))

    # ------------------------------------------------------------ position
    @property
    def epoch(self) -> int:
        return self._epoch

    @property
    def step(self) -> int:
        return self._step

    def commit(self, epoch: int, step: int) -> None:
        """Move the position to ``(epoch, step)`` = the next batch to
        deliver; rolls into the next epoch at epoch end."""
        if step >= self.num_batches:
            epoch, step = epoch + 1, 0
        self._epoch, self._step = int(epoch), int(step)

    # ----------------------------------------------------------- iteration
    def __iter__(self) -> Iterator[Any]:
        """Yield the REMAINING batches of the current epoch, committing
        the position as each batch is handed out; at epoch end the
        position rolls to ``(epoch+1, 0)``.  ``for batch in pipeline:``
        therefore consumes exactly one (rest-of-)epoch per loop."""
        epoch = self._epoch
        t0 = time.perf_counter()
        for step, batch in self.iter_epoch(epoch, self._step):
            self._m["wait"].observe(time.perf_counter() - t0)
            self._m["batches"].inc()
            self.commit(epoch, step + 1)
            yield batch
            t0 = time.perf_counter()

    def __len__(self) -> int:
        return self.num_batches

    # -------------------------------------------------------- checkpointing
    def state_dict(self) -> Dict[str, Any]:
        """Position + the stream-identity fingerprint.  Restoring this
        into a pipeline with the same fingerprint resumes the exact
        batch stream at the exact next batch."""
        s = self.sampler
        return {
            "version": STATE_VERSION,
            "epoch": self._epoch,
            "step": self._step,
            "seed": s.seed,
            "shuffle": s.shuffle,
            "batch_size": s.batch_size,
            "shard_index": s.shard_index,
            "shard_count": s.shard_count,
            "num_records": s.num_records,
        }

    def load_state_dict(self, state: Dict[str, Any],
                        strict: bool = True) -> None:
        """Restore the position.  ``strict`` verifies the fingerprint —
        a checkpoint taken with a different seed/batch/shard geometry
        describes a DIFFERENT batch stream, and resuming it silently
        would skip and replay samples."""
        if int(state.get("version", 0)) != STATE_VERSION:
            raise ValueError(
                f"data pipeline state version "
                f"{state.get('version')!r} != {STATE_VERSION}")
        if strict:
            s = self.sampler
            mine = {"seed": s.seed, "shuffle": s.shuffle,
                    "batch_size": s.batch_size,
                    "shard_count": s.shard_count,
                    "num_records": s.num_records}
            diffs = {k: (state.get(k), v) for k, v in mine.items()
                     if int(state.get(k, v)) != int(v)}
            if diffs:
                raise ValueError(
                    "data pipeline state does not match this pipeline "
                    f"(checkpointed vs current): {diffs}; pass "
                    "strict=False to restore the position anyway")
        self._epoch = int(state["epoch"])
        self._step = int(state["step"])
        if self._step >= self.num_batches:
            self._epoch, self._step = self._epoch + 1, 0

    # ------------------------------------------------------------- cleanup
    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None

    def __enter__(self) -> "DataPipeline":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()
