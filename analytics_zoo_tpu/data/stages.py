"""Composable host-side batch stages + the shared worker pool.

Reference: the MTSampleToMiniBatch worker threads that assemble
minibatches ahead of the training tasks (MTSampleToMiniBatch.scala:28)
and the Preprocessing ``->`` chains (Preprocessing.scala).  A stage is
``batch -> batch`` on HOST pytrees; chains run inside the pipeline's
worker pool, overlapping with device compute.

These primitives are deliberately framework-free so the serving path
reuses them: ``ClusterServing`` runs its JPEG decode through the same
:class:`WorkerPool` / :func:`pad_to_batch` that train pipelines use.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import numpy as np
import jax


class Stage:
    """One host-side batch transformation."""

    name = "stage"

    def __call__(self, batch: Any) -> Any:
        raise NotImplementedError


class MapStage(Stage):
    """Apply ``fn`` to the whole batch pytree (``fn(batch) -> batch``);
    with ``per_leaf=True`` apply it leaf-wise instead."""

    def __init__(self, fn: Callable, per_leaf: bool = False,
                 name: str = "map"):
        self.fn = fn
        self.per_leaf = per_leaf
        self.name = name

    def __call__(self, batch):
        if self.per_leaf:
            return jax.tree_util.tree_map(self.fn, batch)
        return self.fn(batch)


class TransformStage(Stage):
    """Run a ``feature.common.Preprocessing`` (or any callable) over
    the X half of an ``(x, y)`` batch — the migration bridge for
    ``FeatureSet.transform`` chains."""

    def __init__(self, preprocessing, name: str = "transform"):
        from analytics_zoo_tpu.feature.common import Preprocessing
        self.fn = preprocessing.apply \
            if isinstance(preprocessing, Preprocessing) else preprocessing
        self.name = name

    def __call__(self, batch):
        if isinstance(batch, tuple) and len(batch) == 2:
            x, y = batch
            return (self.fn(x), y)
        return self.fn(batch)


class BatchStage(Stage):
    """Collate a SEQUENCE of per-record samples into one batched
    pytree (stacked leaves) — used by record-at-a-time sources
    (TFRecord) whose ``gather`` has no columnar fast path."""

    name = "batch"

    def __call__(self, samples: Sequence[Any]):
        return jax.tree_util.tree_map(
            lambda *leaves: np.stack([np.asarray(l) for l in leaves]),
            *samples)


def run_stages(batch: Any, stages: Sequence[Stage]) -> Any:
    for s in stages:
        batch = s(batch)
    return batch


def pad_to_batch(arr: np.ndarray, batch_size: int) -> np.ndarray:
    """Zero-pad rows up to ``batch_size`` so one compiled program
    serves every (possibly short) batch — shared by the serving
    batcher and the pipeline's pad-remainder mode."""
    real = len(arr)
    if real >= batch_size:
        return arr
    return np.concatenate(
        [arr, np.zeros((batch_size - real,) + arr.shape[1:], arr.dtype)])


class WorkerPool:
    """A small named thread pool with an ORDERED pull-ahead map — the
    multi-threaded stage engine (host stages release the GIL inside
    numpy/cv2, so threads genuinely overlap; process isolation is not
    worth the pickling for columnar batches).

    ``imap(fn, it, depth)`` keeps up to ``depth`` items in flight and
    yields results strictly in input order — exactly the contract a
    deterministic pipeline needs (parallelism must never reorder the
    batch stream) and the one the serving loop needs (results ack in
    stream order).
    """

    def __init__(self, workers: int = 2, name: str = "data-worker"):
        self.workers = max(int(workers), 1)
        self._pool = ThreadPoolExecutor(self.workers,
                                        thread_name_prefix=name)
        self._closed = False

    def submit(self, fn: Callable, *args) -> Future:
        return self._pool.submit(fn, *args)

    def imap(self, fn: Callable, items: Iterable, depth: Optional[int]
             = None, on_depth: Optional[Callable[[int], None]] = None
             ) -> Iterator:
        """Ordered parallel map: results come back in input order with
        at most ``depth`` (default ``2 x workers``) in flight.
        ``on_depth`` (if given) observes the in-flight count before
        each result is handed out — the worker-queue-depth gauge."""
        if depth is None:
            depth = 2 * self.workers
        depth = max(int(depth), 1)
        from collections import deque
        inflight: deque = deque()
        it = iter(items)
        try:
            while True:
                while len(inflight) < depth:
                    try:
                        item = next(it)
                    except StopIteration:
                        break
                    inflight.append(self._pool.submit(fn, item))
                if not inflight:
                    if on_depth is not None:
                        on_depth(0)
                    return
                if on_depth is not None:
                    on_depth(len(inflight))
                yield inflight.popleft().result()
        finally:
            for f in inflight:
                f.cancel()

    def shutdown(self, wait: bool = False) -> None:
        # no closed-guard: Executor.shutdown is itself thread-safe and
        # idempotent, so a check-then-act here would only add a window
        # where two closers race on the flag
        self._closed = True
        self._pool.shutdown(wait=wait)

    close = shutdown

    def __enter__(self) -> "WorkerPool":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown()


class PrefetchIterator:
    """Background-thread prefetch over any iterator with queue-depth
    and wait-time instrumentation fed by the caller.

    The generic engine under both ``DataPipeline`` host prefetch and
    ``DeviceLoader`` double-buffering: a daemon thread pulls from
    ``source_iter`` (optionally mapping ``fn`` over each item — e.g.
    the H2D placement) into a bounded queue; exceptions propagate to
    the consumer; the consumer stops early by just abandoning the
    iterator (daemon thread + bounded queue => no leak beyond ``depth``
    buffered items).
    """

    _END = object()

    def __init__(self, source_iter: Iterable, depth: int,
                 fn: Optional[Callable] = None,
                 on_depth: Optional[Callable[[int], None]] = None):
        self.depth = max(int(depth), 1)
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._on_depth = on_depth
        self._fn = fn
        self._src = source_iter
        self._abort = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer aborted —
        q.put would otherwise block this thread forever (pinning the
        buffered items, which on the DeviceLoader path are
        device-RESIDENT batches) if the consumer walks away
        mid-epoch."""
        while not self._abort.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for item in self._src:
                if self._fn is not None:
                    item = self._fn(item)
                if not self._put(item):
                    return
            self._put(self._END)
        except BaseException as e:   # propagate into the consumer
            self._put(e)

    def close(self) -> None:
        """Stop the worker and release everything it buffered.  Called
        by the consumer when it stops early (e.g. an end-trigger
        firing mid-epoch); idempotent."""
        self._abort.set()
        while True:   # unblock + drop buffered items
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __iter__(self):
        return self

    def __next__(self):
        # depth sampled BEFORE the dequeue so a full steady-state
        # pipeline reads `depth`, not depth-1 (same convention as
        # trainer.prefetch)
        if self._on_depth is not None:
            self._on_depth(self._q.qsize())
        item = self._q.get()
        if item is self._END:
            if self._on_depth is not None:
                self._on_depth(0)
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item
