"""Analytics-Zoo-TPU: a TPU-native analytics + AI framework.

A ground-up re-design of the capabilities of Analytics Zoo
(reference: louie-tsai/analytics-zoo) for TPU hardware: JAX/XLA is the
compute engine (the role BigDL+MKL played on CPU), ``jax.sharding`` over
a device ``Mesh`` is the distribution fabric (the role Spark's
BlockManager allreduce played), and Pallas provides hand-written kernels
where XLA needs help.

Top-level layout (mirrors the reference's layer map, SURVEY.md §1):

- ``common``    : context init, config layering, triggers
                  (ref: zoo/common/NNContext.scala, ZooTrigger.scala)
- ``parallel``  : mesh/topology, sharding strategies, collectives,
                  the distributed training engine
                  (ref: BigDL DistriOptimizer + AllReduceParameter)
- ``feature``   : FeatureSet input pipeline, image/text pipelines
                  (ref: zoo/feature/FeatureSet.scala, ImageSet, TextSet)
- ``pipeline``  : Keras-style model API, autograd, estimator, inference
                  (ref: zoo/pipeline/api/keras, pipeline/estimator, ...)
- ``models``    : built-in model zoo (NCF, Wide&Deep, AnomalyDetector,
                  TextClassifier, Seq2seq, image models, ...)
- ``ops``       : low-level JAX/Pallas ops shared by layers and models
- ``serving``   : cluster-serving service (Redis streams protocol)
- ``utils``     : summaries (TensorBoard-style), file IO, logging
"""

from analytics_zoo_tpu.version import __version__
from analytics_zoo_tpu.common.zoo_context import (
    init_zoo_context,
    get_zoo_context,
    ZooContext,
)

__all__ = [
    "__version__",
    "init_zoo_context",
    "get_zoo_context",
    "ZooContext",
]
