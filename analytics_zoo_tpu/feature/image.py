"""Image pipeline: ImageSet + per-image transforms.

Reference: zoo/feature/image/ImageSet.scala:46-140 and the transform set
(ImageResize, ImageChannelNormalize, ImageMatToTensor, ImageColorJitter,
ImageSetToSample...) built on OpenCV mats.

TPU design: transforms are host-side numpy/cv2 ops running in the input
pipeline (the executor-side OpenCV role), producing channels-last f32
arrays ready for device infeed.  An ImageSet is a thin container over
file paths or ndarrays; ``transform`` chains Preprocessing stages, and
``to_feature_set`` materialises a columnar FeatureSet for training.
"""

from __future__ import annotations

import glob
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

try:
    import cv2
    _HAS_CV2 = True
except Exception:            # pragma: no cover
    _HAS_CV2 = False

from analytics_zoo_tpu.feature.common import Preprocessing
from analytics_zoo_tpu.feature.feature_set import FeatureSet


def decode_image_bytes(data: bytes, to_rgb: bool = True,
                       context: str = "") -> np.ndarray:
    """Decode one encoded image (JPEG/PNG bytes) to HWC uint8 — the
    per-record decode the reference ran on executors for byte-RDD
    inputs (TFBytesDataset, serving ImageProcessing.scala:24).
    ``context`` names the source (path / record id) in decode errors."""
    what = f"image {context}" if context else "image bytes"
    if _HAS_CV2:
        img = cv2.imdecode(np.frombuffer(data, np.uint8),
                           cv2.IMREAD_COLOR)
        if img is None:
            raise IOError(f"cannot decode {what}")
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB) if to_rgb else img
    import io                        # pragma: no cover
    from PIL import Image
    try:
        rgb = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
    except Exception as e:
        raise IOError(f"cannot decode {what}") from e
    return rgb if to_rgb else rgb[..., ::-1]


def read_image(path: str, to_rgb: bool = True) -> np.ndarray:
    """Decode one image file (local or remote URI) to HWC uint8."""
    from analytics_zoo_tpu.utils import file_io
    if file_io.is_remote(path):
        return decode_image_bytes(file_io.read_bytes(path), to_rgb,
                                  context=path)
    if _HAS_CV2:
        img = cv2.imread(path, cv2.IMREAD_COLOR)
        if img is None:
            raise IOError(f"cannot decode image {path}")
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB) if to_rgb else img
    from PIL import Image            # pragma: no cover
    return np.asarray(Image.open(path).convert("RGB"))


# ------------------------------------------------------------- transforms
class ImageResize(Preprocessing):
    """(ref ImageResize.scala)"""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def apply(self, img: np.ndarray) -> np.ndarray:
        if _HAS_CV2:
            return cv2.resize(img, (self.w, self.h),
                              interpolation=cv2.INTER_LINEAR)
        from PIL import Image        # pragma: no cover
        return np.asarray(Image.fromarray(img).resize((self.w, self.h)))


class ImageCenterCrop(Preprocessing):
    def __init__(self, crop_h: int, crop_w: int):
        self.h, self.w = int(crop_h), int(crop_w)

    def apply(self, img):
        H, W = img.shape[:2]
        top = max((H - self.h) // 2, 0)
        left = max((W - self.w) // 2, 0)
        return img[top:top + self.h, left:left + self.w]


class ImageRandomCrop(Preprocessing):
    def __init__(self, crop_h: int, crop_w: int, seed: int = 0):
        self.h, self.w = int(crop_h), int(crop_w)
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, img):
        H, W = img.shape[:2]
        top = int(self.rng.integers(0, max(H - self.h, 0) + 1))
        left = int(self.rng.integers(0, max(W - self.w, 0) + 1))
        return img[top:top + self.h, left:left + self.w]


class ImageHFlip(Preprocessing):
    def __init__(self, prob: float = 0.5, seed: int = 0):
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, img):
        if self.rng.random() < self.prob:
            return img[:, ::-1]
        return img


class ImageChannelNormalize(Preprocessing):
    """Subtract per-channel mean / divide std (ImageChannelNormalize)."""

    def __init__(self, mean_r, mean_g, mean_b, std_r=1.0, std_g=1.0,
                 std_b=1.0):
        self.mean = np.array([mean_r, mean_g, mean_b], np.float32)
        self.std = np.array([std_r, std_g, std_b], np.float32)

    def apply(self, img):
        return (img.astype(np.float32) - self.mean) / self.std


class ImageBrightness(Preprocessing):
    """Additive brightness jitter (part of ImageColorJitter)."""

    def __init__(self, delta: float = 32.0, seed: int = 0):
        self.delta = delta
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, img):
        shift = self.rng.uniform(-self.delta, self.delta)
        return np.clip(img.astype(np.float32) + shift, 0, 255)


class ImageContrast(Preprocessing):
    """Multiplicative contrast jitter (part of ImageColorJitter)."""

    def __init__(self, lower: float = 0.5, upper: float = 1.5,
                 seed: int = 0):
        self.lower, self.upper = float(lower), float(upper)
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, img):
        alpha = self.rng.uniform(self.lower, self.upper)
        return np.clip(img.astype(np.float32) * alpha, 0, 255)


class ImageSaturation(Preprocessing):
    """Blend with per-pixel grayscale (part of ImageColorJitter)."""

    def __init__(self, lower: float = 0.5, upper: float = 1.5,
                 seed: int = 0):
        self.lower, self.upper = float(lower), float(upper)
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, img):
        alpha = self.rng.uniform(self.lower, self.upper)
        f = img.astype(np.float32)
        gray = f @ np.array([0.299, 0.587, 0.114], np.float32)
        return np.clip(alpha * f + (1 - alpha) * gray[..., None], 0, 255)


class ImageHue(Preprocessing):
    """Hue rotation in HSV space (part of ImageColorJitter)."""

    def __init__(self, delta: float = 18.0, seed: int = 0):
        self.delta = float(delta)
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, img):
        shift = self.rng.uniform(-self.delta, self.delta)
        u8 = np.clip(img, 0, 255).astype(np.uint8)
        if _HAS_CV2:
            hsv = cv2.cvtColor(u8, cv2.COLOR_RGB2HSV)
            h = hsv[..., 0].astype(np.int16)
            hsv[..., 0] = ((h + int(shift / 2)) % 180).astype(np.uint8)
            out = cv2.cvtColor(hsv, cv2.COLOR_HSV2RGB)
        else:                        # pragma: no cover
            from PIL import Image
            hsv = np.asarray(Image.fromarray(u8).convert("HSV"),
                             np.int16)
            hsv[..., 0] = (hsv[..., 0] + int(shift * 255 / 360)) % 256
            out = np.asarray(Image.fromarray(
                hsv.astype(np.uint8), "HSV").convert("RGB"))
        return out.astype(img.dtype if np.issubdtype(
            np.asarray(img).dtype, np.floating) else np.uint8)


class ImageColorJitter(Preprocessing):
    """Random-order brightness/contrast/saturation/hue jitter
    (ref ImageColorJitter.scala — the full photometric distort)."""

    def __init__(self, brightness_delta: float = 32.0,
                 contrast: Tuple[float, float] = (0.5, 1.5),
                 saturation: Tuple[float, float] = (0.5, 1.5),
                 hue_delta: float = 18.0, seed: int = 0):
        self.rng = np.random.default_rng(seed)
        self.stages = [
            ImageBrightness(brightness_delta, seed=seed + 1),
            ImageContrast(*contrast, seed=seed + 2),
            ImageSaturation(*saturation, seed=seed + 3),
            ImageHue(hue_delta, seed=seed + 4),
        ]

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)
        for i, st in enumerate(self.stages):
            if hasattr(st, "reseed"):
                st.reseed(seed + 10 + i)

    def apply(self, img):
        order = self.rng.permutation(len(self.stages))
        out = img
        for i in order:
            out = self.stages[i].apply(out)
        return out


def expand_canvas(img: np.ndarray, rng, max_ratio: float, mean
                  ) -> Tuple[np.ndarray, int, int]:
    """Paste ``img`` at a random offset on a mean-filled canvas up to
    ``max_ratio`` larger; returns (canvas, top, left) so detection
    callers can shift boxes.  Shared by ImageExpand and DetExpand."""
    h, w, c = img.shape
    ratio = float(rng.uniform(1.0, max_ratio))
    H, W = int(h * ratio), int(w * ratio)
    top = int(rng.integers(0, H - h + 1))
    left = int(rng.integers(0, W - w + 1))
    canvas = np.empty((H, W, c), img.dtype)
    canvas[...] = np.asarray(mean, np.float32).astype(img.dtype)
    canvas[top:top + h, left:left + w] = img
    return canvas, top, left


class ImageExpand(Preprocessing):
    """Zoom-out onto a mean-filled canvas (ref ImageExpand.scala)."""

    def __init__(self, max_ratio: float = 4.0, mean=(123, 117, 104),
                 prob: float = 0.5, seed: int = 0):
        self.max_ratio = float(max_ratio)
        self.mean = np.asarray(mean, np.float32)
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, img):
        if self.rng.random() >= self.prob:
            return img
        canvas, _, _ = expand_canvas(img, self.rng, self.max_ratio,
                                     self.mean)
        return canvas


class ImageChannelOrder(Preprocessing):
    """RGB <-> BGR swap (ref ImageChannelOrder / mat channel ops)."""

    def apply(self, img):
        return np.ascontiguousarray(img[..., ::-1])


class ImageMatToTensor(Preprocessing):
    """HWC uint8/float -> float32, optional CHW (ImageMatToTensor)."""

    def __init__(self, format: str = "NHWC"):
        self.format = format

    def apply(self, img):
        arr = img.astype(np.float32)
        if self.format == "NCHW":
            arr = arr.transpose(2, 0, 1)
        return arr


# -------------------------------------------------------------- ImageSet
class ImageSet:
    """Container of images (+ optional labels) with chained transforms.

    ``read`` mirrors ImageSet.read (ImageSet.scala:98): local dir or
    file list; with ``with_label=True``, one sub-dir per class.
    """

    def __init__(self, images: List, labels: Optional[np.ndarray] = None,
                 label_map: Optional[dict] = None):
        self.images = images
        self.labels = labels
        self.label_map = label_map

    @classmethod
    def read(cls, path: str, with_label: bool = False,
             pattern: str = "*.jpg") -> "ImageSet":
        if with_label:
            classes = sorted(
                d for d in os.listdir(path)
                if os.path.isdir(os.path.join(path, d)))
            label_map = {c: i for i, c in enumerate(classes)}
            files, labels = [], []
            for c in classes:
                for f in sorted(glob.glob(os.path.join(path, c, pattern))):
                    files.append(f)
                    labels.append(label_map[c])
            images = [read_image(f) for f in files]
            return cls(images, np.asarray(labels, np.int32), label_map)
        files = sorted(glob.glob(os.path.join(path, pattern)))
        return cls([read_image(f) for f in files])

    @classmethod
    def from_ndarrays(cls, images: np.ndarray,
                      labels: Optional[np.ndarray] = None) -> "ImageSet":
        return cls(list(images),
                   None if labels is None else np.asarray(labels))

    def transform(self, stage: Preprocessing) -> "ImageSet":
        return ImageSet([stage.apply(im) for im in self.images],
                        self.labels, self.label_map)

    __rshift__ = transform

    def to_feature_set(self, shuffle: bool = True) -> FeatureSet:
        x = np.stack(self.images).astype(np.float32)
        y = None if self.labels is None else \
            self.labels.reshape(-1, 1)
        return FeatureSet.from_ndarrays(x, y, shuffle=shuffle)

    def __len__(self):
        return len(self.images)
