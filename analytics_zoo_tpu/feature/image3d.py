"""3D (volumetric / medical) image transforms.

Reference: zoo/feature/image3d/ — Rotation3D (Rotation.scala:133),
Crop3D, AffineTransform3D, with scipy-quality resampling on the host
(the role OpenCV played for 2D).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing


class Crop3D(Preprocessing):
    """Crop a (D, H, W) volume at ``start`` with ``patch_size``."""

    def __init__(self, start: Sequence[int], patch_size: Sequence[int]):
        self.start = tuple(int(v) for v in start)
        self.patch = tuple(int(v) for v in patch_size)

    def apply(self, vol: np.ndarray) -> np.ndarray:
        (z, y, x), (dz, dy, dx) = self.start, self.patch
        return vol[z:z + dz, y:y + dy, x:x + dx]


class CenterCrop3D(Preprocessing):
    def __init__(self, patch_size: Sequence[int]):
        self.patch = tuple(int(v) for v in patch_size)

    def apply(self, vol: np.ndarray) -> np.ndarray:
        start = [(s - p) // 2 for s, p in zip(vol.shape[:3], self.patch)]
        return Crop3D(start, self.patch).apply(vol)


class RandomCrop3D(Preprocessing):
    def __init__(self, patch_size: Sequence[int], seed: int = 0):
        self.patch = tuple(int(v) for v in patch_size)
        self.rng = np.random.default_rng(seed)

    def apply(self, vol: np.ndarray) -> np.ndarray:
        start = [int(self.rng.integers(0, max(s - p, 0) + 1))
                 for s, p in zip(vol.shape[:3], self.patch)]
        return Crop3D(start, self.patch).apply(vol)


class Rotate3D(Preprocessing):
    """Rotate around one axis by ``angle`` degrees (Rotation.scala)."""

    def __init__(self, angle: float, axes: Tuple[int, int] = (0, 1),
                 order: int = 1):
        self.angle = float(angle)
        self.axes = axes
        self.order = order

    def apply(self, vol: np.ndarray) -> np.ndarray:
        from scipy.ndimage import rotate
        return rotate(vol, self.angle, axes=self.axes, reshape=False,
                      order=self.order, mode="nearest")


class AffineTransform3D(Preprocessing):
    """Apply a 3x3 affine matrix (+ optional translation)
    (AffineTransform3D)."""

    def __init__(self, matrix: np.ndarray,
                 translation: Optional[Sequence[float]] = None,
                 order: int = 1):
        self.matrix = np.asarray(matrix, np.float64)
        self.translation = (np.zeros(3) if translation is None
                            else np.asarray(translation, np.float64))
        self.order = order

    def apply(self, vol: np.ndarray) -> np.ndarray:
        from scipy.ndimage import affine_transform
        center = (np.asarray(vol.shape[:3]) - 1) / 2.0
        offset = center - self.matrix @ center + self.translation
        return affine_transform(vol, self.matrix, offset=offset,
                                order=self.order, mode="nearest")
