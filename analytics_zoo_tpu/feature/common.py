"""Composable preprocessing — the ``Preprocessing[A,B]`` analogue
(ref: zoo/feature/common/Preprocessing.scala, chained with ``->``).

A Preprocessing maps one sample to another; chains compose with ``>>``
(and ``->`` is spelled ``.then``).  They run on the host, feeding the
device input pipeline.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List


class Preprocessing:
    def apply(self, sample: Any) -> Any:
        raise NotImplementedError

    def __call__(self, sample: Any) -> Any:
        return self.apply(sample)

    def then(self, other: "Preprocessing") -> "ChainedPreprocessing":
        return ChainedPreprocessing([self, other])

    __rshift__ = then

    def apply_all(self, samples: Iterable[Any]) -> List[Any]:
        return [self.apply(s) for s in samples]


class ChainedPreprocessing(Preprocessing):
    def __init__(self, stages: List[Preprocessing]):
        self.stages = []
        for s in stages:
            if isinstance(s, ChainedPreprocessing):
                self.stages.extend(s.stages)
            else:
                self.stages.append(s)

    def apply(self, sample):
        for s in self.stages:
            sample = s.apply(sample)
        return sample


class FnPreprocessing(Preprocessing):
    def __init__(self, fn: Callable):
        self.fn = fn

    def apply(self, sample):
        return self.fn(sample)


class SplitColumns(Preprocessing):
    """Split a packed ``(n, sum(sizes))`` feature matrix into a LIST of
    ``(n, size_i)`` blocks — the bridge from a single DataFrame
    ``features`` column to a multi-input model (the reference packs
    WideAndDeep features into one assembled vector the same way,
    models/recommendation/Utils.scala:325)."""

    def __init__(self, sizes):
        self.sizes = [int(s) for s in sizes]

    def apply(self, sample):
        import numpy as np
        m = np.asarray(sample)
        if sum(self.sizes) != m.shape[-1]:
            raise ValueError(
                f"SplitColumns sizes {self.sizes} sum to "
                f"{sum(self.sizes)} but the packed matrix has "
                f"{m.shape[-1]} columns")
        out, lo = [], 0
        for s in self.sizes:
            out.append(m[..., lo:lo + s])
            lo += s
        return out
