from analytics_zoo_tpu.feature.feature_set import FeatureSet
from analytics_zoo_tpu.feature.common import Preprocessing, ChainedPreprocessing

__all__ = ["FeatureSet", "Preprocessing", "ChainedPreprocessing"]
