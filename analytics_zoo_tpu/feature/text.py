"""Text pipeline: TextSet + tokenize → normalize → word2idx →
shapeSequence → generateSample.

Reference: zoo/feature/text/TextSet.scala:43-712 and the transformer
classes (Tokenizer, Normalizer, WordIndexer, SequenceShaper,
TextFeatureToSample).  Word-index save/load and relation-pair
construction for ranking (``from_relation_pairs``, used by KNRM QA
ranking) are part of the surface.

Host-side pipeline producing padded int32 id matrices for device infeed.
"""

from __future__ import annotations

import json
import os
import re
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.feature.feature_set import FeatureSet

_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


class TextFeature:
    """One text sample: raw text, optional label, pipeline artifacts."""

    def __init__(self, text: str, label: Optional[int] = None, uri=None):
        self.text = text
        self.label = label
        self.uri = uri
        self.tokens: Optional[List[str]] = None
        self.indices: Optional[np.ndarray] = None


class TextSet:
    """Container of TextFeatures with chained pipeline stages."""

    def __init__(self, features: List[TextFeature],
                 word_index: Optional[Dict[str, int]] = None):
        self.features = features
        self.word_index = word_index

    # ------------------------------------------------------------ creation
    @classmethod
    def from_texts(cls, texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "TextSet":
        labels = labels if labels is not None else [None] * len(texts)
        return cls([TextFeature(t, l) for t, l in zip(texts, labels)])

    @classmethod
    def read_csv(cls, path: str, sep: str = ",") -> "TextSet":
        """uri,text per line (TextSet.readCSV)."""
        feats = []
        with open(path) as f:
            for line in f:
                uri, text = line.rstrip("\n").split(sep, 1)
                feats.append(TextFeature(text, uri=uri))
        return cls(feats)

    # ------------------------------------------------------------ pipeline
    def tokenize(self) -> "TextSet":
        for ft in self.features:
            ft.tokens = _TOKEN_RE.findall(ft.text)
        return self

    def normalize(self) -> "TextSet":
        for ft in self.features:
            assert ft.tokens is not None, "tokenize first"
            ft.tokens = [t.lower() for t in ft.tokens]
        return self

    def word2idx(self, remove_topN: int = 0, max_words_num: int = -1,
                 min_freq: int = 1,
                 existing_map: Optional[Dict[str, int]] = None
                 ) -> "TextSet":
        """Build (or reuse) the word index; 0 is reserved for padding /
        unknown (TextSet.word2idx semantics: index starts at 1)."""
        if existing_map is None:
            counter = Counter()
            for ft in self.features:
                counter.update(ft.tokens or [])
            ranked = [w for w, c in counter.most_common() if c >= min_freq]
            ranked = ranked[remove_topN:]
            if max_words_num > 0:
                ranked = ranked[:max_words_num]
            self.word_index = {w: i + 1 for i, w in enumerate(ranked)}
        else:
            self.word_index = dict(existing_map)
        wi = self.word_index
        for ft in self.features:
            ft.indices = np.asarray(
                [wi.get(t, 0) for t in (ft.tokens or [])], np.int32)
        return self

    def shape_sequence(self, len_: int, trunc_mode: str = "pre",
                       pad_element: int = 0) -> "TextSet":
        """Pad/truncate to fixed length (SequenceShaper)."""
        for ft in self.features:
            idx = ft.indices
            assert idx is not None, "word2idx first"
            if len(idx) > len_:
                idx = idx[-len_:] if trunc_mode == "pre" else idx[:len_]
            elif len(idx) < len_:
                pad = np.full(len_ - len(idx), pad_element, np.int32)
                idx = np.concatenate([pad, idx]) if trunc_mode == "pre" \
                    else np.concatenate([idx, pad])
            ft.indices = idx
        return self

    def generate_sample(self) -> "TextSet":
        return self

    # ------------------------------------------------------------- exports
    def to_arrays(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        x = np.stack([ft.indices for ft in self.features])
        labels = [ft.label for ft in self.features]
        y = None if any(l is None for l in labels) else \
            np.asarray(labels, np.int32).reshape(-1, 1)
        return x, y

    def to_feature_set(self, shuffle: bool = True) -> FeatureSet:
        x, y = self.to_arrays()
        return FeatureSet.from_ndarrays(x, y, shuffle=shuffle)

    # --------------------------------------------------------- persistence
    def save_word_index(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.word_index, f)

    def load_word_index(self, path: str) -> "TextSet":
        with open(path) as f:
            self.word_index = json.load(f)
        return self

    def __len__(self):
        return len(self.features)

    # --------------------------------------------------------- qa ranking
    @classmethod
    def from_relation_pairs(cls, relations, corpus1: Dict[str, str],
                            corpus2: Dict[str, str]) -> "TextSet":
        """Build interleaved (pos, neg) text pairs for pairwise ranking
        (TextSet.fromRelationPairs, feeding RankHinge loss).

        ``relations``: list of (id1, id2, label); for each id1, every
        positive id2 pairs with every negative id2.
        """
        by_q: Dict[str, Dict[int, List[str]]] = {}
        for id1, id2, label in relations:
            by_q.setdefault(id1, {0: [], 1: []})[int(label)].append(id2)
        feats = []
        for id1, groups in by_q.items():
            for pos in groups[1]:
                for neg in groups[0]:
                    feats.append(TextFeature(
                        corpus1[id1] + " \t " + corpus2[pos], label=1))
                    feats.append(TextFeature(
                        corpus1[id1] + " \t " + corpus2[neg], label=0))
        return cls(feats)
