"""MovieLens data utilities for the NCF workload.

Reference: pyzoo/zoo/examples/recommendation (NCF on MovieLens-1M) and
models/recommendation sample building.  ``load_ratings`` reads the
ml-1m ``ratings.dat`` format when a copy exists locally;
``synthetic_ratings`` generates a same-shape corpus (6040 users, 3706
items, ~1M interactions) for offline benchmarking.

``build_ncf_samples`` reproduces the implicit-feedback recipe: each
positive (u, i) pairs with ``neg_per_pos`` sampled negatives for
training, and leave-one-out evaluation groups 1 positive + ``eval_neg``
negatives contiguously (what HitRatio/NDCG metrics expect).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

ML1M_USERS = 6040
ML1M_ITEMS = 3706


def load_ratings(path: str) -> np.ndarray:
    """Read ml-1m ratings.dat (``user::item::rating::ts``) into an
    (N, 3) int array of user, item, rating (ids 1-based)."""
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.strip().split("::")
            if len(parts) >= 3:
                rows.append((int(parts[0]), int(parts[1]),
                             int(float(parts[2]))))
    return np.asarray(rows, np.int64)


def synthetic_ratings(num_users: int = ML1M_USERS,
                      num_items: int = ML1M_ITEMS,
                      num_ratings: int = 1_000_000,
                      seed: int = 42) -> np.ndarray:
    """Same-shape synthetic corpus with a popularity skew (zipf-ish),
    deterministic per seed."""
    rng = np.random.default_rng(seed)
    users = rng.integers(1, num_users + 1, num_ratings)
    # zipf-like item popularity, clipped into range
    items = (rng.zipf(1.2, num_ratings) % num_items) + 1
    ratings = rng.integers(1, 6, num_ratings)
    return np.stack([users, items, ratings], axis=1)


def build_ncf_samples(ratings: np.ndarray, num_users: int, num_items: int,
                      neg_per_pos: int = 4, eval_neg: int = 100,
                      seed: int = 7,
                      max_users_eval: Optional[int] = None):
    """Implicit-feedback train/eval split.

    Returns ``(train_x=[users, items], train_y, eval_x, eval_groups)``:
    train pairs each observed interaction (label 1) with sampled
    unobserved items (label 0); eval holds out each user's last positive
    and ranks it against ``eval_neg`` sampled negatives, groups laid out
    contiguously (positive first).
    """
    rng = np.random.default_rng(seed)
    users = ratings[:, 0].astype(np.int64)
    items = ratings[:, 1].astype(np.int64)

    # last interaction per user (by row order) → eval positive
    last_row = {}
    for idx in range(len(users)):
        last_row[users[idx]] = idx
    eval_rows = np.array(sorted(last_row.values()))
    train_mask = np.ones(len(users), bool)
    train_mask[eval_rows] = False

    tr_u = users[train_mask]
    tr_i = items[train_mask]

    # negatives: uniform over items; collision with a true positive is
    # rare and tolerated, as in the reference example pipeline
    neg_u = np.repeat(tr_u, neg_per_pos)
    neg_i = rng.integers(1, num_items + 1, len(neg_u))
    train_users = np.concatenate([tr_u, neg_u])
    train_items = np.concatenate([tr_i, neg_i])
    train_labels = np.concatenate(
        [np.ones(len(tr_u), np.int32), np.zeros(len(neg_u), np.int32)])
    perm = rng.permutation(len(train_users))
    train_x = [train_users[perm].reshape(-1, 1).astype(np.int32),
               train_items[perm].reshape(-1, 1).astype(np.int32)]
    train_y = train_labels[perm].reshape(-1, 1)

    # eval: per held-out user, 1 positive + eval_neg negatives
    ev = eval_rows if max_users_eval is None else eval_rows[:max_users_eval]
    g = eval_neg + 1
    ev_users = np.repeat(users[ev], g)
    ev_items = np.empty(len(ev) * g, np.int64)
    ev_items[0::g] = items[ev]
    for k in range(1, g):
        ev_items[k::g] = rng.integers(1, num_items + 1, len(ev))
    eval_x = [ev_users.reshape(-1, 1).astype(np.int32),
              ev_items.reshape(-1, 1).astype(np.int32)]
    eval_y = np.zeros((len(ev_users), 1), np.int32)
    eval_y[0::g] = 1
    return train_x, train_y, eval_x, eval_y
