"""Pure-Python TFRecord reader/writer + tf.train.Example codec.

Reference: ``TFDataset.from_tfrecord_file`` (pyzoo tf_dataset.py:479)
reads TFRecords through the tensorflow-hadoop input format; SURVEY.md
§2.9 calls for a pure-Python reader here (no TF dependency).

TFRecord framing (tensorflow/core/lib/io/record_writer.h):

    uint64 length            (little-endian)
    uint32 masked_crc32c(length bytes)
    byte   data[length]
    uint32 masked_crc32c(data)

CRC is CRC-32C (Castagnoli), masked with the rot-15 + magic recipe.
``Example`` parsing uses the in-house protobuf wire codec
(utils/pbwire.py) — schema from tensorflow/core/example/{example,
feature}.proto.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.utils.pbwire import Field, Message

# crc32c lives in the native data-path module (C++ with a pure-Python
# fallback) and is shared with the TensorBoard writer
from analytics_zoo_tpu.native import crc32c  # noqa: F401


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


class CorruptRecordError(IOError):
    """A TFRecord frame failed validation: truncated header/payload or
    a crc mismatch.  Carries the file path and the BYTE OFFSET of the
    bad frame so a corrupt shard can be repaired / resharded without a
    hex-dump hunt."""

    def __init__(self, path: str, offset: int, reason: str):
        super().__init__(f"{path}: corrupt TFRecord at byte offset "
                         f"{offset}: {reason}")
        self.path = path
        self.offset = offset
        self.reason = reason


# ----------------------------------------------------------------- framing

def _read_frame(f, offset: int, path: str, check_crc: bool):
    """Read one framed record at ``offset`` (file position must already
    be there).  Returns the payload bytes, or None at clean EOF.

    The length-crc is ALWAYS verified before the length field is
    trusted: a corrupt 8-byte length would otherwise drive a
    multi-gigabyte read (or a bogus "truncated" report) from 12 bytes
    of garbage.  ``check_crc`` gates only the payload crc, whose cost
    scales with the data.
    """
    header = f.read(12)
    if not header:
        return None
    if len(header) < 12:
        raise CorruptRecordError(
            path, offset,
            f"truncated header ({len(header)} of 12 bytes)")
    length, length_crc = struct.unpack("<QI", header)
    if masked_crc32c(header[:8]) != length_crc:
        raise CorruptRecordError(path, offset, "length crc mismatch")
    data = f.read(length)
    if len(data) < length:
        raise CorruptRecordError(
            path, offset,
            f"truncated payload ({len(data)} of {length} bytes)")
    crc_bytes = f.read(4)
    if len(crc_bytes) < 4:
        raise CorruptRecordError(
            path, offset,
            f"truncated payload crc ({len(crc_bytes)} of 4 bytes)")
    if check_crc:
        (data_crc,) = struct.unpack("<I", crc_bytes)
        if masked_crc32c(data) != data_crc:
            raise CorruptRecordError(path, offset, "payload crc mismatch")
    return data


def read_tfrecord(path: str, check_crc: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads from one TFRecord file."""
    with open(path, "rb") as f:
        offset = 0
        while True:
            data = _read_frame(f, offset, path, check_crc)
            if data is None:
                return
            offset += 12 + len(data) + 4
            yield data


def index_tfrecord(path: str, check_crc: bool = True
                   ) -> Iterator[tuple]:
    """Yield ``(offset, length)`` for every frame in one file — the
    random-access index for ``data.source.TFRecordSource``.  Walks the
    framing by seeking over payloads, so indexing cost is header IO
    only; with ``check_crc`` the payloads are read and verified too
    (one up-front integrity pass instead of a mid-epoch crash)."""
    with open(path, "rb") as f:
        offset = 0
        size = os.fstat(f.fileno()).st_size
        while True:
            if check_crc:
                data = _read_frame(f, offset, path, True)
                if data is None:
                    return
                length = len(data)
            else:
                header = f.read(12)
                if not header:
                    return
                if len(header) < 12:
                    raise CorruptRecordError(
                        path, offset,
                        f"truncated header ({len(header)} of 12 bytes)")
                length, length_crc = struct.unpack("<QI", header)
                if masked_crc32c(header[:8]) != length_crc:
                    raise CorruptRecordError(path, offset,
                                             "length crc mismatch")
                end = f.seek(length + 4, os.SEEK_CUR)
                if end > size:
                    raise CorruptRecordError(
                        path, offset,
                        f"truncated payload (frame ends at {end}, file "
                        f"is {size} bytes)")
            yield offset, length
            offset += 12 + length + 4


def read_record_at(f, offset: int, check_crc: bool = True,
                   path: str = "<tfrecord>") -> bytes:
    """Random-access read of one frame at a known ``offset`` from an
    open binary file handle."""
    f.seek(offset)
    data = _read_frame(f, offset, path, check_crc)
    if data is None:
        raise CorruptRecordError(path, offset, "offset is at/past EOF")
    return data


def write_tfrecord(path: str, records: Sequence[bytes]) -> None:
    with open(path, "wb") as f:
        for data in records:
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", masked_crc32c(header)))
            f.write(data)
            f.write(struct.pack("<I", masked_crc32c(data)))


# ----------------------------------------- tf.train.Example proto schema

class BytesList(Message):
    FIELDS = [Field(1, "value", "bytes", repeated=True)]


class FloatList(Message):
    FIELDS = [Field(1, "value", "float", repeated=True)]


class Int64List(Message):
    FIELDS = [Field(1, "value", "int64", repeated=True)]


class Feature(Message):
    FIELDS = [
        Field(1, "bytes_list", "msg", msg_cls=BytesList),
        Field(2, "float_list", "msg", msg_cls=FloatList),
        Field(3, "int64_list", "msg", msg_cls=Int64List),
    ]


class FeatureEntry(Message):
    """map<string, Feature> entry."""
    FIELDS = [
        Field(1, "key", "string"),
        Field(2, "value", "msg", msg_cls=Feature),
    ]


class Features(Message):
    FIELDS = [Field(1, "feature", "msg", repeated=True,
                    msg_cls=FeatureEntry)]


class Example(Message):
    FIELDS = [Field(1, "features", "msg", msg_cls=Features)]


def parse_example(data: bytes) -> Dict[str, np.ndarray]:
    """Decode one serialized tf.train.Example into name → ndarray."""
    ex = Example.decode(data)
    out: Dict[str, np.ndarray] = {}
    if ex.features is None:
        return out
    for entry in ex.features.feature:
        feat = entry.value
        if feat is None:
            continue
        if feat.int64_list is not None and feat.int64_list.value:
            out[entry.key] = np.asarray(feat.int64_list.value, np.int64)
        elif feat.float_list is not None and feat.float_list.value:
            out[entry.key] = np.asarray(feat.float_list.value, np.float32)
        elif feat.bytes_list is not None and feat.bytes_list.value:
            out[entry.key] = np.asarray(feat.bytes_list.value, object)
        else:
            out[entry.key] = np.asarray([], np.float32)
    return out


def make_example(features: Dict[str, object]) -> bytes:
    """Encode name → (ints | floats | bytes) into a tf.train.Example."""
    entries = []
    for name, value in features.items():
        arr = np.asarray(value)
        if arr.dtype.kind in "iu b".replace(" ", ""):
            feat = Feature(int64_list=Int64List(
                value=[int(v) for v in arr.ravel()]))
        elif arr.dtype.kind == "f":
            feat = Feature(float_list=FloatList(
                value=[float(v) for v in arr.ravel()]))
        else:
            vals = [v if isinstance(v, bytes) else str(v).encode()
                    for v in np.atleast_1d(arr)]
            feat = Feature(bytes_list=BytesList(value=vals))
        entries.append(FeatureEntry(key=name, value=feat))
    return Example(features=Features(feature=entries)).encode()


# -------------------------------------------------- dataset-level helpers

def read_examples(paths, check_crc: bool = True
                  ) -> Iterator[Dict[str, np.ndarray]]:
    """Iterate parsed Examples over one path, a glob, or a list."""
    import glob as _glob
    if isinstance(paths, (str, os.PathLike)):
        paths = sorted(_glob.glob(str(paths))) or [str(paths)]
    for p in paths:
        for rec in read_tfrecord(p, check_crc=check_crc):
            yield parse_example(rec)


def load_tfrecord_arrays(paths, feature_names: Optional[List[str]] = None
                         ) -> Dict[str, np.ndarray]:
    """Materialise TFRecord Examples into stacked arrays (fixed-shape
    features only) — the eager path feeding FeatureSet."""
    cols: Dict[str, List[np.ndarray]] = {}
    for ex in read_examples(paths):
        for k, v in ex.items():
            if feature_names is None or k in feature_names:
                cols.setdefault(k, []).append(v)
    return {k: np.stack(vs) for k, vs in cols.items()}
