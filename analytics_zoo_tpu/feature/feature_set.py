"""FeatureSet — the input pipeline feeding the TPU mesh.

Reference (zoo/feature/FeatureSet.scala): partition-cached data with a
shuffled index array and an endless wraparound iterator for training
(CachedDistributedFeatureSet :229-329), finite ordered iteration for
eval, memory tiers (DRAM / PMEM / DISK_AND_DRAM(n) slices :585-662), and
``->`` transformer chaining.

TPU redesign: data lives host-side as *columnar numpy pytrees* (struct
of arrays, not the reference's array of Sample structs) so a minibatch
is a zero-copy slice + gather, ready for ``jax.device_put`` into HBM.
Per-epoch shuffling uses a deterministic per-epoch RNG — the analogue of
the reference's per-partition index shuffle, reproducible across hosts
(each host computes the same global permutation and takes its own
shard).  Disk-slice mode memory-maps .npy files and loads 1/num_slices
per sub-epoch.
"""

from __future__ import annotations

import math
import os
from typing import Any, Callable, Iterator, List, Optional, Tuple

import numpy as np
import jax

from analytics_zoo_tpu.feature.common import Preprocessing


def _tree_len(tree) -> int:
    return len(jax.tree_util.tree_leaves(tree)[0])


def pad_rows(tree, pad: int):
    """Zero-pad ``pad`` rows onto the leading axis of every leaf —
    the shared fixed-shape padding used by the eval tail batch, the
    predict tail batch, and the HBM epoch-cache source."""
    if pad <= 0:
        return tree
    pad_fn = lambda a: np.concatenate(
        [a, np.zeros((pad,) + a.shape[1:], a.dtype)])
    return jax.tree_util.tree_map(pad_fn, tree)


def _tree_take(tree, idx):
    from analytics_zoo_tpu import native

    def take(a):
        if isinstance(a, np.ndarray) and a.ndim >= 1:
            return native.gather_rows(a, idx)
        return a[idx]
    return jax.tree_util.tree_map(take, tree)


class FeatureSet:
    """Columnar in-memory dataset with train/eval iteration semantics."""

    def __init__(self, x, y=None, shuffle: bool = True,
                 num_slices: int = 1, seed: Optional[int] = None):
        self.x = x
        self.y = y
        self.shuffle = shuffle
        self.num_slices = max(int(num_slices), 1)
        if seed is None:
            from analytics_zoo_tpu.common.config import get_config
            seed = int(get_config().get("data.shuffle_seed"))
        self.seed = seed
        self._size = _tree_len(x)
        if y is not None:
            ylen = _tree_len(y)
            if ylen != self._size:
                raise ValueError(f"x has {self._size} samples, y has {ylen}")

    # ------------------------------------------------------------- factories
    @classmethod
    def from_ndarrays(cls, x, y=None, shuffle: bool = True,
                      seed: Optional[int] = None) -> "FeatureSet":
        """From numpy arrays / pytrees of arrays (leading dim = samples)."""
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)
        return cls(to_np(x), to_np(y) if y is not None else None,
                   shuffle=shuffle, seed=seed)

    @classmethod
    def from_samples(cls, samples: List[Tuple[Any, Any]],
                     shuffle: bool = True) -> "FeatureSet":
        """From a list of (x, y) sample pytrees — stacked columnar."""
        xs = [s[0] for s in samples]
        ys = [s[1] for s in samples]
        stack = lambda seq: jax.tree_util.tree_map(
            lambda *leaves: np.stack(leaves), *seq)
        return cls(stack(xs), stack(ys), shuffle=shuffle)

    @classmethod
    def from_torch_dataloader(cls, dataloader, shuffle: bool = True,
                              max_items: Optional[int] = None
                              ) -> "FeatureSet":
        """Drain a PyTorch DataLoader into columnar storage.

        The PythonLoaderFeatureSet role (reference FeatureSet.scala:331
        runs the cloudpickled loader inside Jep on each executor); here
        the host IS the executor, so the loader runs in-process and the
        resulting columns feed the device prefetcher.
        """
        xs, ys = [], []
        n = 0
        to_np = lambda t: jax.tree_util.tree_map(
            lambda v: v.numpy() if hasattr(v, "numpy") else np.asarray(v),
            t, is_leaf=lambda v: hasattr(v, "numpy"))
        for item in dataloader:
            if isinstance(item, (tuple, list)) and len(item) == 2:
                bx, by = item
                xs.append(to_np(bx))
                ys.append(to_np(by))
            else:
                xs.append(to_np(item))
            n += _tree_len(xs[-1])
            if max_items is not None and n >= max_items:
                break
        if not xs:
            raise ValueError("dataloader yielded no items")
        cat = lambda seq: jax.tree_util.tree_map(
            lambda *leaves: np.concatenate(leaves), *seq)
        x = cat(xs)
        y = cat(ys) if ys else None
        if max_items is not None and n > max_items:
            trim = lambda t: jax.tree_util.tree_map(
                lambda a: a[:max_items], t)
            x = trim(x)
            y = trim(y) if y is not None else None
        if y is not None:
            y = jax.tree_util.tree_map(
                lambda a: a[:, None] if a.ndim == 1 else a, y)
        return cls(x, y, shuffle=shuffle)

    @classmethod
    def from_npy_dir(cls, path: str, num_slices: Optional[int] = None,
                     shuffle: bool = True,
                     memory_type: str = "PMEM") -> "FeatureSet":
        """Disk-backed mode with the reference's cache-tier policy
        names (FeatureSet.scala memoryType — DRAM / PMEM / DIRECT,
        :585-662):

        * ``"DRAM"``  — materialise fully into host RAM,
        * ``"PMEM"``  — memory-map (the persistent-memory tier's role:
          bigger-than-RAM data paged on demand),
        * ``"DIRECT"``— memory-map AND stream 1/num_slices per
          sub-epoch (the disk-sliced DiskFeatureSet).

        The fourth tier — device HBM — is above all of these:
        ``DistributedTrainer.put_epoch`` + ``epoch_scan_fn``.
        """
        tier = memory_type.upper()
        if tier not in ("DRAM", "PMEM", "DIRECT"):
            raise ValueError(
                f"memory_type {memory_type!r}: expected DRAM|PMEM|DIRECT")
        mmap = None if tier == "DRAM" else "r"
        x = np.load(os.path.join(path, "x.npy"), mmap_mode=mmap)
        ypath = os.path.join(path, "y.npy")
        y = np.load(ypath, mmap_mode=mmap) if os.path.exists(ypath) \
            else None
        if num_slices is None:
            # tier default only when the caller didn't choose
            num_slices = 4 if tier == "DIRECT" else 1
        return cls(x, y, shuffle=shuffle, num_slices=num_slices)

    # ------------------------------------------------------------ transforms
    def transform(self, fn) -> "FeatureSet":
        """Apply a Preprocessing / callable to the whole columnar x."""
        f = fn.apply if isinstance(fn, Preprocessing) else fn
        return FeatureSet(f(self.x), self.y, shuffle=self.shuffle,
                          num_slices=self.num_slices, seed=self.seed)

    __rshift__ = transform

    # -------------------------------------------------------------- iteration
    @property
    def size(self) -> int:
        return self._size

    def num_batches(self, batch_size: int, train: bool = True) -> int:
        if train:
            return self._size // batch_size
        return math.ceil(self._size / batch_size)

    def _epoch_perm(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng(self.seed * 1_000_003 + epoch)
        return rng.permutation(self._size)

    def epoch_batches(self, epoch: int, batch_size: int,
                      train: bool = True) -> Iterator[Tuple]:
        """Finite per-epoch batch iterator.

        Train: deterministically shuffled per epoch, remainder dropped
        (the global batch must tile the data-parallel mesh).  Eval: in
        order; the tail batch is zero-padded and a float mask column
        marks real rows so metric partials stay exact.
        """
        n = self._size
        if train:
            idx = self._epoch_perm(epoch) if self.shuffle else np.arange(n)
            nb = n // batch_size
            for b in range(nb):
                sel = idx[b * batch_size:(b + 1) * batch_size]
                yield (_tree_take(self.x, sel),
                       _tree_take(self.y, sel) if self.y is not None
                       else None)
        else:
            nb = math.ceil(n / batch_size)
            for b in range(nb):
                lo = b * batch_size
                hi = min(lo + batch_size, n)
                sel = np.arange(lo, hi)
                xb = _tree_take(self.x, sel)
                yb = _tree_take(self.y, sel) if self.y is not None else None
                mask = np.ones(hi - lo, np.float32)
                if hi - lo < batch_size:
                    pad = batch_size - (hi - lo)
                    xb = pad_rows(xb, pad)
                    if yb is not None:
                        yb = pad_rows(yb, pad)
                    mask = np.concatenate([mask, np.zeros(pad, np.float32)])
                yield (xb, yb, mask)

    def epoch_chunks(self, epoch: int, batch_size: int, steps: int
                     ) -> Iterator[Tuple]:
        """Chunked training iterator: yields ``(x, y)`` host arrays of
        up to ``steps`` whole batches each (same per-epoch permutation
        and remainder-drop as ``epoch_batches``).

        The training engine scans each chunk on-device in ONE dispatch
        (``DistributedTrainer.epoch_scan_fn(k, batch_size)``), cutting
        per-step host/dispatch overhead by ``steps`` while only ever
        holding ``steps x batch_size`` rows in HBM — the middle tier
        between per-step dispatch and the whole-epoch HBM scan."""
        n = self._size
        idx = self._epoch_perm(epoch) if self.shuffle else np.arange(n)
        nb_total = n // batch_size
        b = 0
        while b < nb_total:
            k = min(int(steps), nb_total - b)
            sel = idx[b * batch_size:(b + k) * batch_size]
            yield (_tree_take(self.x, sel),
                   _tree_take(self.y, sel) if self.y is not None
                   else None, k)
            b += k

    def slice_batches(self, epoch: int, slice_index: int, batch_size: int
                      ) -> Iterator[Tuple]:
        """Disk-slice training: iterate one 1/num_slices shard of this
        epoch's permutation (materialising only that shard)."""
        idx = self._epoch_perm(epoch) if self.shuffle \
            else np.arange(self._size)
        per = self._size // self.num_slices
        lo = slice_index * per
        hi = self._size if slice_index == self.num_slices - 1 \
            else lo + per
        shard = np.sort(idx[lo:hi])  # sorted → sequential mmap reads
        x = _tree_take(self.x, shard)
        y = _tree_take(self.y, shard) if self.y is not None else None
        sub = FeatureSet(x, y, shuffle=self.shuffle, seed=self.seed + 7)
        yield from sub.epoch_batches(epoch, batch_size, train=True)
