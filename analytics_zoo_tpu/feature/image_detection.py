"""Detection data pipeline: Pascal-VOC reader + box-aware transforms.

Reference: objectdetection/common/dataset/roiimage/ (RoiImageSeqGenerator,
VOC parsing), feature/image transforms ImageExpand.scala /
ImageRandomCrop / ImageColorJitter — the OpenCV executor-side pipeline
that feeds SSD training with (image, RoiLabel) pairs.

TPU design: samples are plain dicts {image HWC, boxes (N,4) ABSOLUTE
x1y1x2y2 pixels, labels (N,), difficult (N,)} flowing through chained
host-side transforms; ``to_feature_set`` pads boxes to a fixed
``max_boxes`` and normalizes to [0,1] so every batch has static shapes
for the jitted MultiBox loss (multibox_loss.py matches on
(gt_boxes, gt_labels, gt_mask)).
"""

from __future__ import annotations

import glob
import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.feature.common import Preprocessing
from analytics_zoo_tpu.feature.feature_set import FeatureSet
from analytics_zoo_tpu.feature.image import read_image

VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car",
    "cat", "chair", "cow", "diningtable", "dog", "horse", "motorbike",
    "person", "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


def parse_voc_xml(xml_path: str, class_to_idx: Dict[str, int]
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """One VOC annotation file → (boxes (N,4) absolute x1y1x2y2,
    labels (N,) int32 1-based, difficult (N,) bool).  Unknown class
    names are skipped (matches the reference's configurable class
    list)."""
    root = ET.parse(xml_path).getroot()
    boxes, labels, difficult = [], [], []
    for obj in root.findall("object"):
        name = obj.findtext("name", "").strip()
        if name not in class_to_idx:
            continue
        bb = obj.find("bndbox")
        # VOC pixel coordinates are 1-based inclusive
        x1 = float(bb.findtext("xmin")) - 1.0
        y1 = float(bb.findtext("ymin")) - 1.0
        x2 = float(bb.findtext("xmax")) - 1.0
        y2 = float(bb.findtext("ymax")) - 1.0
        boxes.append([x1, y1, x2, y2])
        labels.append(class_to_idx[name])
        difficult.append(obj.findtext("difficult", "0").strip() == "1")
    return (np.asarray(boxes, np.float32).reshape(-1, 4),
            np.asarray(labels, np.int32),
            np.asarray(difficult, bool))


class DetectionSet:
    """Container of detection samples with chained transforms (the
    roiimage ImageSet analogue).

    Transforms are LAZY: ``transform``/``>>`` records the stage and
    ``materialize(epoch)`` (called by ``to_feature_set``) applies the
    chain with per-epoch reseeding of random stages — so each epoch
    sees FRESH augmentation draws, like the reference's executor-side
    per-iteration transforms, not one frozen draw."""

    def __init__(self, samples: List[dict],
                 classes: Sequence[str] = VOC_CLASSES,
                 stages: Optional[List[Preprocessing]] = None):
        self.samples = samples
        self.classes = tuple(classes)
        self.stages: List[Preprocessing] = list(stages or [])

    @classmethod
    def read_voc(cls, root: str, split: Optional[str] = None,
                 classes: Sequence[str] = VOC_CLASSES) -> "DetectionSet":
        """Read a VOCdevkit-layout dataset: ``JPEGImages/``,
        ``Annotations/``, optional ``ImageSets/Main/<split>.txt``.
        Class indices are 1-based (0 = background)."""
        class_to_idx = {c: i + 1 for i, c in enumerate(classes)}
        if split is not None:
            ids = [ln.strip().split()[0] for ln in
                   open(os.path.join(root, "ImageSets", "Main",
                                     split + ".txt"))
                   if ln.strip()]
        else:
            ids = sorted(
                os.path.splitext(os.path.basename(p))[0]
                for p in glob.glob(os.path.join(root, "Annotations",
                                                "*.xml")))
        samples = []
        for img_id in ids:
            xml = os.path.join(root, "Annotations", img_id + ".xml")
            boxes, labels, difficult = parse_voc_xml(xml, class_to_idx)
            img_path = None
            for ext in (".jpg", ".jpeg", ".png"):
                p = os.path.join(root, "JPEGImages", img_id + ext)
                if os.path.exists(p):
                    img_path = p
                    break
            if img_path is None:
                raise FileNotFoundError(
                    f"no image for annotation {img_id} under "
                    f"{os.path.join(root, 'JPEGImages')}")
            samples.append({"image": read_image(img_path), "boxes": boxes,
                            "labels": labels, "difficult": difficult,
                            "id": img_id})
        return cls(samples, classes)

    @classmethod
    def from_samples(cls, samples: List[dict],
                     classes: Sequence[str] = VOC_CLASSES
                     ) -> "DetectionSet":
        return cls(list(samples), classes)

    def transform(self, stage: Preprocessing) -> "DetectionSet":
        return DetectionSet(self.samples, self.classes,
                            self.stages + [stage])

    __rshift__ = transform

    def __len__(self):
        return len(self.samples)

    def materialize(self, epoch: int = 0) -> "DetectionSet":
        """Run the recorded transform chain; random stages are reseeded
        per (epoch, stage index) so every epoch draws fresh
        augmentations."""
        samples = self.samples
        for i, st in enumerate(self.stages):
            if hasattr(st, "reseed"):
                st.reseed(epoch * 1000 + i)
            samples = [st.apply(dict(s)) for s in samples]
        return DetectionSet(samples, self.classes)

    def to_feature_set(self, max_boxes: int = 16, shuffle: bool = True,
                       include_difficult: bool = True,
                       epoch: int = 0) -> FeatureSet:
        """Pad/normalize into the MultiBoxLoss target layout:
        x = images (B,H,W,C) f32; y = (boxes (B,G,4) in [0,1],
        labels (B,G) int32, mask (B,G) f32).

        Ground truths beyond ``max_boxes`` are DROPPED (logged once) —
        raise ``max_boxes`` for crowd-heavy datasets."""
        import logging
        imgs, bxs, lbs, msks = [], [], [], []
        dropped = 0
        for s in self.materialize(epoch).samples:
            img = np.asarray(s["image"], np.float32)
            h, w = img.shape[:2]
            boxes = np.asarray(s["boxes"], np.float32).reshape(-1, 4)
            labels = np.asarray(s["labels"], np.int32)
            if not include_difficult and len(labels):
                keep = ~np.asarray(s["difficult"], bool)
                boxes, labels = boxes[keep], labels[keep]
            n = min(len(labels), max_boxes)
            dropped += len(labels) - n
            b = np.zeros((max_boxes, 4), np.float32)
            l = np.zeros((max_boxes,), np.int32)
            m = np.zeros((max_boxes,), np.float32)
            if n:
                b[:n] = boxes[:n] / np.array([w, h, w, h], np.float32)
                l[:n] = labels[:n]
                m[:n] = 1.0
            imgs.append(img)
            bxs.append(b)
            lbs.append(l)
            msks.append(m)
        if dropped:
            logging.getLogger("analytics_zoo_tpu").warning(
                "to_feature_set: dropped %d ground-truth boxes beyond "
                "max_boxes=%d — raise max_boxes to keep them", dropped,
                max_boxes)
        shapes = {im.shape for im in imgs}
        if len(shapes) > 1:
            raise ValueError(
                f"images must share one shape for batching, got {shapes};"
                " add DetResize to the transform chain")
        return FeatureSet.from_ndarrays(
            np.stack(imgs),
            (np.stack(bxs), np.stack(lbs), np.stack(msks)),
            shuffle=shuffle)


# --------------------------------------------------------- box transforms
class DetResize(Preprocessing):
    """Resize image and scale boxes (ref ImageResize + RoiResize)."""

    def __init__(self, resize_h: int, resize_w: int):
        self.h, self.w = int(resize_h), int(resize_w)

    def apply(self, s: dict) -> dict:
        from analytics_zoo_tpu.feature.image import ImageResize
        h, w = s["image"].shape[:2]
        s["image"] = ImageResize(self.h, self.w).apply(s["image"])
        if len(s["boxes"]):
            scale = np.array([self.w / w, self.h / h] * 2, np.float32)
            s["boxes"] = s["boxes"] * scale
        return s


class DetHFlip(Preprocessing):
    """Horizontal flip of image AND boxes (ref RoiHFlip)."""

    def __init__(self, prob: float = 0.5, seed: int = 0):
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, s: dict) -> dict:
        if self.rng.random() >= self.prob:
            return s
        w = s["image"].shape[1]
        s["image"] = np.ascontiguousarray(s["image"][:, ::-1])
        if len(s["boxes"]):
            b = s["boxes"].copy()
            b[:, [0, 2]] = w - s["boxes"][:, [2, 0]]
            s["boxes"] = b
        return s


class DetExpand(Preprocessing):
    """Zoom-out: paste the image at a random offset on a mean-filled
    canvas up to ``max_ratio`` larger; boxes shift (ref
    ImageExpand.scala — the SSD small-object augmentation)."""

    def __init__(self, max_ratio: float = 4.0, mean=(123, 117, 104),
                 prob: float = 0.5, seed: int = 0):
        self.max_ratio = float(max_ratio)
        self.mean = np.asarray(mean, np.float32)
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def apply(self, s: dict) -> dict:
        if self.rng.random() >= self.prob:
            return s
        from analytics_zoo_tpu.feature.image import expand_canvas
        canvas, top, left = expand_canvas(s["image"], self.rng,
                                          self.max_ratio, self.mean)
        s["image"] = canvas
        if len(s["boxes"]):
            s["boxes"] = s["boxes"] + np.array(
                [left, top, left, top], np.float32)
        return s


class DetRandomCrop(Preprocessing):
    """SSD batch-sampler crop: repeatedly sample a patch whose min-IoU
    with some ground truth meets a randomly chosen constraint; keep
    boxes whose CENTERS fall inside, clip them to the patch (ref
    ImageRandomCrop + the SSD sampler in roiimage)."""

    def __init__(self, min_ious=(None, 0.1, 0.3, 0.5, 0.7, 0.9),
                 min_scale: float = 0.3, max_trials: int = 50,
                 prob: float = 0.5, seed: int = 0):
        self.min_ious = tuple(min_ious)
        self.min_scale = float(min_scale)
        self.max_trials = int(max_trials)
        self.prob = prob
        self.rng = np.random.default_rng(seed)

    def reseed(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    @staticmethod
    def _iou(boxes, patch):
        lt = np.maximum(boxes[:, :2], patch[:2])
        rb = np.minimum(boxes[:, 2:], patch[2:])
        wh = np.clip(rb - lt, 0, None)
        inter = wh[:, 0] * wh[:, 1]
        area_b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        area_p = (patch[2] - patch[0]) * (patch[3] - patch[1])
        return inter / np.maximum(area_b + area_p - inter, 1e-10)

    def apply(self, s: dict) -> dict:
        if self.rng.random() >= self.prob or not len(s["boxes"]):
            return s
        img, boxes = s["image"], s["boxes"]
        h, w = img.shape[:2]
        min_iou = self.min_ious[
            int(self.rng.integers(0, len(self.min_ious)))]
        if min_iou is None:
            return s
        for _ in range(self.max_trials):
            cw = float(self.rng.uniform(self.min_scale, 1.0)) * w
            ch = float(self.rng.uniform(self.min_scale, 1.0)) * h
            if not 0.5 <= cw / ch <= 2.0:     # aspect constraint
                continue
            left = float(self.rng.uniform(0, w - cw))
            top = float(self.rng.uniform(0, h - ch))
            patch = np.array([left, top, left + cw, top + ch],
                             np.float32)
            if self._iou(boxes, patch).max() < min_iou:
                continue
            centers = (boxes[:, :2] + boxes[:, 2:]) / 2
            keep = ((centers[:, 0] >= patch[0])
                    & (centers[:, 0] <= patch[2])
                    & (centers[:, 1] >= patch[1])
                    & (centers[:, 1] <= patch[3]))
            if not keep.any():
                continue
            x1, y1, x2, y2 = (int(patch[0]), int(patch[1]),
                              int(patch[2]), int(patch[3]))
            s["image"] = np.ascontiguousarray(img[y1:y2, x1:x2])
            b = boxes[keep].copy()
            b[:, [0, 2]] = np.clip(b[:, [0, 2]] - x1, 0, x2 - x1)
            b[:, [1, 3]] = np.clip(b[:, [1, 3]] - y1, 0, y2 - y1)
            s["boxes"] = b
            s["labels"] = np.asarray(s["labels"])[keep]
            s["difficult"] = np.asarray(s["difficult"])[keep]
            return s
        return s


class DetColorJitter(Preprocessing):
    """Photometric jitter on the image only — boxes untouched."""

    def __init__(self, **kwargs):
        from analytics_zoo_tpu.feature.image import ImageColorJitter
        self.jitter = ImageColorJitter(**kwargs)

    def reseed(self, seed: int) -> None:
        self.jitter.reseed(seed)

    def apply(self, s: dict) -> dict:
        s["image"] = self.jitter.apply(s["image"])
        return s


class DetNormalize(Preprocessing):
    """Per-channel mean/std on the image only."""

    def __init__(self, mean, std=(1.0, 1.0, 1.0)):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def apply(self, s: dict) -> dict:
        s["image"] = (np.asarray(s["image"], np.float32) - self.mean) \
            / self.std
        return s
