"""Ring attention — sequence-parallel exact attention over the ICI ring.

Long-context capability with no reference counterpart (the reference's
attention is single-node full-sequence, SURVEY.md §5): the sequence is
sharded over the mesh's ``seq`` axis; each device holds one block of
Q/K/V.  K/V blocks rotate around the ring via ``lax.ppermute`` while
each device accumulates its queries' attention with the online-softmax
(flash) recurrence — memory stays O(T/n · T/n) per device and the K/V
transfer overlaps with compute on real hardware.

Built on ``shard_map`` so the collective schedule is explicit; inside
the shard the math is the same ``blockwise_attention_step`` the
single-device flash path uses.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.ops.attention import blockwise_attention_step
from analytics_zoo_tpu.parallel.mesh import SEQ_AXIS


def _ring_body(q, k, v, axis_name: str, causal: bool, scale: float,
               axis_size: int):
    """Per-shard computation: q,k,v are the local (B,H,Tblk,D) blocks."""
    my_idx = jax.lax.axis_index(axis_name)
    b, h, t_blk, d = q.shape

    acc = jnp.zeros((b, h, t_blk, d), jnp.float32)
    m = jnp.full((b, h, t_blk), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t_blk), jnp.float32)

    def step(i, carry):
        acc, m, l, k_cur, v_cur = carry
        # which device's block are we currently holding?
        src_idx = (my_idx + i) % axis_size
        if causal:
            # global positions: queries my_idx*t_blk+.., keys src_idx*t_blk+..
            q_pos = my_idx * t_blk + jnp.arange(t_blk)[:, None]
            k_pos = src_idx * t_blk + jnp.arange(t_blk)[None, :]
            bias = jnp.where(q_pos >= k_pos, 0.0, -1e30)
        else:
            bias = None
        acc, m, l = blockwise_attention_step(
            q, k_cur, v_cur, acc, m, l, scale, logits_bias=bias)
        # rotate K/V one hop around the ring
        perm = [(j, (j - 1) % axis_size) for j in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc, m, l, _, _ = jax.lax.fori_loop(
        0, axis_size, step, (acc, m, l, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = False,
                   scale: Optional[float] = None,
                   axis_name: str = SEQ_AXIS):
    """Exact attention with Q/K/V sharded on ``axis_name`` (dim 2).

    q,k,v: (B, H, T, D) global arrays; T must divide the seq-axis size.
    Returns (B, H, T, D) with the same sharding.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    axis_size = mesh.shape[axis_name]
    if axis_size == 1:
        from analytics_zoo_tpu.ops.attention import (
            scaled_dot_product_attention)
        return scaled_dot_product_attention(q, k, v, causal=causal,
                                            scale=scale)
    spec = P(None, None, axis_name, None)
    body = functools.partial(_ring_body, axis_name=axis_name,
                             causal=causal, scale=scale,
                             axis_size=axis_size)
    fn = _shard_map(body, mesh, (spec, spec, spec), spec)
    return fn(q, k, v)


def _shard_map(body, mesh, in_specs, out_specs):
    """shard_map across jax versions: ``jax.shard_map(check_vma=)``
    (new) vs ``jax.experimental.shard_map.shard_map(check_rep=)``
    (jax<=0.4.x) — replication checking is off either way (the ring
    body is explicitly collective)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(body, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as sm
    return sm(body, mesh=mesh, in_specs=in_specs,
              out_specs=out_specs, check_rep=False)
