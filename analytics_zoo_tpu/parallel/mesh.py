"""Device-mesh construction and sharding helpers.

The reference's distribution fabric is Spark: ``Engine.init`` discovers
node/core counts and ``AllReduceParameter`` partitions the parameter
vector across Spark block managers (SURVEY.md §2.4).  TPU-natively the
fabric is a ``jax.sharding.Mesh``: ICI links inside a slice, DCN between
slices, with XLA inserting collectives from sharding annotations.

Axis convention (outer → inner, fastest collectives innermost):

- ``data``  : pure data parallelism (gradient psum) — the reference's
              only training parallelism (wp-bigdl.md:113-171).
- ``fsdp``  : optional parameter/optimizer sharding (ZeRO-style) —
              a new TPU-native capability.
- ``model`` : tensor parallelism for wide layers.
- ``seq``   : sequence/context parallelism (ring attention).
- ``pipe``  : pipeline parallelism (GPipe microbatch schedule over
              ppermute — parallel/pipeline.py).
- ``expert``: expert parallelism for MoE layers (all_to_all token
              routing).

A 1-chip mesh is simply shape ``{"data": 1}`` — every code path is
written against the mesh so that single-chip and pod runs share code.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"

ALL_AXES = (DATA_AXIS, FSDP_AXIS, MODEL_AXIS, SEQ_AXIS, PIPE_AXIS,
            EXPERT_AXIS)


def create_mesh(shape: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh from an axis→size dict.

    ``shape=None`` puts every device on the ``data`` axis (matching the
    reference's pure-DP posture).  Axes with size 1 are still created so
    sharding specs can always name them.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if shape is None:
        shape = {DATA_AXIS: n}
    # Fill in implicit axes with size 1, preserving canonical order.
    sizes = {ax: int(shape.get(ax, 1)) for ax in ALL_AXES}
    # Allow a -1 wildcard on one axis.
    wild = [ax for ax, s in sizes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = math.prod(s for s in sizes.values() if s != -1)
    if wild:
        if n % fixed != 0:
            raise ValueError(
                f"cannot infer {wild[0]}: {n} devices not divisible by {fixed}")
        sizes[wild[0]] = n // fixed
    total = math.prod(sizes.values())
    if total != n:
        raise ValueError(
            f"mesh shape {sizes} needs {total} devices, have {n}")
    dims = [sizes[ax] for ax in ALL_AXES]
    arr = None
    if devices and getattr(devices[0], "platform", "") == "tpu" \
            and n > 1:
        # On real TPU pods, let mesh_utils lay devices out so inner
        # mesh axes ride ICI and the outermost (data) axis spans
        # DCN/slices — a plain reshape can put a model axis across
        # slice boundaries and turn every tensor-parallel collective
        # into a DCN hop.  Falls back to row-major on any failure
        # (virtual CPU meshes, exotic topologies).
        try:
            from jax.experimental import mesh_utils
            arr = mesh_utils.create_device_mesh(
                dims, devices=devices, allow_split_physical_axes=True)
        except Exception:
            arr = None
    if arr is None:
        arr = np.array(devices).reshape(dims)
    return Mesh(arr, ALL_AXES)


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard dim 0 across data(+fsdp) axes; replicate the rest.

    Batches are split over every data-parallel device, the way the
    reference splits an RDD's partitions across executors.
    """
    spec = [None] * ndim
    spec[0] = (DATA_AXIS, FSDP_AXIS)
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_shardings(mesh: Mesh, batch_pytree):
    """Per-leaf data shardings for an arbitrary batch pytree."""
    return jax.tree_util.tree_map(
        lambda x: data_sharding(mesh, np.ndim(x)), batch_pytree)


def fsdp_shardings(mesh: Mesh, params, min_size: int = 2 ** 12):
    """ZeRO-style sharding spec for a parameter pytree.

    Each large-enough leaf is sharded along its largest dimension that
    divides the fsdp axis size; small leaves replicate.  This is the
    TPU-native answer to the reference's *partitioned*
    ``AllReduceParameter`` (the parameter vector chunked across nodes,
    Topology.scala:1126-1128) — except here the optimizer update also
    runs sharded and XLA handles the gather.
    """
    axis = mesh.shape[FSDP_AXIS]

    def leaf_spec(x):
        if axis == 1 or x.size < min_size:
            return NamedSharding(mesh, P())
        dims = list(np.argsort(x.shape)[::-1])
        for d in dims:
            if x.shape[d] % axis == 0:
                spec = [None] * x.ndim
                spec[d] = FSDP_AXIS
                return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(leaf_spec, params)


def local_batch_size(mesh: Mesh, batch_size: int) -> int:
    """Per-device rows for ``batch_size``.

    Single-host: ``batch_size`` is the global batch.  Multi-host:
    ``batch_size`` is the PER-HOST batch (each process contributes its
    own slice of the global batch), so it must tile this host's share
    of the data-parallel degree.
    """
    dp = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
    nproc = jax.process_count()
    if nproc > 1 and dp % nproc == 0:
        dp = dp // nproc
    if batch_size % dp != 0:
        raise ValueError(
            f"batch {batch_size} not divisible by data-parallel "
            f"degree {dp}" + (" (per-host)" if nproc > 1 else ""))
    return batch_size // dp


def data_split_across_hosts(mesh: Mesh) -> bool:
    """True when the data axes divide across processes (each host feeds
    its own slice of the global batch); False means every host must
    feed IDENTICAL replicated batches.  The single source of truth for
    the host-splitting rule used by put_batch / epoch_scan_fn /
    benchmarks."""
    dp = mesh.shape[DATA_AXIS] * mesh.shape[FSDP_AXIS]
    nproc = jax.process_count()
    return nproc > 1 and dp % nproc == 0 and dp >= nproc


def global_batch_rows(mesh: Mesh, batch_size: int) -> int:
    """Rows of the GLOBAL batch for a per-host ``batch_size`` (equal to
    ``batch_size`` whenever hosts replicate instead of splitting)."""
    return batch_size * (jax.process_count()
                         if data_split_across_hosts(mesh) else 1)


def fetch_global(tree):
    """Bring a (possibly cross-process-sharded) pytree to host numpy.

    ``jax.device_get`` refuses arrays whose shards live on other
    processes' devices (e.g. fsdp-sharded params on a multi-host mesh);
    those leaves go through ``process_allgather`` instead — a
    collective, so EVERY process must call this together (the reference
    analogue is InternalDistriOptimizer.getModel pulling the
    AllReduceParameter chunks back to the driver, Topology.scala:1549).
    """
    def fetch(leaf):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            from jax.experimental import multihost_utils
            return multihost_utils.process_allgather(leaf, tiled=True)
        return jax.device_get(leaf)

    return jax.tree_util.tree_map(fetch, tree)
