"""The distributed training engine.

Reference: ``InternalDistriOptimizer`` (Topology.scala:1069-1598) — per
iteration it launches a Spark job that runs forward/backward on every
executor's model replicas, then syncs gradients through a partitioned
allreduce over the Spark BlockManager, applies the OptimMethod per
parameter chunk, and broadcasts updated weights back.

TPU redesign: the *entire* iteration is ONE jit-compiled XLA program
over the device mesh.  The batch is sharded on the ``data`` axis;
params/optimizer state are replicated (or fsdp-sharded); XLA inserts the
gradient all-reduce over ICI automatically from the sharding contract —
there is no hand-written communication.  Buffer donation makes the
update in-place in HBM.

Supports the reference's optimizer features: constant / L2-norm gradient
clipping (Topology.scala setConstantGradientClipping etc.), multiple
optim methods over disjoint parameter groups (Topology.scala:1130-1151),
and bf16 gradient sync (the analogue of BigDL's compressed FP16
parameter exchange).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.compile import engine_jit
from analytics_zoo_tpu.observability import get_registry, get_tracer
from analytics_zoo_tpu.observability.diagnostics import (
    get_compile_monitor, publish_mfu, step_attribution_histogram)
from analytics_zoo_tpu.observability.watchdog import (
    fold_finiteness_check)
from analytics_zoo_tpu.parallel import mesh as mesh_lib
from analytics_zoo_tpu.resilience.chaos import (
    SITE_TRAINER_DISPATCH, active_chaos)


def _record_grad_norm(gnorm) -> None:
    """Host callback target: surface the in-jit global grad norm as a
    gauge (debug.callback delivers a host copy after the step runs)."""
    try:
        get_registry().gauge(
            "train_grad_norm",
            "global L2 gradient norm (observability.grad_norm=true)"
        ).set(float(gnorm))
    except Exception:
        pass


@dataclasses.dataclass
class ClipSpec:
    kind: str          # "const" | "l2norm"
    a: float = 0.0
    b: float = 0.0


def _apply_clipping(grads, clip: Optional[ClipSpec]):
    if clip is None:
        return grads
    if clip.kind == "const":
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, clip.a, clip.b), grads)
    if clip.kind == "l2norm":
        gnorm = optax.global_norm(grads)
        scale = jnp.minimum(1.0, clip.a / (gnorm + 1e-12))
        return jax.tree_util.tree_map(lambda g: g * scale, grads)
    raise ValueError(clip.kind)


def mask_frozen_params(model, params, new_params):
    """Keep frozen layers' params bit-identical through an optimizer
    update (transfer learning): restoring the old leaves masks weight
    decay too, which plain gradient zeroing would not."""
    frozen = (model.frozen_layer_names()
              if hasattr(model, "frozen_layer_names") else set())
    if not frozen:
        return new_params
    return {k: (params[k] if k in frozen else v)
            for k, v in new_params.items()}


def _group_params(params, groups: Dict[str, Sequence[str]]):
    """Split a top-level params dict into named disjoint groups.

    ``groups`` maps group name -> list of top-level layer names; one
    group may be "*" (the rest).  Mirrors the reference's
    multi-optimMethod parameter splits (Topology.scala:1130-1151).
    """
    assigned = set()
    for names in groups.values():
        if names != "*":
            assigned.update(names)
    out = {}
    for gname, names in groups.items():
        if names == "*":
            out[gname] = [k for k in params if k not in assigned]
        else:
            out[gname] = list(names)
    return out


class DistributedTrainer:
    """Builds and runs the jitted train/eval/predict steps."""

    def __init__(self, model, loss_fn: Callable, optim_method=None,
                 mesh=None, clip: Optional[ClipSpec] = None,
                 optim_groups: Optional[Dict[str, Tuple[Any, Sequence[str]]]]
                 = None):
        from analytics_zoo_tpu.common.zoo_context import get_zoo_context
        self.model = model
        self.loss_fn = loss_fn
        self.optim = optim_method
        self.mesh = mesh if mesh is not None else get_zoo_context().mesh
        self.clip = clip
        self.optim_groups = optim_groups  # {name: (OptimMethod, layer_names)}
        cfg = get_config()
        self.donate = bool(cfg.get("train.donate"))
        self.remat = bool(cfg.get("train.remat"))
        self.grad_sync_dtype = str(cfg.get("train.grad_sync_dtype"))
        # fused optimizer update (ops/fused.py): clip + moment update +
        # param apply in ONE pass per leaf instead of the optax
        # global_norm → update → apply_updates triple traversal (three
        # full HBM sweeps of params+grads).  None = unsupported
        # (optimizer groups, exotic transform, or train.fused_optimizer
        # off) — the optax path below stays the source of truth.
        self._fused_update = None
        if (bool(cfg.get("train.fused_optimizer", True))
                and not self.optim_groups and self.optim is not None):
            from analytics_zoo_tpu.ops.fused import build_fused_update
            self._fused_update = build_fused_update(self.optim,
                                                    self.clip)
        self._train_step = None
        self._train_step_at = None
        self._eval_step = None
        self._predict_step = None
        self._permute_rows = None
        self._rep = mesh_lib.replicated(self.mesh)
        self._param_shardings = None
        # observability: shared-registry instruments for the hot path.
        # Per-step latency here is HOST dispatch-to-dispatch wall time —
        # device work is async, but donation + the dispatch queue make
        # it converge to device step time in steady state.
        reg = get_registry()
        self._m_step_latency = reg.histogram(
            "train_step_latency_seconds",
            "host wall time per dispatched train step (dispatch-to-"
            "dispatch; device work is async)", labels=("path",))
        self._m_steps = reg.counter(
            "train_steps_total", "train steps dispatched",
            labels=("path",))
        self._m_prefetch_depth = reg.gauge(
            "train_prefetch_queue_depth",
            "device-placed batches waiting in the prefetch queue")
        # grad-norm gauge costs an in-jit norm + host callback per step:
        # opt-in via config (observability.grad_norm)
        self._obs_grad_norm = bool(cfg.get("observability.grad_norm"))
        # training-health diagnostics: in-jit finite check (watchdog
        # NaN detector), sampled device-step bracket, compile monitor
        self._obs_check_finite = bool(
            cfg.get("observability.check_finite"))
        self._obs_device_every = int(
            cfg.get("observability.device_time_every") or 0)
        self._monitor = get_compile_monitor()
        self._m_step_time = step_attribution_histogram(reg)
        # cross-host skew instrumentation: at every sampled device
        # step on a multi-process run, time an explicit cluster
        # barrier — the wait is (max_host_step − my_step), so the
        # straggler reads ~0 while every other host reads the skew.
        # The aggregator's straggler report consumes this together
        # with per-host train_step_latency_seconds.
        self._obs_barrier_probe = bool(
            cfg.get("observability.barrier_probe", True))
        self._barrier_supported: Optional[bool] = None
        self._m_barrier_wait = reg.histogram(
            "train_barrier_wait_seconds",
            "sampled cross-host barrier wait after a train step "
            "(multi-host only): ~0 on the straggler, ~skew on the "
            "fastest host")
        # collective accounting: per-step psum/all-gather bytes implied
        # by the sharding contract (observability/collectives.py),
        # estimated once per params signature then counted per dispatch
        self._obs_collectives = bool(
            cfg.get("observability.collectives", True))
        self._collective_bytes = None
        self._m_device_step = reg.gauge(
            "train_device_step_seconds",
            "sampled dispatch->block_until_ready wall of one train "
            "step (observability.device_time_every)")
        # registered here so a scrape shows the gauge (at 0) even
        # before the first computable sample — see publish_mfu
        reg.gauge(
            "train_mfu",
            "model FLOPs utilisation: cost-analysis FLOPs / sampled "
            "device step time / chip peak (observability.peak_flops "
            "overrides the denominator)")
        self._dispatch_count = 0

    # ------------------------------------------------------------ sharding
    def param_shardings(self, params):
        """TP/FSDP/replicated sharding pytree for the model's params."""
        if self._param_shardings is None:
            from analytics_zoo_tpu.parallel.sharding import (
                collect_param_shardings)
            self._param_shardings = collect_param_shardings(
                self.model, params, self.mesh)
        return self._param_shardings

    def place_params(self, params):
        """Copy params onto the mesh per their TP/FSDP shardings.

        Multi-host: every process holds the full host copy (identical
        init / restored checkpoint), so each contributes its
        addressable shards via ``make_array_from_process_local_data``.
        """
        sh = self.param_shardings(params)
        if jax.process_count() > 1:
            return jax.tree_util.tree_map(
                lambda a, s: jax.make_array_from_process_local_data(
                    s, np.asarray(a), np.shape(a)), params, sh)
        return jax.tree_util.tree_map(
            lambda a, s: jax.device_put(jnp.array(a, copy=True), s),
            params, sh)

    def place_like(self, host_tree, like_tree):
        """Place host arrays with the shardings of a live device tree
        (checkpoint restore of sharded optimizer state)."""
        if jax.process_count() > 1:
            return jax.tree_util.tree_map(
                lambda a, ref: jax.make_array_from_process_local_data(
                    ref.sharding, np.asarray(a), np.shape(a)),
                host_tree, like_tree)
        return jax.tree_util.tree_map(
            lambda a, ref: jax.device_put(jnp.array(a, copy=True),
                                          ref.sharding),
            host_tree, like_tree)

    # ----------------------------------------------------------- optimizer
    def init_opt_state(self, params):
        """Jitted so optimizer-state leaves inherit the param shardings
        (GSPMD propagation) — sharded optimizer update, ZeRO-style."""
        def init(p):
            if self.optim_groups:
                groups = _group_params(
                    p, {k: v[1] for k, v in self.optim_groups.items()})
                return {
                    g: self.optim_groups[g][0].init(
                        {k: p[k] for k in names})
                    for g, names in groups.items()
                }
            return self.optim.init(p)

        out = engine_jit(init, key_hint="init_opt_state")(params)
        if jax.process_count() > 1:
            # multi-host jit outputs are already global arrays
            return out
        # leaves unrelated to any param (e.g. the step counter) may land
        # on a single device — normalize them onto the mesh
        mesh_devices = set(np.asarray(self.mesh.devices).flat)

        def fix(leaf):
            if isinstance(leaf, jax.Array) and \
                    set(leaf.sharding.device_set) != mesh_devices:
                return jax.device_put(leaf, self._rep)
            return leaf

        return jax.tree_util.tree_map(fix, out)

    @property
    def fused_optimizer_active(self) -> bool:
        """Whether steps run the single-pass fused update
        (ops/fused.py) instead of the optax triple traversal."""
        return self._fused_update is not None

    def _optimizer_update(self, grads, opt_state, params):
        if self.optim_groups:
            groups = _group_params(
                params, {k: v[1] for k, v in self.optim_groups.items()})
            new_params = dict(params)
            new_state = {}
            for g, names in groups.items():
                method = self.optim_groups[g][0]
                sub_p = {k: params[k] for k in names}
                sub_g = {k: grads[k] for k in names}
                updates, new_state[g] = method.update(
                    sub_g, opt_state[g], sub_p)
                upd = optax.apply_updates(sub_p, updates)
                new_params.update(upd)
            return new_params, new_state
        updates, new_state = self.optim.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state

    # ---------------------------------------------------------- train step
    def _step_core(self, params, opt_state, state, batch, rng):
        """One forward+backward+update — traced into both the per-step
        jit and the whole-epoch scan.

        Mixed precision is OP-LEVEL: the matmul/conv kernels cast their
        operands per ``dtype.compute`` (ops/dtypes.py policy), so bf16
        MXU compute with f32 master weights needs no whole-tree casting
        here."""
        model, loss_fn, clip = self.model, self.loss_fn, self.clip
        x, y = batch

        def objective(p):
            out, new_state = model.apply(p, x, state=state,
                                         training=True, rng=rng)
            loss = loss_fn(y, out)
            reg = model.regularization_loss(p)
            return loss + reg, (new_state, loss)

        if self.remat:
            # recompute the forward during the backward instead of
            # storing activations (train.remat) — see config.py
            objective = jax.checkpoint(objective)
        grads, (new_state, loss) = jax.grad(
            objective, has_aux=True)(params)
        if self._obs_grad_norm:
            # surfaces the norm on host after each step without
            # changing the step's signature; opt-in because the
            # callback costs a host round trip per step
            jax.debug.callback(_record_grad_norm,
                               optax.global_norm(grads))
        if self._obs_check_finite:
            # watchdog NaN/Inf detector, folded into the step's
            # program; the flag surfaces asynchronously through the
            # same callback path as the grad norm — the driver's
            # watchdog polls it between steps
            fold_finiteness_check(loss, grads)
        if self.grad_sync_dtype == "bfloat16":
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.bfloat16).astype(jnp.float32),
                grads)
        if self._fused_update is not None:
            # single-pass clip+moments+apply (ops/fused.py), numerically
            # the optax triple pass below — proven by
            # tests/test_fused_kernels.py
            new_params, new_opt_state = self._fused_update(
                grads, opt_state, params)
        else:
            grads = _apply_clipping(grads, clip)
            new_params, new_opt_state = self._optimizer_update(
                grads, opt_state, params)
        new_params = mask_frozen_params(model, params, new_params)
        return new_params, new_opt_state, new_state, loss

    def _build_train_step(self, fold_rng: bool = False):
        """One source of truth for the train-step jit spec; with
        ``fold_rng`` the program takes (.., rng, step) and derives the
        per-step rng in-jit."""
        donate = (0, 1, 2) if self.donate else ()
        if fold_rng:
            fn = lambda p, o, s, b, r, i: self._step_core(  # noqa: E731
                p, o, s, b, jax.random.fold_in(r, i))
        else:
            fn = self._step_core
        jitted = engine_jit(
            fn,
            out_shardings=(self._param_shardings, None, self._rep,
                           self._rep),
            donate_argnums=donate,
            key_hint="train_step_at" if fold_rng else "train_step")
        # compile/recompile accounting + cost-analysis FLOPs for the
        # live MFU gauge (diagnostics.CompileMonitor)
        return self._monitor.wrap("train_step", jitted)

    def _dispatch_instrumented(self, fn, *args):
        """One step dispatch wrapped in a train_step span + the
        per-step latency histogram and step counter.

        Step-time attribution: every dispatch observes its host wall
        (``host_dispatch``); every N-th dispatch additionally brackets
        dispatch→``block_until_ready`` (``device``) — one device sync
        on the sampled step only — and refreshes the live MFU gauge
        from the CompileMonitor's cost-analysis FLOPs."""
        chaos = active_chaos()
        if chaos is not None:
            # fault-injection site, keyed on this trainer's 0-based
            # dispatch index and tripped BEFORE the dispatch: a fault
            # at step k leaves exactly k committed steps and donates
            # no buffer to a doomed dispatch (resilience/chaos.py)
            chaos.trip(SITE_TRAINER_DISPATCH, self._dispatch_count)
        self._dispatch_count += 1
        sample_device = (self._obs_device_every > 0 and
                         self._dispatch_count % self._obs_device_every
                         == 0)
        if self._collective_bytes is None and args:
            self._collective_bytes = self._estimate_collectives(args[0])
        with get_tracer().span("train_step"):
            t0 = time.perf_counter()
            out = fn(*args)
            dispatch_s = time.perf_counter() - t0
            self._m_step_latency.labels("per_step").observe(dispatch_s)
            self._m_step_time.labels("host_dispatch").observe(
                dispatch_s)
            if sample_device:
                try:
                    jax.block_until_ready(out)
                    device_s = time.perf_counter() - t0
                except Exception:
                    device_s = None
                if device_s is not None:
                    self._m_step_time.labels("device").observe(device_s)
                    self._m_device_step.set(device_s)
                    publish_mfu("train_step", device_s)
                self._probe_barrier_wait()
        if self._collective_bytes:
            from analytics_zoo_tpu.observability.collectives import (
                record_step_collectives)
            record_step_collectives(self._collective_bytes)
        self._m_steps.labels("per_step").inc()
        return out

    def _estimate_collectives(self, params) -> Dict[str, float]:
        """One-time {op: bytes/step} estimate from the sharding
        contract; {} disables the per-dispatch accounting."""
        if not self._obs_collectives:
            return {}
        try:
            from analytics_zoo_tpu.observability.collectives import (
                estimate_train_step_collectives)
            return estimate_train_step_collectives(
                params, self.mesh, self.grad_sync_dtype)
        except Exception:
            return {}

    def account_collectives(self, params, steps: int) -> None:
        """Collective accounting for a FUSED dispatch of ``steps``
        steps (the chunked / epoch-scan paths, which bypass
        ``_dispatch_instrumented``): the per-step traffic is identical
        regardless of dispatch shape, so the counters stay comparable
        across engines.  Never raises."""
        if self._collective_bytes is None:
            self._collective_bytes = self._estimate_collectives(params)
        if self._collective_bytes and steps > 0:
            from analytics_zoo_tpu.observability.collectives import (
                record_step_collectives)
            record_step_collectives(self._collective_bytes,
                                    steps=steps)

    def _probe_barrier_wait(self) -> None:
        """Time a cross-host barrier on the sampled step (multi-host
        only): my wait = slowest host's remaining step time, the
        direct skew signal the aggregator attributes stragglers from.
        Piggybacks on the device-sample cadence so every process hits
        the barrier on the same dispatch count."""
        if not self._obs_barrier_probe or jax.process_count() <= 1 \
                or self._barrier_supported is False:
            return
        if self._barrier_supported is None:
            # capability gate, decided at the FIRST sampled step only:
            # every host reaches it at the same dispatch count, and a
            # does-this-backend-support-it failure is symmetric, so
            # all hosts disable together — participation stays in
            # lockstep
            try:
                from jax.experimental import multihost_utils
                t0 = time.perf_counter()
                multihost_utils.sync_global_devices(
                    "zoo_obs_barrier_probe")
                self._m_barrier_wait.observe(time.perf_counter() - t0)
                self._barrier_supported = True
            except Exception:
                self._barrier_supported = False
                import logging
                logging.getLogger(
                    "analytics_zoo_tpu.observability").exception(
                    "cross-host barrier probe unsupported here; "
                    "disabling it (straggler attribution loses the "
                    "barrier-wait signal)")
            return
        # past the gate, a failure means the collective fabric broke
        # mid-run: swallowing it would DESYNC the sampled barrier
        # (peers park waiting for us → cluster-wide silent hang), so
        # let it propagate into the step loop like any other
        # collective failure — the retry/failure machinery owns it
        from jax.experimental import multihost_utils
        t0 = time.perf_counter()
        multihost_utils.sync_global_devices("zoo_obs_barrier_probe")
        self._m_barrier_wait.observe(time.perf_counter() - t0)

    def train_step(self, params, opt_state, state, batch, rng):
        """Run one step; ``batch`` must already be device-placed
        (see ``prefetch``/``put_batch``)."""
        if self._train_step is None:
            self._train_step = self._build_train_step()
        return self._dispatch_instrumented(
            self._train_step, params, opt_state, state, batch, rng)

    def train_step_at(self, params, opt_state, state, batch, rng, step):
        """``train_step`` with the per-step rng derived IN-JIT:
        equivalent to ``train_step(..., fold_in(rng, step))`` but
        without dispatching a separate fold_in op per step (one extra
        round trip each over a tunneled backend).  ``step`` must be a
        numpy scalar (traced arg — a Python int would retrace)."""
        if self._train_step_at is None:
            self._train_step_at = self._build_train_step(fold_rng=True)
        return self._dispatch_instrumented(
            self._train_step_at, params, opt_state, state, batch, rng,
            step)

    # ----------------------------------------------------- AOT warm-start
    def warm_start(self, params, opt_state, state, host_batch,
                   rng) -> bool:
        """Pre-lower-and-compile (or cache-load) the per-step train
        program BEFORE the first real batch arrives, so the compile —
        or the ~seconds deserialize from a warm executable cache — is
        paid at startup where it is attributable, not inside the first
        training step.

        ``params``/``opt_state``/``state`` are the live device trees
        (their shardings are part of the program signature);
        ``host_batch`` is one representative HOST batch — it is
        device-placed exactly like a real step's batch (``put_batch``)
        so the warmed signature is bit-for-bit the one the training
        loop will dispatch.  Nothing is executed and nothing is
        donated.  Returns whether an AOT executable is in place
        (False = the plain jit path will compile lazily — never an
        error)."""
        try:
            if self._train_step_at is None:
                self._train_step_at = self._build_train_step(
                    fold_rng=True)
            batch = self.put_batch(host_batch)
            with get_tracer().span("aot_warm_start"):
                # _MonitoredJit forwards .warm to the EngineJit
                return bool(self._train_step_at.warm(
                    params, opt_state, state, batch, rng, np.int32(0)))
        except Exception:   # noqa: BLE001 — warm-start is best-effort
            import logging
            logging.getLogger("analytics_zoo_tpu.compile").debug(
                "train-step warm start failed; compiling lazily",
                exc_info=True)
            return False

    # ------------------------------------------------- device-resident epoch
    def epoch_scan_fn(self, num_batches: int, batch_size: int,
                      unroll: int = 1):
        """Whole-epoch trainer over DEVICE-RESIDENT data — the HBM tier
        of the FeatureSet cache hierarchy (the reference's DRAM cache,
        FeatureSet.scala:229-329, moved all the way onto the chip).

        One ``lax.scan`` runs ``num_batches`` steps with zero host
        involvement: no per-step dispatch, no H2D transfers.  Batches
        are contiguous slices of the (host-preshuffled) epoch arrays.
        Returns ``f(params, opt_state, state, x, y, rng) ->
        (params, opt_state, state, mean_loss)``.

        ``batch_size`` is PER-HOST, matching the per-step
        ``put_batch`` convention: when the data axes divide across
        processes, ``put_epoch`` builds a global epoch array of
        ``local_rows * process_count`` rows and each scan step slices
        the GLOBAL batch of ``batch_size * process_count`` rows —
        ``num_batches`` (= per-host rows // batch_size) steps then
        consume exactly the whole epoch.  When ``put_batch`` falls back
        to REPLICATING (dp doesn't divide across hosts), global rows ==
        local rows and the slice stays ``batch_size``.
        """
        local_bs = mesh_lib.local_batch_size(self.mesh, batch_size)
        del local_bs   # validation only
        global_bs = mesh_lib.global_batch_rows(self.mesh, batch_size)
        # multi-host: make_array_from_process_local_data lays the global
        # epoch out as CONTIGUOUS PER-HOST BLOCKS ([host0 rows][host1
        # rows]...), so step i must gather each host's rows
        # [i*bs:(i+1)*bs] from within its own block — a flat
        # [i*global_bs:(i+1)*global_bs] slice would hand step i ONE
        # host's data. The block-local slice is communication-free
        # (every device slices rows it already holds) and reproduces
        # the per-step put_batch batch composition exactly.
        nproc = jax.process_count() \
            if mesh_lib.data_split_across_hosts(self.mesh) else 1

        def epoch(params, opt_state, state, x, y, rng, start_step=0):
            # rng for step i is fold_in(rng, start_step + i): with
            # start_step = the global iteration counter this matches
            # the per-step path's fold_in(rng, ts.iteration) exactly,
            # so chunked dispatch is a pure performance knob — same
            # rng stream, same batches, same updates
            def body(carry, i):
                params, opt_state, state = carry

                def take(a):
                    if nproc > 1:
                        r = a.reshape((nproc, num_batches, batch_size)
                                      + a.shape[1:])
                        blk = jax.lax.dynamic_slice_in_dim(r, i, 1,
                                                           axis=1)
                        return blk.reshape((nproc * batch_size,)
                                           + a.shape[1:])
                    return jax.lax.dynamic_slice_in_dim(
                        a, i * global_bs, global_bs, axis=0)
                batch = (jax.tree_util.tree_map(take, x),
                         jax.tree_util.tree_map(take, y))
                params, opt_state, state, loss = self._step_core(
                    params, opt_state, state, batch,
                    jax.random.fold_in(rng, start_step + i))
                return (params, opt_state, state), loss

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state),
                jnp.arange(num_batches), unroll=unroll)
            return params, opt_state, state, losses.mean()

        donate = (0, 1, 2) if self.donate else ()
        jitted = engine_jit(
            epoch,
            out_shardings=(self._param_shardings, None, self._rep,
                           self._rep),
            donate_argnums=donate, key_hint="train_epoch_scan")
        # cost analysis counts the scan BODY once (~ one step), so the
        # monitor's flops gauge stays per-step-comparable
        return self._monitor.wrap("train_epoch_scan", jitted)

    def put_epoch(self, x, y, epoch: int, feature_set=None):
        """Device-place a whole epoch, sharded on the data axis.

        If ``feature_set`` is given, its deterministic per-epoch
        permutation is applied host-side first (one gather per epoch
        instead of one per step).  Placement goes through
        ``put_epoch_source`` so ragged row counts pad-and-shard
        instead of silently replicating; ``epoch_scan_fn`` never
        reaches the padded rows (its ``num_batches`` covers only whole
        real batches)."""
        if feature_set is not None and feature_set.shuffle:
            perm = feature_set._epoch_perm(epoch)
            take = lambda a: a[perm]
            x = jax.tree_util.tree_map(take, x)
            y = jax.tree_util.tree_map(take, y) if y is not None else None
        return self.put_epoch_source(x, y)

    def put_epoch_source(self, x, y):
        """Place the UNPERMUTED whole dataset on device once — the HBM
        cache tier of the FeatureSet hierarchy (the reference's DRAM
        cache, FeatureSet.scala:585-662, promoted into device memory).

        Rows are zero-padded up to a multiple of the data-parallel
        width so ``put_batch`` SHARDS the source instead of falling
        back to replication; padded rows are never consumed — every
        epoch permutation only indexes the real ``n`` rows, and
        ``epoch_scan_fn``'s ``num_batches`` covers only whole real
        batches.  Padding applies single-process only: the multi-host
        ``epoch_scan_fn`` layout reshapes each host block to exactly
        ``num_batches * batch_size`` rows, which padding would break
        (multi-host callers already size their rows to the mesh)."""
        dp = self.mesh.shape[mesh_lib.DATA_AXIS] * \
            self.mesh.shape[mesh_lib.FSDP_AXIS]
        from analytics_zoo_tpu.feature.feature_set import pad_rows
        n = len(jax.tree_util.tree_leaves(x)[0])
        if jax.process_count() > 1:
            if mesh_lib.data_split_across_hosts(self.mesh):
                local_dp = dp // jax.process_count()
                if n % local_dp:
                    # multi-host rows must tile the mesh EXACTLY: the
                    # multi-host epoch_scan_fn layout reshapes each
                    # host block to num_batches * batch_size rows,
                    # which padding would break — refuse HERE with
                    # epoch-level context rather than letting
                    # put_batch raise its per-batch message deep in
                    # the placement
                    raise ValueError(
                        f"put_epoch_source: this host's {n} rows do "
                        f"not tile its data-parallel share "
                        f"({local_dp} of the {dp}-way data axes "
                        f"across {jax.process_count()} processes); "
                        f"pad or trim each host's rows to a multiple "
                        f"of {local_dp} (single-process callers are "
                        "padded automatically)")
            # non-split meshes replicate the epoch (put_batch's
            # replica branch) — no tiling requirement, no padding
            pad = 0
        else:
            pad = (-n) % dp
        if pad:
            x = pad_rows(x, pad)
            y = pad_rows(y, pad) if y is not None else None
        return self.put_batch((x, y))

    def permute_rows_fn(self):
        """Jitted DEVICE-SIDE row gather ``(x, y, perm) -> (x[perm],
        y[perm])`` with outputs sharded on the data axes.

        One on-device gather per epoch replaces re-transferring the
        whole (host-permuted) epoch over H2D — the per-epoch cost
        drops from epoch-bytes over the host link to an int32 index
        upload. The permutation values come from the FeatureSet's own
        deterministic per-epoch rng, so batch composition is
        bit-identical to the per-step / chunked paths."""
        if self._permute_rows is None:
            mesh = self.mesh

            def permute(x, y, perm):
                def take(a):
                    out = jnp.take(a, perm, axis=0)
                    return jax.lax.with_sharding_constraint(
                        out, mesh_lib.data_sharding(mesh, out.ndim))
                xe = jax.tree_util.tree_map(take, x)
                ye = jax.tree_util.tree_map(take, y) \
                    if y is not None else None
                return xe, ye

            self._permute_rows = engine_jit(permute,
                                            key_hint="permute_rows")
        return self._permute_rows

    # ----------------------------------------------------------- eval step
    def _build_eval_step(self, metrics):
        model = self.model

        def step(params, state, batch):
            x, y, mask = batch
            out, _ = model.apply(params, x, state=state, training=False)
            return tuple(m.batch_update(y, out, mask) for m in metrics)

        return engine_jit(step, out_shardings=self._rep,
                          key_hint="eval_step")

    def make_eval_runner(self, metrics):
        from analytics_zoo_tpu.pipeline.api.keras.metrics import accumulate
        step = self._build_eval_step(metrics)

        def run(params, state, batches):
            return accumulate(
                metrics, (step(params, state, batch)
                          for batch in self.prefetch(batches)))
        return run

    # -------------------------------------------------------- predict step
    def predict_fn(self):
        model = self.model
        if self._predict_step is None:
            def step(params, state, x):
                out, _ = model.apply(params, x, state=state, training=False)
                return out
            self._predict_step = engine_jit(step,
                                            out_shardings=self._rep,
                                            key_hint="predict_step")
        return self._predict_step

    # ------------------------------------------------------- data movement
    def put_batch(self, batch):
        """Place a host batch onto the mesh, sharded on the data axis.

        Single-host path: ``device_put`` with NamedSharding.  Multi-host
        path: ``jax.make_array_from_process_local_data`` — the per-host
        FeatureSet shard becomes this host's slice of the global batch
        (so the effective global batch = per-host batch x processes).

        Leaves whose leading dim doesn't tile the data axis (e.g. a
        group-aligned ranking-eval batch) are replicated instead — same
        math, no shard speedup for that batch.
        """
        dp = self.mesh.shape[mesh_lib.DATA_AXIS] * \
            self.mesh.shape[mesh_lib.FSDP_AXIS]
        nproc = jax.process_count()
        # data axes spread across processes only when they divide evenly;
        # otherwise (e.g. pure model-parallel, dp=1 over 2 hosts) every
        # host must feed the IDENTICAL batch, which is replicated below.
        data_split_across_hosts = mesh_lib.data_split_across_hosts(
            self.mesh)
        local_dp = dp // nproc if data_split_across_hosts else dp

        def put(a):
            if a is None:
                return None
            if nproc > 1:
                a = np.asarray(a)
                if a.ndim == 0 or not data_split_across_hosts:
                    # replica semantics: hosts must pass identical data
                    # (make_array_from_process_local_data requires it
                    # when global_shape == local shape)
                    return jax.make_array_from_process_local_data(
                        self._rep, a, a.shape)
                if a.shape[0] % local_dp != 0:
                    # replicating per-host-DIFFERENT rows would silently
                    # disagree across processes, and mixing global dims
                    # within one batch breaks the jitted step — refuse.
                    raise ValueError(
                        f"multi-host batch dim {a.shape[0]} must tile "
                        f"this host's data-parallel share {local_dp}")
                # this process's rows are one slice of the global batch
                return jax.make_array_from_process_local_data(
                    mesh_lib.data_sharding(self.mesh, a.ndim), a,
                    (a.shape[0] * nproc,) + a.shape[1:])
            if np.ndim(a) == 0 or np.shape(a)[0] % dp != 0:
                return jax.device_put(a, self._rep)
            return jax.device_put(
                a, mesh_lib.data_sharding(self.mesh, np.ndim(a)))

        return jax.tree_util.tree_map(
            put, batch, is_leaf=lambda v: v is None)

    def replicate(self, tree):
        """Replicate a pytree across the mesh, always copying: the train
        step donates its inputs, and ``device_put`` may alias an
        already-device-resident array — donating an alias would delete
        the caller's buffer."""
        if jax.process_count() > 1:
            return jax.tree_util.tree_map(
                lambda a: jax.make_array_from_process_local_data(
                    self._rep, np.asarray(a), np.shape(a)), tree)
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.array(a, copy=True), self._rep),
            tree)

    def prefetch(self, batches, depth: Optional[int] = None):
        """Overlap host batch assembly + H2D transfer with device compute.

        A background thread pulls host batches, places them on the mesh
        (``put_batch``) and queues them ``depth`` deep — the analogue of
        the reference's MTSampleToMiniBatch worker threads feeding the
        training tasks (MTSampleToMiniBatch.scala:28).
        """
        import queue
        import threading
        if depth is None:
            depth = int(get_config().get("data.prefetch"))
        wait_hist = self._m_step_time.labels("data_wait")
        if depth <= 0:
            it = iter(batches)
            while True:
                # data_wait here covers host batch assembly + H2D —
                # the whole input-side cost the device waits on
                t0 = time.perf_counter()
                try:
                    b = next(it)
                except StopIteration:
                    return
                placed = self.put_batch(b)
                wait_hist.observe(time.perf_counter() - t0)
                yield placed
        q: "queue.Queue" = queue.Queue(maxsize=depth)
        _END = object()

        def worker():
            try:
                for b in batches:
                    q.put(self.put_batch(b))
                q.put(_END)
            except BaseException as e:   # propagate into consumer
                q.put(e)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            # sampled before the dequeue so a full steady-state
            # pipeline reads `depth`, not depth-1
            self._m_prefetch_depth.set(q.qsize())
            t0 = time.perf_counter()
            item = q.get()
            if item is _END:
                self._m_prefetch_depth.set(0)
                break
            if isinstance(item, BaseException):
                raise item
            # attribution: how long the consumer stalled waiting for
            # the next device-placed batch (0 ≈ input keeps up)
            wait_hist.observe(time.perf_counter() - t0)
            yield item
