from analytics_zoo_tpu.parallel.mesh import (
    create_mesh,
    data_sharding,
    replicated,
    batch_shardings,
    fsdp_shardings,
    local_batch_size,
    DATA_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
)

__all__ = [
    "create_mesh",
    "data_sharding",
    "replicated",
    "batch_shardings",
    "fsdp_shardings",
    "local_batch_size",
    "DATA_AXIS",
    "FSDP_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
]
