"""Pipeline parallelism — GPipe microbatch schedule over the ``pipe``
mesh axis.

No reference analogue (the reference scales data-parallel only,
wp-bigdl.md:113-171); this is TPU-native capability in the style of the
public scaling-book/praxis SPMD pipelining recipe: every stage runs the
SAME program under ``shard_map``; stage identity comes from
``jax.lax.axis_index("pipe")``, activations advance one stage per tick
via ``ppermute`` over ICI, and ``jax.grad`` differentiates straight
through the schedule (the transpose of a ppermute is the reverse
ppermute).

Semantics: ``pipeline_apply(stage_fn, stacked_params, x)`` computes

    stage_{P-1}( ... stage_1(stage_0(x)) ... )

for P pipeline stages whose activations share one shape.  The batch is
split into M microbatches; wall-clock fills/drains the classic
``M + P - 1`` ticks.  Stage parameters are stacked on a leading axis
sharded over ``pipe`` — each device materialises only its own stage's
weights (P-way parameter sharding, the pipeline analogue of FSDP).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel import mesh as mesh_lib


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] with identical structure →
    one tree with a leading stage axis (shard it over ``pipe``)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def stage_param_sharding(mesh, stacked_params):
    """NamedSharding placing the leading stage axis on ``pipe``."""
    shard = NamedSharding(mesh, P(mesh_lib.PIPE_AXIS))
    return jax.tree_util.tree_map(lambda _: shard, stacked_params)


def _spmd_pipeline(stage_fn: Callable, params, x, *, num_stages: int,
                   num_microbatches: int):
    """Runs INSIDE shard_map: ``params`` is this device's stage params
    (leading stage axis already sharded away to size 1), ``x`` is the
    full local batch on every stage (replicated over pipe)."""
    m = num_microbatches
    p = num_stages
    stage = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
    params = jax.tree_util.tree_map(lambda a: a[0], params)

    mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])
    state = jnp.zeros_like(mb[0])           # activation entering stage
    outputs = jnp.zeros_like(mb)            # collected on last stage

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (when one remains)
        inject = jnp.where(t < m, t, 0)
        state = jnp.where(stage == 0, mb[inject], state)
        y = stage_fn(params, state)
        # last stage banks microbatch (t - (p-1)) when it's valid
        out_slot = jnp.clip(t - (p - 1), 0, m - 1)
        bank = (stage == p - 1) & (t >= p - 1)
        outputs = jax.lax.dynamic_update_index_in_dim(
            outputs, jnp.where(bank, y, outputs[out_slot]), out_slot,
            axis=0)
        # advance the baton: stage i's output becomes stage i+1's input
        state = jax.lax.ppermute(
            y, mesh_lib.PIPE_AXIS,
            [(i, (i + 1) % p) for i in range(p)])
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(
        tick, (state, outputs), jnp.arange(m + p - 1))
    # broadcast the last stage's collected outputs to every stage so
    # the loss (and psum'd grads) are computed identically everywhere
    outputs = jax.lax.ppermute(
        outputs, mesh_lib.PIPE_AXIS,
        [(i, (i + 1) % p) for i in range(p)])     # last -> 0
    outputs = _pipe_broadcast(outputs, src=0, p=p)
    return outputs.reshape((outputs.shape[0] * outputs.shape[1],)
                           + outputs.shape[2:])


def _pipe_broadcast(v, src: int, p: int):
    """Broadcast ``v`` from stage ``src`` to all stages (log-step
    ppermute chain is overkill at typical P — one rotation per hop)."""
    out = v
    for _ in range(p - 1):
        rolled = jax.lax.ppermute(
            out, mesh_lib.PIPE_AXIS, [(i, (i + 1) % p) for i in range(p)])
        stage = jax.lax.axis_index(mesh_lib.PIPE_AXIS)
        out = jnp.where(stage == src, out, rolled)
    return out


def _record_schedule_metrics(p: int, m: int, x) -> None:
    """Host-side schedule accounting per ``pipeline_apply``: the GPipe
    fill/drain bubble is exact from the schedule — ``P-1`` of the
    ``M+P-1`` ticks per stage are idle — and every tick ppermutes one
    microbatch of activations over ICI.  Feeds the aggregator's
    bubble-fraction and collective sections; never raises."""
    try:
        from analytics_zoo_tpu.observability import get_registry
        from analytics_zoo_tpu.observability.collectives import (
            BYTES_PER_STEP_HELP, estimate_pipeline_ppermute_bytes,
            record_step_collectives)
        reg = get_registry()
        ticks = m + p - 1
        bubble = (p - 1) / ticks
        lab = reg.gauge(
            "pipeline_num_stages",
            "pipeline stages (pipe mesh axis) of the last apply")
        lab.set(p)
        reg.gauge(
            "pipeline_num_microbatches",
            "microbatches per pipeline_apply").set(m)
        reg.gauge(
            "pipeline_bubble_fraction",
            "GPipe fill/drain bubble: (P-1)/(M+P-1) of each stage's "
            "ticks are idle — raise num_microbatches to amortize"
        ).set(bubble)
        mb_bytes = (x.size // m) * x.dtype.itemsize
        ppermute_bytes = estimate_pipeline_ppermute_bytes(mb_bytes, p, m)
        if isinstance(x, jax.core.Tracer):
            # under tracing this site runs once per COMPILE, not per
            # step — counting there would undercount wildly, so only
            # the per-apply estimate gauge is refreshed
            reg.gauge("collective_bytes_per_step", BYTES_PER_STEP_HELP,
                      labels=("op",)).labels("ppermute").set(
                          ppermute_bytes)
        else:
            record_step_collectives({"ppermute": ppermute_bytes})
    except Exception:
        pass


def pipeline_apply(stage_fn: Callable, stacked_params, x, mesh,
                   num_microbatches: int):
    """Forward through the pipeline; differentiable end-to-end.

    ``stage_fn(params, h) -> h`` is ONE stage's computation (all stages
    share code; weights differ).  ``stacked_params`` carries the
    leading stage axis (shard with ``stage_param_sharding``).
    ``x``: (B, ...) with B divisible by ``num_microbatches``.
    Returns the last stage's outputs, replicated over ``pipe``.
    """
    p = mesh.shape[mesh_lib.PIPE_AXIS]
    if p == 1:
        params0 = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        return stage_fn(params0, x)

    _record_schedule_metrics(p, num_microbatches, x)
    fn = functools.partial(_spmd_pipeline, stage_fn, num_stages=p,
                           num_microbatches=num_microbatches)
    pspec_params = jax.tree_util.tree_map(
        lambda _: P(mesh_lib.PIPE_AXIS), stacked_params)
    return jax.shard_map(
        fn, mesh=mesh,
        in_specs=(pspec_params, P()),
        out_specs=P(),
        check_vma=False,
    )(stacked_params, x)
