"""``zoo-launch`` CLI: spawn an N-process jax.distributed job on this
machine (the spark-submit launcher-script role, reference
scripts/spark-submit-python-with-zoo.sh + RayOnSpark bootstrap).

Usage: ``python -m analytics_zoo_tpu.parallel.launch_cli -n 4
script.py [args...]``.  Each worker gets ZOO_TPU_COORDINATOR /
NUM_PROCESSES / PROCESS_ID and should call ``init_zoo_context()``.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
    p = argparse.ArgumentParser(prog="zoo-launch")
    p.add_argument("-n", "--num-processes", type=int, default=1)
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 (default: local free port)")
    p.add_argument("--timeout", type=float, default=None,
                   help="seconds to wait before killing stragglers")
    p.add_argument("--run-dir", default=None,
                   help="observability run directory: each worker "
                        "gets a host-<k>/ metrics slot + port and a "
                        "shared clock anchor; aggregate with "
                        "scripts/obs_report.py --merge-hosts")
    p.add_argument("--max-degraded", type=int, default=0,
                   help="exit 0 when at most this many workers exit "
                        "DEGRADED (code 17: checkpoint-and-queue, a "
                        "structured partial result) and the rest "
                        "exit 0")
    p.add_argument("script")
    p.add_argument("args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    import os
    import subprocess

    from analytics_zoo_tpu.parallel.launcher import ZooCluster
    # CLI convenience (the spark-submit --py-files role): python puts
    # the SCRIPT's dir on a worker's sys.path, not the launch cwd —
    # propagate the cwd so `zoo-launch -n 4 train.py` resolves the
    # same imports the launcher shell does.  CLI-only: ZooCluster as
    # a library leaves worker import paths alone.
    env = {"PYTHONPATH": os.getcwd() + (
        os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else "")}
    cluster = ZooCluster(num_processes=args.num_processes,
                         coordinator=args.coordinator,
                         run_dir=args.run_dir, env=env)
    import json

    from analytics_zoo_tpu.resilience.policy import DEGRADED_EXIT_CODE
    cluster.start(args.script, args.args)
    try:
        codes = cluster.wait(timeout=args.timeout)
    except subprocess.TimeoutExpired:
        health = cluster.check_health()
        print(f"workers still running after {args.timeout}s; "
              "killing stragglers", file=sys.stderr)
        # structured record instead of a bare timeout: which host
        # died first (the cause — the rest is collective collateral)
        print(json.dumps({"status": "timeout",
                          "first_failure": health.first_death,
                          "missing": health.missing,
                          "alive": health.alive}))
        return 1
    finally:
        cluster.stop()
    degraded = [i for i, c in enumerate(codes)
                if c == DEGRADED_EXIT_CODE]
    bad = [c for c in codes if c not in (0, DEGRADED_EXIT_CODE)]
    if bad:
        print(f"workers exited with codes {list(codes)}; first "
              f"failure: {codes.first_failure}", file=sys.stderr)
        print(json.dumps({"status": "failed", "codes": list(codes),
                          "first_failure": codes.first_failure}))
        return 1
    if degraded:
        # checkpoint-and-queue workers (resilience.policy
        # DEGRADED_EXIT_CODE): a structured partial result, not a
        # crash — exit 0 within the --max-degraded budget
        within = len(degraded) <= args.max_degraded
        print(json.dumps({"status": "degraded",
                          "degraded_workers": degraded,
                          "codes": list(codes),
                          "max_degraded": args.max_degraded,
                          "within_budget": within}))
        return 0 if within else 1
    print(f"{args.num_processes} workers completed")
    if args.run_dir:
        print(f"observability run dir: {args.run_dir} — merge with "
              f"`python scripts/obs_report.py --merge-hosts "
              f"{args.run_dir}`")
    return 0


if __name__ == "__main__":
    sys.exit(main())
