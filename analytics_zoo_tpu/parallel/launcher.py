"""Multi-host job launcher — the RayOnSpark analogue.

Reference: pyzoo/zoo/ray/raycontext.py — a Spark barrier stage starts
``ray start`` on every executor (gen_ray_start :155), ``JVMGuard``
(:32) kills the ray processes if the parent JVM dies, and
``ProcessMonitor`` tracks pids.

TPU version: the cluster fabric is ``jax.distributed`` — the launcher
spawns one worker process per host (or simulates N hosts on one
machine), injects the coordinator env that ``init_zoo_context`` consumes
(ZOO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID), and guards children
with PR_SET_PDEATHSIG so they die with the launcher, plus atexit
cleanup.
"""

from __future__ import annotations

import atexit
import ctypes
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _set_pdeathsig():
    """Child dies when the launcher dies (the JVMGuard role)."""
    try:
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:       # pragma: no cover - non-linux
        pass


class ProcessMonitor:
    """Track spawned workers; kill them all on exit
    (raycontext.py ProcessMonitor + JVMGuard)."""

    def __init__(self):
        self.procs: List[subprocess.Popen] = []
        atexit.register(self.stop_all)

    def register(self, proc: subprocess.Popen) -> None:
        self.procs.append(proc)

    def stop_all(self, timeout: float = 5.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + timeout
        for p in self.procs:
            try:
                p.wait(max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()

    def alive(self) -> int:
        return sum(1 for p in self.procs if p.poll() is None)


class ZooCluster:
    """Launch ``script`` as an N-process jax.distributed job.

    Each worker sees ZOO_TPU_COORDINATOR / ZOO_TPU_NUM_PROCESSES /
    ZOO_TPU_PROCESS_ID and calls ``init_zoo_context()`` which performs
    the ``jax.distributed.initialize`` handshake — the Engine.init /
    barrier-stage role of the reference.
    """

    def __init__(self, num_processes: int,
                 coordinator: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None):
        self.num_processes = int(num_processes)
        self.coordinator = coordinator or \
            f"localhost:{_free_port()}"
        self.extra_env = env or {}
        self.monitor = ProcessMonitor()

    def worker_env(self, process_id: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update({
            "ZOO_TPU_COORDINATOR": self.coordinator,
            "ZOO_TPU_NUM_PROCESSES": str(self.num_processes),
            "ZOO_TPU_PROCESS_ID": str(process_id),
        })
        return env

    def start(self, script: str, args: Sequence[str] = ()) -> None:
        for pid in range(self.num_processes):
            proc = subprocess.Popen(
                [sys.executable, script, *args],
                env=self.worker_env(pid),
                preexec_fn=_set_pdeathsig,
            )
            self.monitor.register(proc)

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        codes = []
        deadline = None if timeout is None else time.time() + timeout
        for p in self.monitor.procs:
            remaining = None if deadline is None else \
                max(deadline - time.time(), 0.1)
            codes.append(p.wait(remaining))
        return codes

    def stop(self) -> None:
        self.monitor.stop_all()
