"""Multi-host job launcher — the RayOnSpark analogue.

Reference: pyzoo/zoo/ray/raycontext.py — a Spark barrier stage starts
``ray start`` on every executor (gen_ray_start :155), ``JVMGuard``
(:32) kills the ray processes if the parent JVM dies, and
``ProcessMonitor`` tracks pids.

TPU version: the cluster fabric is ``jax.distributed`` — the launcher
spawns one worker process per host (or simulates N hosts on one
machine), injects the coordinator env that ``init_zoo_context`` consumes
(ZOO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID), and guards children
with PR_SET_PDEATHSIG so they die with the launcher, plus atexit
cleanup.

Observability plane: passing ``run_dir`` makes the launcher the
cluster's rendezvous for fleet-level metrics — it creates one
``host-<k>/`` slot per worker, pre-allocates a metrics port each,
broadcasts a shared clock anchor (so per-host Chrome traces align on
one epoch), and writes a ``cluster.json`` manifest that host 0's
aggregator and ``obs_report.py --merge-hosts`` both read.  Workers
pick the contract up from ZOO_TPU_RUN_DIR / ZOO_TPU_METRICS_DIR /
ZOO_TPU_METRICS_PORT / ZOO_TPU_CLOCK_ANCHOR via
``observability.aggregator.init_worker_observability`` (called by
``init_zoo_context``).
"""

from __future__ import annotations

import atexit
import ctypes
import dataclasses
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

log = logging.getLogger("analytics_zoo_tpu.launcher")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _set_pdeathsig():
    """Child dies when the launcher dies (the JVMGuard role)."""
    try:
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:       # pragma: no cover - non-linux
        pass


class ProcessMonitor:
    """Track spawned workers; kill them all on exit
    (raycontext.py ProcessMonitor + JVMGuard)."""

    def __init__(self):
        self.procs: List[subprocess.Popen] = []
        self.indices: List[int] = []
        # exit codes observed by stop_all/poll, by process index —
        # kept after procs are cleared so post-mortems still classify
        self.exit_codes: Dict[int, Optional[int]] = {}
        atexit.register(self.stop_all)

    def register(self, proc: subprocess.Popen,
                 index: Optional[int] = None) -> None:
        self.indices.append(len(self.procs) if index is None
                            else int(index))
        self.procs.append(proc)

    def stop_all(self, timeout: float = 5.0,
                 kill_grace: float = 2.0) -> Dict[int, Optional[int]]:
        """TERM every worker, then escalate to KILL *per process* and
        reap each one — a worker that ignores/blocks SIGTERM gets
        SIGKILLed and still gets waited on, so no zombie survives a
        hang.  Returns {process_index: exit code} (None only for a
        truly unkillable process, e.g. stuck in uninterruptible IO)."""
        codes: Dict[int, Optional[int]] = {}
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + timeout
        for idx, p in zip(self.indices, self.procs):
            try:
                codes[idx] = p.wait(max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    codes[idx] = p.wait(kill_grace)
                except subprocess.TimeoutExpired:   # pragma: no cover
                    log.error("worker %d (pid %d) survived SIGKILL "
                              "(uninterruptible state?)", idx, p.pid)
                    codes[idx] = None
        self.exit_codes.update(codes)
        self.procs.clear()
        self.indices.clear()
        return codes

    def poll_classified(self) -> List[Dict]:
        """One liveness/exit record per tracked worker, with the exit
        code classified (resilience.detector.classify_exit) — the
        launcher-side half of lost-host detection.  A worker that
        exited with the degraded protocol code is classified
        ``degraded``: an orderly checkpoint-and-queue ending, not a
        death."""
        from analytics_zoo_tpu.resilience.detector import classify_exit
        from analytics_zoo_tpu.resilience.policy import (
            DEGRADED_EXIT_CODE)
        out = []
        for idx, p in zip(self.indices, self.procs):
            code = p.poll()
            if code is not None:
                self.exit_codes.setdefault(idx, code)
            out.append({
                "process_index": idx,
                "pid": p.pid,
                "running": code is None,
                "code": code,
                "classification": ("degraded"
                                   if code == DEGRADED_EXIT_CODE
                                   else classify_exit(code)),
            })
        return out

    def alive(self) -> int:
        return sum(1 for p in self.procs if p.poll() is None)


class WaitResult(list):
    """``ZooCluster.wait``'s return value: still the per-process exit
    code list (index = process index) the old API promised, plus the
    forensic fields a flat list could not carry — which host died
    FIRST (on a pod, the first death is the cause; every later
    non-zero exit is usually collateral collective teardown)."""

    def __init__(self, codes: Sequence[int]):
        super().__init__(codes)
        #: [(process_index, code, wall time)] in observed exit order
        self.exit_order: List[tuple] = []
        #: first non-ok exit: {process_index, code, classification}
        self.first_failure: Optional[Dict] = None


@dataclasses.dataclass
class ClusterHealth:
    """Snapshot from ``ZooCluster.check_health``."""
    expected: int
    alive: int
    missing: List[int]                 # dead-bad or heartbeat-stale
    first_death: Optional[Dict]        # first worker seen dead-bad
    states: List[Dict]                 # poll_classified() records
    degraded: List[int] = dataclasses.field(default_factory=list)
    # ^ workers that exited DEGRADED_EXIT_CODE: orderly
    #   checkpoint-and-queue endings — neither alive nor missing

    @property
    def ok(self) -> bool:
        return not self.missing


class ZooCluster:
    """Launch ``script`` as an N-process jax.distributed job.

    Each worker sees ZOO_TPU_COORDINATOR / ZOO_TPU_NUM_PROCESSES /
    ZOO_TPU_PROCESS_ID and calls ``init_zoo_context()`` which performs
    the ``jax.distributed.initialize`` handshake — the Engine.init /
    barrier-stage role of the reference.
    """

    def __init__(self, num_processes: int,
                 coordinator: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 run_dir: Optional[str] = None,
                 chaos=None):
        self.num_processes = int(num_processes)
        self.coordinator = coordinator or \
            f"localhost:{_free_port()}"
        self.extra_env = env or {}
        # fault injection (resilience.chaos.ChaosPlan or its JSON):
        # stamped into every worker env so scripted worker
        # kill/hang/slow faults fire deterministically in-process
        self.chaos = chaos
        self._first_death: Optional[Dict] = None
        self.monitor = ProcessMonitor()
        # observability plane: per-worker metrics slots + ports and a
        # shared clock anchor, manifested in run_dir/cluster.json
        self.run_dir = run_dir
        self.clock_anchor: Optional[float] = None
        self.worker_ports: Dict[int, int] = {}
        if run_dir:
            self._prepare_run_dir(run_dir)

    def _prepare_run_dir(self, run_dir: str) -> None:
        # imported lazily: the supervisor process doesn't need the
        # observability submodules loaded unless a run dir is in play
        from analytics_zoo_tpu.observability import (
            aggregator as agg_lib)
        self.clock_anchor = time.time()
        hostname = socket.gethostname()
        workers = []
        from analytics_zoo_tpu.resilience.detector import (
            HEARTBEAT_FILE)
        for pid in range(self.num_processes):
            wdir = os.path.join(run_dir,
                                agg_lib.host_dir_name(pid))
            os.makedirs(wdir, exist_ok=True)
            # a REUSED run dir may hold a previous run's heartbeat;
            # left in place it would make check_health flag a live,
            # still-initializing worker as stale (same reused-run_dir
            # contamination merge_traces already guards against)
            try:
                os.remove(os.path.join(wdir, HEARTBEAT_FILE))
            except OSError:
                pass
            self.worker_ports[pid] = _free_port()
            workers.append({
                "process_index": pid,
                "dir": agg_lib.host_dir_name(pid),
                "hostname": hostname,
                "metrics_port": self.worker_ports[pid],
            })
        from analytics_zoo_tpu.common.fsutil import atomic_write_text
        # cluster.json is read by obs_report/zoo-doctor while the run
        # is live — publish it whole or not at all
        atomic_write_text(
            os.path.join(run_dir, agg_lib.CLUSTER_FILE),
            json.dumps({
                "clock_anchor": self.clock_anchor,
                "num_processes": self.num_processes,
                "coordinator": self.coordinator,
                "workers": workers,
            }, indent=2))

    def worker_env(self, process_id: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update({
            "ZOO_TPU_COORDINATOR": self.coordinator,
            "ZOO_TPU_NUM_PROCESSES": str(self.num_processes),
            "ZOO_TPU_PROCESS_ID": str(process_id),
        })
        if self.chaos is not None:
            from analytics_zoo_tpu.resilience.chaos import ENV_CHAOS
            env[ENV_CHAOS] = (self.chaos if isinstance(self.chaos, str)
                              else self.chaos.to_json())
        if self.run_dir:
            from analytics_zoo_tpu.observability import (
                aggregator as agg_lib)
            env.update({
                agg_lib.ENV_RUN_DIR: self.run_dir,
                agg_lib.ENV_METRICS_DIR: os.path.join(
                    self.run_dir, agg_lib.host_dir_name(process_id)),
                agg_lib.ENV_METRICS_PORT:
                    str(self.worker_ports[process_id]),
                agg_lib.ENV_CLOCK_ANCHOR: repr(self.clock_anchor),
            })
        return env

    def start(self, script: str, args: Sequence[str] = ()) -> None:
        for pid in range(self.num_processes):
            proc = subprocess.Popen(
                [sys.executable, script, *args],
                env=self.worker_env(pid),
                preexec_fn=_set_pdeathsig,
            )
            self.monitor.register(proc, index=pid)

    def wait(self, timeout: Optional[float] = None) -> WaitResult:
        """Wait for every worker; returns the exit-code list (ordered
        by process index, as before) as a :class:`WaitResult` that
        also records the observed EXIT ORDER and the first failure —
        on a pod, the first host to die is the root cause and the
        rest are collective-teardown collateral, so "which died
        first" is the question a flat code list cannot answer.

        Raises ``subprocess.TimeoutExpired`` when workers outlive
        ``timeout`` (unchanged contract)."""
        deadline = None if timeout is None else time.time() + timeout
        pending = dict(zip(self.monitor.indices, self.monitor.procs))
        by_index: Dict[int, int] = {}
        exit_order: List[tuple] = []
        from analytics_zoo_tpu.resilience.policy import (
            DEGRADED_EXIT_CODE)
        while pending:
            for idx in sorted(pending):
                code = pending[idx].poll()
                if code is None:
                    continue
                del pending[idx]
                by_index[idx] = code
                exit_order.append((idx, code, time.time()))
                if code not in (0, DEGRADED_EXIT_CODE):
                    # exit-17 is the orderly checkpoint-and-queue
                    # protocol, not a death — it must never be named
                    # the root cause of a later real failure
                    self._record_death(idx, code)
            if not pending:
                break
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired(
                    cmd=f"zoo-cluster({self.num_processes} workers)",
                    timeout=timeout)
            time.sleep(0.05)
        result = WaitResult([by_index[i] for i in sorted(by_index)])
        result.exit_order = exit_order
        for idx, code, _t in exit_order:
            if code not in (0, DEGRADED_EXIT_CODE):
                from analytics_zoo_tpu.resilience.detector import (
                    classify_exit)
                result.first_failure = {
                    "process_index": idx, "code": code,
                    "classification": classify_exit(code)}
                break
        return result

    def _record_death(self, idx: int, code: int) -> None:
        if self._first_death is not None:
            return
        from analytics_zoo_tpu.resilience.detector import classify_exit
        self._first_death = {
            "process_index": idx, "code": code,
            "classification": classify_exit(code),
            "observed_unix": round(time.time(), 3)}
        log.error("worker %d died first (%s) — later failures are "
                  "likely collateral", idx,
                  self._first_death["classification"])

    def check_health(self,
                     heartbeat_timeout_s: Optional[float] = None
                     ) -> ClusterHealth:
        """Classify worker liveness NOW — before a collective hangs on
        a dead peer.  Combines process polling (exit-code
        classification) with run-dir heartbeat staleness (a process
        can be alive but wedged in a dead collective: its heartbeat
        goes stale while poll() still says running).  Surfaces the
        PR 4 ``cluster_hosts_expected``/``cluster_hosts_missing``
        gauges so dashboards see the loss the moment the launcher
        does."""
        states = self.monitor.poll_classified()
        dead_bad, exited_ok, running, degraded = [], set(), set(), []
        for s in states:
            if s["running"]:
                running.add(s["process_index"])
            elif s["classification"] == "ok":
                exited_ok.add(s["process_index"])
            elif s["classification"] == "degraded":
                # orderly checkpoint-and-queue exit: neither alive
                # nor missing — must not inflate cluster_hosts_missing
                degraded.append(s["process_index"])
            else:
                dead_bad.append(s)
        if dead_bad and self._first_death is None:
            self._record_death(dead_bad[0]["process_index"],
                               dead_bad[0]["code"])
        stale: List[int] = []
        if self.run_dir and running:
            from analytics_zoo_tpu.common.config import get_config
            from analytics_zoo_tpu.resilience.detector import stale_hosts
            if heartbeat_timeout_s is None:
                heartbeat_timeout_s = float(get_config().get(
                    "resilience.heartbeat_timeout_s", 30.0))
            # only among workers that have beaten at least once AND
            # are still supposed to be running: a worker that exited
            # (cleanly or not) stops beating by design, and one that
            # has not started training yet has nothing to report
            stale = [i for i in stale_hosts(self.run_dir,
                                            heartbeat_timeout_s)
                     if i in running]
        missing = sorted({s["process_index"] for s in dead_bad}
                         | set(stale))
        health = ClusterHealth(
            expected=self.num_processes,
            alive=len(running),
            missing=missing,
            first_death=self._first_death,
            states=states,
            degraded=sorted(degraded))
        self._export_health(health)
        if missing:
            log.error(
                "cluster hosts missing: %s (%d/%d alive) — collectives "
                "including them will hang; recover or re-form now",
                missing, health.alive, health.expected)
        return health

    def _export_health(self, health: ClusterHealth) -> None:
        # same gauge names the PR 4 aggregator derives offline, now
        # live from the launcher; best-effort by the usual contract
        try:
            from analytics_zoo_tpu.observability import get_registry
            reg = get_registry()
            reg.gauge("cluster_hosts_expected",
                      "workers the launcher started").set(
                float(health.expected))
            reg.gauge("cluster_hosts_missing",
                      "workers dead or heartbeat-stale").set(
                float(len(health.missing)))
        except Exception:   # noqa: BLE001
            pass

    def stop(self) -> Dict[int, Optional[int]]:
        return self.monitor.stop_all()
