"""Multi-host job launcher — the RayOnSpark analogue.

Reference: pyzoo/zoo/ray/raycontext.py — a Spark barrier stage starts
``ray start`` on every executor (gen_ray_start :155), ``JVMGuard``
(:32) kills the ray processes if the parent JVM dies, and
``ProcessMonitor`` tracks pids.

TPU version: the cluster fabric is ``jax.distributed`` — the launcher
spawns one worker process per host (or simulates N hosts on one
machine), injects the coordinator env that ``init_zoo_context`` consumes
(ZOO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID), and guards children
with PR_SET_PDEATHSIG so they die with the launcher, plus atexit
cleanup.

Observability plane: passing ``run_dir`` makes the launcher the
cluster's rendezvous for fleet-level metrics — it creates one
``host-<k>/`` slot per worker, pre-allocates a metrics port each,
broadcasts a shared clock anchor (so per-host Chrome traces align on
one epoch), and writes a ``cluster.json`` manifest that host 0's
aggregator and ``obs_report.py --merge-hosts`` both read.  Workers
pick the contract up from ZOO_TPU_RUN_DIR / ZOO_TPU_METRICS_DIR /
ZOO_TPU_METRICS_PORT / ZOO_TPU_CLOCK_ANCHOR via
``observability.aggregator.init_worker_observability`` (called by
``init_zoo_context``).
"""

from __future__ import annotations

import atexit
import ctypes
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _set_pdeathsig():
    """Child dies when the launcher dies (the JVMGuard role)."""
    try:
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGKILL)
    except Exception:       # pragma: no cover - non-linux
        pass


class ProcessMonitor:
    """Track spawned workers; kill them all on exit
    (raycontext.py ProcessMonitor + JVMGuard)."""

    def __init__(self):
        self.procs: List[subprocess.Popen] = []
        atexit.register(self.stop_all)

    def register(self, proc: subprocess.Popen) -> None:
        self.procs.append(proc)

    def stop_all(self, timeout: float = 5.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.time() + timeout
        for p in self.procs:
            try:
                p.wait(max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                p.kill()
        self.procs.clear()

    def alive(self) -> int:
        return sum(1 for p in self.procs if p.poll() is None)


class ZooCluster:
    """Launch ``script`` as an N-process jax.distributed job.

    Each worker sees ZOO_TPU_COORDINATOR / ZOO_TPU_NUM_PROCESSES /
    ZOO_TPU_PROCESS_ID and calls ``init_zoo_context()`` which performs
    the ``jax.distributed.initialize`` handshake — the Engine.init /
    barrier-stage role of the reference.
    """

    def __init__(self, num_processes: int,
                 coordinator: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 run_dir: Optional[str] = None):
        self.num_processes = int(num_processes)
        self.coordinator = coordinator or \
            f"localhost:{_free_port()}"
        self.extra_env = env or {}
        self.monitor = ProcessMonitor()
        # observability plane: per-worker metrics slots + ports and a
        # shared clock anchor, manifested in run_dir/cluster.json
        self.run_dir = run_dir
        self.clock_anchor: Optional[float] = None
        self.worker_ports: Dict[int, int] = {}
        if run_dir:
            self._prepare_run_dir(run_dir)

    def _prepare_run_dir(self, run_dir: str) -> None:
        # imported lazily: the supervisor process doesn't need the
        # observability submodules loaded unless a run dir is in play
        from analytics_zoo_tpu.observability import (
            aggregator as agg_lib)
        self.clock_anchor = time.time()
        hostname = socket.gethostname()
        workers = []
        for pid in range(self.num_processes):
            wdir = os.path.join(run_dir,
                                agg_lib.host_dir_name(pid))
            os.makedirs(wdir, exist_ok=True)
            self.worker_ports[pid] = _free_port()
            workers.append({
                "process_index": pid,
                "dir": agg_lib.host_dir_name(pid),
                "hostname": hostname,
                "metrics_port": self.worker_ports[pid],
            })
        with open(os.path.join(run_dir, agg_lib.CLUSTER_FILE),
                  "w") as f:
            json.dump({
                "clock_anchor": self.clock_anchor,
                "num_processes": self.num_processes,
                "coordinator": self.coordinator,
                "workers": workers,
            }, f, indent=2)

    def worker_env(self, process_id: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update({
            "ZOO_TPU_COORDINATOR": self.coordinator,
            "ZOO_TPU_NUM_PROCESSES": str(self.num_processes),
            "ZOO_TPU_PROCESS_ID": str(process_id),
        })
        if self.run_dir:
            from analytics_zoo_tpu.observability import (
                aggregator as agg_lib)
            env.update({
                agg_lib.ENV_RUN_DIR: self.run_dir,
                agg_lib.ENV_METRICS_DIR: os.path.join(
                    self.run_dir, agg_lib.host_dir_name(process_id)),
                agg_lib.ENV_METRICS_PORT:
                    str(self.worker_ports[process_id]),
                agg_lib.ENV_CLOCK_ANCHOR: repr(self.clock_anchor),
            })
        return env

    def start(self, script: str, args: Sequence[str] = ()) -> None:
        for pid in range(self.num_processes):
            proc = subprocess.Popen(
                [sys.executable, script, *args],
                env=self.worker_env(pid),
                preexec_fn=_set_pdeathsig,
            )
            self.monitor.register(proc)

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        codes = []
        deadline = None if timeout is None else time.time() + timeout
        for p in self.monitor.procs:
            remaining = None if deadline is None else \
                max(deadline - time.time(), 0.1)
            codes.append(p.wait(remaining))
        return codes

    def stop(self) -> None:
        self.monitor.stop_all()
