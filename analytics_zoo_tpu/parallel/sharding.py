"""Parameter-sharding collection: map a model's parameter pytree to
NamedShardings.

Three sources, in precedence order:
1. Layer-declared tensor-parallel specs (``layer.param_pspecs``, set by
   e.g. ``Dense(parallel_mode="column")``) — the TP axis.
2. FSDP: large leaves sharded along their biggest divisible dim on the
   ``fsdp`` axis (ZeRO-style) — the partitioned ``AllReduceParameter``
   analogue (Topology.scala:1126-1128), but the optimizer update also
   runs sharded.
3. Replication.

GSPMD propagates these annotations through the jitted train step and
inserts all collectives (allreduce over ``data``, allgather/reduce-
scatter over ``fsdp``, TP collectives over ``model``) — no hand-written
communication anywhere.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.parallel.mesh import FSDP_AXIS


def _default_leaf(mesh: Mesh, x, fsdp_min_size: int) -> NamedSharding:
    axis = mesh.shape[FSDP_AXIS]
    if axis > 1 and np.size(x) >= fsdp_min_size:
        dims = list(np.argsort(np.shape(x))[::-1])
        for d in dims:
            if np.shape(x)[d] % axis == 0:
                spec = [None] * np.ndim(x)
                spec[d] = FSDP_AXIS
                return NamedSharding(mesh, P(*spec))
    return NamedSharding(mesh, P())


def collect_param_shardings(model, params: Dict[str, Any], mesh: Mesh,
                            fsdp_min_size: int = 2 ** 12):
    """Build the sharding pytree matching ``params`` for ``model``."""

    def visit_layer(layer, sub_params):
        declared = getattr(layer, "param_pspecs", {}) or {}
        sub_layers = {l.name: l for l in getattr(layer, "layers", [])}

        def walk(key_path, node):
            if isinstance(node, dict):
                out = {}
                for k, v in node.items():
                    if k in sub_layers:
                        out[k] = visit_layer(sub_layers[k], v)
                    else:
                        out[k] = walk(key_path + (k,), v)
                return out
            # leaf array
            key = key_path[-1] if key_path else None
            if key in declared:
                return NamedSharding(mesh, declared[key])
            return _default_leaf(mesh, node, fsdp_min_size)

        return walk((), sub_params)

    return visit_layer(model, params)
