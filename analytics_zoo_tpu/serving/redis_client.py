"""Minimal Redis client (RESP protocol) + embedded in-process broker.

The reference's Cluster Serving rides Redis streams
(ClusterServing.scala:103-113 reads stream ``image_stream``, results
land in the ``result`` table; client pyzoo/zoo/serving/client.py uses
XADD/HGETALL).  No redis-py is vendored here: RESP is a tiny protocol,
so ``RedisClient`` speaks it directly over a socket — zero external
dependencies.  ``EmbeddedBroker`` implements the same command subset
in-process for tests and single-node serving without a Redis server.
"""

from __future__ import annotations

import itertools
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class _RespReader:
    """Buffered RESP framing over a recv callable — the \\r\\n line /
    exact-n bulk reads shared by the client and the TCP broker."""

    def __init__(self, recv):
        self._recv = recv
        self.buf = b""

    def _fill(self) -> None:
        chunk = self._recv(65536)
        if not chunk:
            raise ConnectionError("connection closed")
        self.buf += chunk

    def line(self) -> bytes:
        while b"\r\n" not in self.buf:
            self._fill()
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:    # payload + trailing \r\n
            self._fill()
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data


class RedisClient:
    """Speaks RESP2 for the commands serving needs: XADD, XREAD, XLEN,
    XTRIM, XDEL, HSET, HGETALL, HDEL, DEL, PING, INFO."""

    def __init__(self, host: str = "localhost", port: int = 6379,
                 timeout: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout)
        self._reader = _RespReader(self.sock.recv)

    # ------------------------------------------------------------ protocol
    def execute(self, *args) -> Any:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            if isinstance(a, str):
                a = a.encode()
            elif not isinstance(a, bytes):
                a = str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        self.sock.sendall(b"".join(out))
        return self._read_reply()

    def _read_line(self) -> bytes:
        return self._reader.line()

    def _read_exact(self, n: int) -> bytes:
        return self._reader.exact(n)

    def _read_reply(self) -> Any:
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RuntimeError(f"redis error: {rest.decode()}")
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            return None if n == -1 else self._read_exact(n)
        if t == b"*":
            n = int(rest)
            return None if n == -1 else [self._read_reply()
                                         for _ in range(n)]
        raise RuntimeError(f"bad RESP type {t!r}")

    # ------------------------------------------------------------ commands
    def ping(self) -> bool:
        return self.execute("PING") == "PONG"

    def xadd(self, stream: str, fields: Dict[str, Any]) -> bytes:
        args = ["XADD", stream, "*"]
        for k, v in fields.items():
            args += [k, v]
        return self.execute(*args)

    def xread(self, stream: str, last_id: str = "0-0",
              count: int = 64, block_ms: Optional[int] = None):
        args = ["XREAD", "COUNT", count]
        # BLOCK 0 means block FOREVER to redis; callers use 0/None for
        # "return immediately", so only emit BLOCK for positive waits
        if block_ms:
            args += ["BLOCK", block_ms]
        args += ["STREAMS", stream, last_id]
        reply = self.execute(*args)
        return _parse_xread(reply)

    def xgroup_create(self, stream: str, group: str,
                      start_id: str = "0") -> None:
        """Create a consumer group (MKSTREAM so a fresh deployment
        works before the first enqueue); BUSYGROUP = already exists."""
        try:
            self.execute("XGROUP", "CREATE", stream, group, start_id,
                         "MKSTREAM")
        except RuntimeError as e:
            if "BUSYGROUP" not in str(e):
                raise

    def xreadgroup(self, group: str, consumer: str, stream: str,
                   count: int = 64, block_ms: Optional[int] = None):
        """Pop NEW entries for this consumer — each stream entry is
        delivered to exactly one consumer in the group."""
        args = ["XREADGROUP", "GROUP", group, consumer, "COUNT", count]
        if block_ms:          # see xread: BLOCK 0 = forever on redis
            args += ["BLOCK", block_ms]
        args += ["STREAMS", stream, ">"]
        return _parse_xread(self.execute(*args))

    def xack(self, stream: str, group: str, *ids) -> int:
        return self.execute("XACK", stream, group, *ids)

    def xautoclaim(self, stream: str, group: str, consumer: str,
                   min_idle_ms: int, count: int = 64):
        """Claim another consumer's pending entries idle for at least
        ``min_idle_ms`` (crash recovery; Redis >= 6.2)."""
        reply = self.execute("XAUTOCLAIM", stream, group, consumer,
                             min_idle_ms, "0-0", "COUNT", count)
        # reply: [next_cursor, [[id, [k,v,...]], ...], (deleted ids)]
        entries = reply[1] if reply and len(reply) > 1 else []
        # Redis 6.2 returns [id, nil] for pending entries whose data
        # was XTRIMmed out of the stream (7.0 drops them server-side).
        # Their payload is unrecoverable — ack them out of the PEL so
        # they can't wedge every future reclaim pass.
        live, dead = [], []
        for entry_id, kvs in entries:
            (live if kvs is not None else dead).append((entry_id, kvs))
        if dead:
            self.xack(stream, group,
                      *[i.decode() if isinstance(i, bytes) else i
                        for i, _ in dead])
        return _parse_xread([[stream, live]])

    def xlen(self, stream: str) -> int:
        return self.execute("XLEN", stream)

    def xlag(self, stream: str, group: str) -> int:
        """The group's true BACKLOG: entries never delivered to any
        consumer (``lag``, Redis >= 7.0) plus delivered-but-unacked
        pending.  ``XLEN`` cannot express this — served entries stay
        in the stream until trimmed, so stream length reads high
        forever; backlog is what admission control and the fleet
        autoscaler actually need.  Falls back to ``XLEN`` when XINFO
        is unavailable (old server) or lag is nil (entries deleted
        mid-stream make it uncomputable)."""
        try:
            reply = self.execute("XINFO", "GROUPS", stream)
        except RuntimeError:
            return self.xlen(stream)
        for entry in reply or []:
            fields = {}
            for i in range(0, len(entry) - 1, 2):
                k = entry[i]
                fields[k.decode() if isinstance(k, bytes) else k] = \
                    entry[i + 1]
            name = fields.get("name")
            if isinstance(name, bytes):
                name = name.decode()
            if name == group:
                lag = fields.get("lag")
                if lag is None:
                    return self.xlen(stream)
                return int(lag) + int(fields.get("pending", 0) or 0)
        return self.xlen(stream)

    def xtrim(self, stream: str, maxlen: int) -> int:
        return self.execute("XTRIM", stream, "MAXLEN", maxlen)

    def xdel(self, stream: str, *ids) -> int:
        return self.execute("XDEL", stream, *ids)

    def shutdown(self) -> None:
        """Terminate the redis server (cluster-serving-shutdown's
        ``redis-cli shutdown`` role); the server closes the connection
        without a reply."""
        try:
            self.execute("SHUTDOWN", "NOSAVE")
        except Exception:
            pass   # connection drop IS the success signal

    def hset(self, key: str, fields: Dict[str, Any]) -> int:
        args = ["HSET", key]
        for k, v in fields.items():
            args += [k, v]
        return self.execute(*args)

    def hgetall(self, key: str) -> Dict[str, bytes]:
        reply = self.execute("HGETALL", key) or []
        return {reply[i].decode(): reply[i + 1]
                for i in range(0, len(reply), 2)}

    def hdel(self, key: str, *fields) -> int:
        return self.execute("HDEL", key, *fields)

    def delete(self, *keys) -> int:
        return self.execute("DEL", *keys)

    def close(self):
        self.sock.close()


def _parse_xread(reply):
    """[[stream, [[id, [k,v,...]], ...]]] -> list of (id, fields)"""
    out: List[Tuple[str, Dict[str, bytes]]] = []
    if not reply:
        return out
    for _stream, entries in reply:
        for entry_id, kvs in entries:
            if kvs is None:      # trimmed-entry tombstone (Redis 6.2)
                continue
            fields = {kvs[i].decode(): kvs[i + 1]
                      for i in range(0, len(kvs), 2)}
            out.append((entry_id.decode()
                        if isinstance(entry_id, bytes) else entry_id,
                        fields))
    return out


class EmbeddedBroker:
    """In-process stand-in with the same method surface."""

    def __init__(self):
        self._streams: Dict[str, List[Tuple[str, Dict]]] = {}
        self._hashes: Dict[str, Dict[str, Any]] = {}
        # (stream, group) -> {"delivered": last id handed out,
        #                     "pending": {id: consumer}}
        self._groups: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._seq = itertools.count(1)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def ping(self) -> bool:
        return True

    def xadd(self, stream: str, fields: Dict[str, Any]) -> str:
        with self._cv:
            entry_id = f"{int(time.time() * 1000)}-{next(self._seq)}"
            enc = {k: (v.encode() if isinstance(v, str) else v)
                   for k, v in fields.items()}
            self._streams.setdefault(stream, []).append((entry_id, enc))
            self._cv.notify_all()
            return entry_id

    def xread(self, stream: str, last_id: str = "0-0", count: int = 64,
              block_ms: Optional[int] = None):
        deadline = time.time() + (block_ms or 0) / 1000.0
        while True:
            with self._cv:
                entries = self._streams.get(stream, [])
                out = [(i, f) for i, f in entries
                       if _id_gt(i, last_id)][:count]
                if out or block_ms is None:
                    return out
                remaining = deadline - time.time()
                if remaining <= 0:
                    return out
                self._cv.wait(min(remaining, 0.05))

    def xgroup_create(self, stream: str, group: str,
                      start_id: str = "0") -> None:
        with self._lock:
            entries = self._streams.setdefault(stream, [])
            if start_id in ("0", "0-0"):
                cursor = "0-0"
            elif start_id == "$":
                cursor = entries[-1][0] if entries else "0-0"
            else:
                cursor = start_id   # must be an exact ms-seq id
                _id_gt(cursor, "0-0")   # validates the format
            self._groups.setdefault(
                (stream, group),
                {"delivered": cursor, "pending": {}})

    def xreadgroup(self, group: str, consumer: str, stream: str,
                   count: int = 64, block_ms: Optional[int] = None):
        deadline = time.time() + (block_ms or 0) / 1000.0
        while True:
            with self._cv:
                g = self._groups.get((stream, group))
                if g is None:
                    raise RuntimeError(
                        f"NOGROUP no such consumer group {group}")
                entries = self._streams.get(stream, [])
                out = [(i, f) for i, f in entries
                       if _id_gt(i, g["delivered"])][:count]
                if out:
                    g["delivered"] = out[-1][0]
                    now = time.time()
                    for i, _f in out:
                        g["pending"][i] = (consumer, now)
                    return out
                if block_ms is None or time.time() >= deadline:
                    return out
                self._cv.wait(min(deadline - time.time(), 0.05))

    def xack(self, stream: str, group: str, *ids) -> int:
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None:
                return 0
            n = 0
            for i in ids:
                n += g["pending"].pop(i, None) is not None
            return n

    def xautoclaim(self, stream: str, group: str, consumer: str,
                   min_idle_ms: int, count: int = 64):
        with self._lock:
            g = self._groups.get((stream, group))
            if g is None:
                return []
            now = time.time()
            stale = [i for i, (_c, ts) in g["pending"].items()
                     if (now - ts) * 1000.0 >= min_idle_ms][:count]
            if not stale:
                return []
            by_id = dict(self._streams.get(stream, []))
            out = []
            for i in stale:
                g["pending"][i] = (consumer, now)
                if i in by_id:
                    out.append((i, by_id[i]))
                else:           # trimmed away — drop from pending
                    g["pending"].pop(i, None)
            return out

    def xlen(self, stream: str) -> int:
        with self._lock:
            return len(self._streams.get(stream, []))

    def group_info(self, stream: str):
        """Per-group bookkeeping snapshot for ``stream``:
        ``[(group, lag, pending, last_delivered_id), ...]`` where lag
        counts entries never delivered past the group cursor — the
        ONE computation behind both ``xlag`` and the TCP broker's
        ``XINFO GROUPS`` answer, so the embedded and wire paths can
        never report different backlogs."""
        with self._lock:
            entries = self._streams.get(stream, [])
            out = []
            for (s, group), g in self._groups.items():
                if s != stream:
                    continue
                lag = sum(1 for i, _f in entries
                          if _id_gt(i, g["delivered"]))
                out.append((group, lag, len(g["pending"]),
                            g["delivered"]))
            return out

    def xlag(self, stream: str, group: str) -> int:
        """Undelivered entries past the group cursor + unacked
        pending (see RedisClient.xlag); stream length when the group
        does not exist yet."""
        for name, lag, pending, _delivered in self.group_info(stream):
            if name == group:
                return lag + pending
        return self.xlen(stream)

    def xtrim(self, stream: str, maxlen: int) -> int:
        with self._lock:
            s = self._streams.get(stream, [])
            drop = max(len(s) - maxlen, 0)
            self._streams[stream] = s[drop:]
            return drop

    def xdel(self, stream: str, *ids) -> int:
        with self._lock:
            s = self._streams.get(stream, [])
            keep = [(i, f) for i, f in s if i not in ids]
            self._streams[stream] = keep
            return len(s) - len(keep)

    def hset(self, key: str, fields: Dict[str, Any]) -> int:
        with self._lock:
            self._hashes.setdefault(key, {}).update(
                {k: (v.encode() if isinstance(v, str) else v)
                 for k, v in fields.items()})
            return len(fields)

    def hgetall(self, key: str) -> Dict[str, Any]:
        with self._lock:
            return dict(self._hashes.get(key, {}))

    def hdel(self, key: str, *fields) -> int:
        with self._lock:
            h = self._hashes.get(key, {})
            n = 0
            for f in fields:
                n += h.pop(f, None) is not None
            return n

    def delete(self, *keys) -> int:
        with self._lock:
            n = 0
            for k in keys:
                n += self._hashes.pop(k, None) is not None
                n += self._streams.pop(k, None) is not None
            return n

    def close(self):
        pass

    def shutdown(self) -> None:
        """In-process broker: clear all state (the redis-server
        shutdown analogue)."""
        with self._lock:
            self._streams.clear()
            self._hashes.clear()


def _id_gt(a: str, b: str) -> bool:
    def parse(x):
        ms, _, seq = x.partition("-")
        # zoolint: disable=SYNC002 — stream ids are host strings
        return (int(ms), int(seq or 0))
    return parse(a) > parse(b)


# ----------------------------------------------------------- TCP broker
def _enc_simple(s: str) -> bytes:
    return b"+%s\r\n" % s.encode()


def _enc_err(s: str) -> bytes:
    return b"-%s\r\n" % s.encode()


def _enc_int(i: int) -> bytes:
    return b":%d\r\n" % int(i)


def _enc_bulk(v) -> bytes:
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, str):
        v = v.encode()
    return b"$%d\r\n%s\r\n" % (len(v), v)


def _enc_array(items) -> bytes:
    if items is None:
        return b"*-1\r\n"
    return b"*%d\r\n" % len(items) + b"".join(items)


def _enc_entries(entries) -> bytes:
    """[(id, {k: bytes})] -> RESP [[id, [k, v, ...]], ...]"""
    out = []
    for entry_id, fields in entries:
        kvs = []
        for k, v in fields.items():
            kvs.append(_enc_bulk(k))
            kvs.append(_enc_bulk(v))
        out.append(_enc_array([_enc_bulk(entry_id), _enc_array(kvs)]))
    return _enc_array(out)


class BrokerServer:
    """TCP RESP front-end over an ``EmbeddedBroker`` — a single-node
    "real" broker, so the socket ``RedisClient`` serves against an
    actual wire protocol (and single-host deployments run without a
    Redis install).  Speaks exactly the command subset the serving
    stack uses; one thread per connection (blocking XREADs park their
    own connection only)."""

    def __init__(self, broker: Optional[EmbeddedBroker] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.broker = broker if broker is not None else EmbeddedBroker()
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        # accept loop adds, per-conn threads discard, stop() snapshots:
        # three threads on one set, so every touch holds the lock
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        self._accept = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._accept.start()

    @property
    def url(self) -> str:
        return f"{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        reader = _RespReader(conn.recv)
        try:
            while not self._stop.is_set():
                line = reader.line()
                if not line.startswith(b"*"):
                    conn.sendall(_enc_err("ERR protocol"))
                    continue
                n = int(line[1:])
                args = []
                for _ in range(n):
                    lens = reader.line()
                    assert lens.startswith(b"$"), lens
                    args.append(reader.exact(int(lens[1:])))
                if not args:
                    continue
                cmd = args[0].decode().upper()
                if cmd == "SHUTDOWN":
                    self.broker.shutdown()
                    conn.close()       # connection drop = success signal
                    self.stop()
                    return
                try:
                    conn.sendall(self._dispatch(cmd, args[1:]))
                except ConnectionError:
                    raise
                except Exception as e:   # command error -> RESP error
                    conn.sendall(_enc_err(f"ERR {e}"))
        except (ConnectionError, OSError):
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, cmd: str, a: List[bytes]) -> bytes:
        b = self.broker
        dec = lambda x: x.decode()
        if cmd == "PING":
            return _enc_simple("PONG")
        if cmd == "INFO":
            return _enc_bulk("# Server\r\nembedded_broker:1\r\n")
        if cmd == "XADD":
            fields = {dec(a[i]): a[i + 1] for i in range(2, len(a), 2)}
            return _enc_bulk(b.xadd(dec(a[0]), fields))
        if cmd == "XREAD":
            opts = self._stream_opts(a)
            entries = b.xread(opts["stream"], opts["id"],
                              count=opts["count"],
                              block_ms=opts["block"])
            if not entries:
                return _enc_array(None)
            return _enc_array([_enc_array(
                [_enc_bulk(opts["stream"]), _enc_entries(entries)])])
        if cmd == "XREADGROUP":
            group, consumer = dec(a[1]), dec(a[2])
            opts = self._stream_opts(a[3:])
            entries = b.xreadgroup(group, consumer, opts["stream"],
                                   count=opts["count"],
                                   block_ms=opts["block"])
            if not entries:
                return _enc_array(None)
            return _enc_array([_enc_array(
                [_enc_bulk(opts["stream"]), _enc_entries(entries)])])
        if cmd == "XGROUP":
            if dec(a[0]).upper() != "CREATE":
                return _enc_err("ERR unsupported XGROUP subcommand")
            # embedded create is idempotent, so no BUSYGROUP ever; a
            # real failure (bad start id) must surface as ERR — the
            # client deliberately swallows BUSYGROUP only
            b.xgroup_create(dec(a[1]), dec(a[2]), dec(a[3]))
            return _enc_simple("OK")
        if cmd == "XACK":
            return _enc_int(b.xack(dec(a[0]), dec(a[1]),
                                   *[dec(i) for i in a[2:]]))
        if cmd == "XAUTOCLAIM":
            # stream group consumer min-idle start [COUNT n]
            count = 64
            if len(a) >= 7 and dec(a[5]).upper() == "COUNT":
                count = int(a[6])
            entries = b.xautoclaim(dec(a[0]), dec(a[1]), dec(a[2]),
                                   int(a[3]), count=count)
            return _enc_array([_enc_bulk("0-0"), _enc_entries(entries),
                               _enc_array([])])
        if cmd == "XLEN":
            return _enc_int(b.xlen(dec(a[0])))
        if cmd == "XINFO":
            if dec(a[0]).upper() != "GROUPS":
                return _enc_err("ERR unsupported XINFO subcommand")
            out = []
            for group, lag, pending, delivered in \
                    b.group_info(dec(a[1])):
                out.append(_enc_array([
                    _enc_bulk("name"), _enc_bulk(group),
                    _enc_bulk("consumers"), _enc_int(0),
                    _enc_bulk("pending"), _enc_int(pending),
                    _enc_bulk("last-delivered-id"),
                    _enc_bulk(delivered),
                    _enc_bulk("lag"), _enc_int(lag),
                ]))
            return _enc_array(out)
        if cmd == "XTRIM":
            return _enc_int(b.xtrim(dec(a[0]), int(a[2])))
        if cmd == "XDEL":
            return _enc_int(b.xdel(dec(a[0]), *[dec(i) for i in a[1:]]))
        if cmd == "HSET":
            fields = {dec(a[i]): a[i + 1] for i in range(1, len(a), 2)}
            return _enc_int(b.hset(dec(a[0]), fields))
        if cmd == "HGETALL":
            flat = []
            for k, v in b.hgetall(dec(a[0])).items():
                flat.append(_enc_bulk(k))
                flat.append(_enc_bulk(v))
            return _enc_array(flat)
        if cmd == "HDEL":
            return _enc_int(b.hdel(dec(a[0]), *[dec(f) for f in a[1:]]))
        if cmd == "DEL":
            return _enc_int(b.delete(*[dec(k) for k in a]))
        return _enc_err(f"ERR unknown command '{cmd}'")

    @staticmethod
    def _stream_opts(a: List[bytes]) -> Dict[str, Any]:
        """Parse [COUNT n] [BLOCK ms] STREAMS stream id."""
        out: Dict[str, Any] = {"count": 64, "block": None}
        i = 0
        while i < len(a):
            word = a[i].decode().upper()
            if word == "COUNT":
                out["count"] = int(a[i + 1])
                i += 2
            elif word == "BLOCK":
                out["block"] = int(a[i + 1])
                i += 2
            elif word == "STREAMS":
                out["stream"] = a[i + 1].decode()
                out["id"] = a[i + 2].decode()
                i += 3
            else:
                i += 1
        return out

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conns_lock:        # copy: serve threads discard
            conns = list(self._conns)
        for c in conns:
            try:
                c.close()
            except OSError:
                pass


def connect(url: Optional[str] = None):
    """'host:port' → RedisClient; None/'embedded' → EmbeddedBroker."""
    if url in (None, "embedded"):
        return EmbeddedBroker()
    host, _, port = url.partition(":")
    return RedisClient(host or "localhost", int(port or 6379))


# ------------------------------------------------------ circuit breaker
class CircuitOpenError(ConnectionError):
    """Fast-fail: the breaker is open — no broker IO was attempted."""


#: the exception classes the breaker counts as broker failures:
#: socket/transport trouble (ConnectionError and TimeoutError are both
#: OSError subclasses) plus injected chaos faults.  Redis COMMAND
#: errors (NOGROUP, WRONGTYPE, …) are application bugs, not outages —
#: they raise RuntimeError and pass through uncounted.
def _breaker_failure_excs():
    from analytics_zoo_tpu.resilience.chaos import InjectedFault
    return (OSError, InjectedFault)


BREAKER_CLOSED, BREAKER_HALF_OPEN, BREAKER_OPEN = 0, 1, 2
_BREAKER_STATE_NAMES = {BREAKER_CLOSED: "closed",
                        BREAKER_HALF_OPEN: "half_open",
                        BREAKER_OPEN: "open"}


def _note_breaker_transition(frm: int, to: int, **detail) -> None:
    """Report a breaker state change to the flight recorder — the
    primary forensic signal of a broker outage (zoo-doctor's
    ``broker_outage`` rule).  Never raises; called OUTSIDE the
    breaker's lock."""
    try:
        from analytics_zoo_tpu.observability.flightrec import \
            record_event
        record_event("breaker.transition",
                     frm=_BREAKER_STATE_NAMES[frm],
                     to=_BREAKER_STATE_NAMES[to], **detail)
    except Exception:   # noqa: BLE001 — forensics must not break IO
        pass


class CircuitBreaker:
    """k-consecutive-failures → open → cooldown → half-open probe.

    Closed: every call allowed; ``failures`` consecutive recorded
    failures open it.  Open: every call fast-fails for ``cooldown_s``.
    Half-open: exactly ONE probe call is allowed through; its success
    closes the breaker, its failure re-opens (fresh cooldown).  All
    transitions happen under one lock that is never held across IO —
    the caller does the blocking call *outside* and reports back."""

    def __init__(self, failures: int = 5, cooldown_s: float = 2.0,
                 clock=time.monotonic):
        self.failures = max(int(failures), 1)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive = 0
        self._state = BREAKER_CLOSED
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> int:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a call be attempted right now?  (Claims the half-open
        probe slot when it grants one during cooldown recovery.)"""
        trans = None
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN and \
                    self._clock() - self._opened_at >= self.cooldown_s:
                self._state = BREAKER_HALF_OPEN
                trans = (BREAKER_OPEN, BREAKER_HALF_OPEN)
            allowed = self._state == BREAKER_HALF_OPEN \
                and not self._probing
            if allowed:
                self._probing = True
        if trans is not None:
            _note_breaker_transition(*trans)
        return allowed

    def record_success(self) -> None:
        with self._lock:
            trans = (self._state, BREAKER_CLOSED) \
                if self._state != BREAKER_CLOSED else None
            self._consecutive = 0
            self._probing = False
            self._state = BREAKER_CLOSED
        if trans is not None:
            _note_breaker_transition(*trans)

    def record_failure(self) -> None:
        trans = None
        with self._lock:
            self._consecutive += 1
            self._probing = False
            if self._state == BREAKER_HALF_OPEN or \
                    self._consecutive >= self.failures:
                if self._state != BREAKER_OPEN:
                    trans = (self._state, BREAKER_OPEN,
                             self._consecutive)
                self._state = BREAKER_OPEN
                self._opened_at = self._clock()
        if trans is not None:
            _note_breaker_transition(trans[0], trans[1],
                                     failures=trans[2])


class BreakerClient:
    """Circuit breaker around a broker connection.

    Every delegated op goes through :meth:`_call`: breaker-open →
    :class:`CircuitOpenError` with **no** socket IO (a broker outage
    degrades to fast-fail instead of a per-op connect-timeout
    crash-loop); a transport failure (see ``_breaker_failure_excs``)
    is counted AND drops the underlying connection, so the half-open
    probe reconnects through ``factory`` instead of reusing a dead
    socket.  Exposes the breaker state as the ``serving_breaker_state``
    gauge (0 closed / 1 half-open / 2 open).

    The chaos site ``serving.redis`` fires here, between the breaker
    gate and the real op — step = attempted ops since the active plan
    was installed (each new plan sees steps 0, 1, 2, …), so a scripted
    outage is "the next k ops fail" regardless of how many ops ran
    before the test armed it.

    Like the raw clients, a ``BreakerClient`` is NOT thread-safe for
    concurrent ops (serving keeps all broker IO on one thread); the
    breaker's own state is locked so `/healthz` threads may read
    ``breaker.state`` concurrently."""

    def __init__(self, factory, failures: int = 5,
                 cooldown_s: float = 2.0, conn=None,
                 clock=time.monotonic):
        self._factory = factory
        self._conn = conn
        self.breaker = CircuitBreaker(failures, cooldown_s, clock)
        # attempted ops while a chaos plan is armed; reset per plan so
        # FaultSpec(at_step=0, times=k) means "the next k ops"
        self._chaos_step = 0
        self._chaos_plan = None
        try:
            from analytics_zoo_tpu.observability import get_registry
            self._gauge = get_registry().gauge(
                "serving_breaker_state",
                "redis circuit breaker: 0 closed, 1 half-open, 2 open")
            self._gauge.set(BREAKER_CLOSED)
        except Exception:   # pragma: no cover — registry unavailable
            self._gauge = None

    # ------------------------------------------------------------ plumbing
    def _set_gauge(self) -> None:
        if self._gauge is not None:
            self._gauge.set(self.breaker.state)

    def _drop_conn(self) -> None:
        conn, self._conn = self._conn, None
        if conn is not None:
            try:
                conn.close()
            except Exception:   # noqa: BLE001 — already broken
                pass

    def _trip_chaos(self) -> None:
        from analytics_zoo_tpu.resilience.chaos import (
            SITE_SERVING_REDIS, active_chaos)
        plan = active_chaos()
        if plan is None:
            self._chaos_plan = None
            return
        if plan is not self._chaos_plan:
            self._chaos_plan = plan
            self._chaos_step = 0
        step = self._chaos_step
        self._chaos_step += 1
        plan.trip(SITE_SERVING_REDIS, step)

    def _call(self, name: str, *args, **kwargs):
        if not self.breaker.allow():
            self._set_gauge()
            raise CircuitOpenError(
                f"redis breaker open: {name} not attempted")
        try:
            self._trip_chaos()
            if self._conn is None:
                self._conn = self._factory()
            out = getattr(self._conn, name)(*args, **kwargs)
        except _breaker_failure_excs():
            self.breaker.record_failure()
            self._drop_conn()
            self._set_gauge()
            raise
        except Exception:
            # a redis COMMAND error (NOGROUP, WRONGTYPE, …) means the
            # broker answered — the transport is healthy.  Recording
            # success matters beyond bookkeeping: it releases a
            # half-open probe slot; leaking it would wedge the breaker
            # HALF_OPEN forever (every later op fast-failing) while
            # readiness, which only checks BREAKER_OPEN, reads ready.
            self.breaker.record_success()
            self._set_gauge()
            raise
        self.breaker.record_success()
        self._set_gauge()
        return out

    def __getattr__(self, name: str):
        # delegate the whole broker command surface through the breaker
        if name.startswith("_"):
            raise AttributeError(name)

        def call(*args, **kwargs):
            return self._call(name, *args, **kwargs)
        call.__name__ = name
        return call

    def close(self) -> None:
        """Release the underlying connection (never breaker-gated)."""
        self._drop_conn()


def with_breaker(url: Optional[str] = None, broker=None,
                 failures: int = 5, cooldown_s: float = 2.0):
    """Wrap a broker in a :class:`BreakerClient`.

    ``url`` given → connects lazily and RE-connects after transport
    failures; ``broker`` given (embedded/test double) → the "reconnect"
    returns the same instance — as does an embedded ``url`` (None /
    'embedded'): an in-process broker IS the state, so a "reconnect"
    must never swap in a fresh empty one.  ``failures <= 0`` disables
    the breaker and returns the raw broker unchanged."""
    if broker is None and url in (None, "embedded"):
        broker = connect(url)
    if failures <= 0:
        return broker if broker is not None else connect(url)
    if broker is not None:
        return BreakerClient(lambda: broker, failures, cooldown_s,
                             conn=broker)
    return BreakerClient(lambda: connect(url), failures, cooldown_s)
