"""Cluster Serving quick start — the 60-second client demo.

Reference: pyzoo/zoo/serving/quick_start.py — enqueue an image into the
Redis input stream, poll the output queue, print the top-N result.

Run against a live deployment (``zoo-serving start`` + redis):

    python -m analytics_zoo_tpu.serving.quick_start --redis-url \
        redis://localhost:6379 --image cat.jpg

With no arguments it is fully self-contained: an embedded broker and a
background serving worker over a tiny classifier, so the round trip
demonstrates the full enqueue → decode → predict → result path with
zero external services.
"""

from __future__ import annotations

import argparse
import sys
import time


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--redis-url", default=None,
                   help="redis://host:port of a live deployment; "
                        "default = self-contained embedded demo")
    p.add_argument("--image", default=None,
                   help="image file to classify; default = synthetic")
    p.add_argument("--uri", default="quick-start-0")
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--smoke", action="store_true",
                   help="CI mode: cap the result-poll timeout")
    args = p.parse_args(argv)
    if args.smoke:
        args.timeout = min(args.timeout, 15.0)

    import numpy as np

    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    broker = None
    worker = serving = None
    if args.redis_url is None:
        # self-contained: embedded broker + background worker
        import jax
        jax.config.update("jax_platforms", "cpu")

        from analytics_zoo_tpu.pipeline.api.keras import Sequential
        from analytics_zoo_tpu.pipeline.api.keras.layers import (
            Conv2D, Dense, Flatten)
        from analytics_zoo_tpu.pipeline.inference import InferenceModel
        from analytics_zoo_tpu.serving.redis_client import EmbeddedBroker
        from analytics_zoo_tpu.serving.server import (ClusterServing,
                                                      ServingConfig)
        model = Sequential()
        model.add(Conv2D(8, 3, 3, input_shape=(32, 32, 3),
                         activation="relu"))
        model.add(Flatten())
        model.add(Dense(5))
        model.compile("adam", "mse")
        broker = EmbeddedBroker()
        serving = ClusterServing(
            InferenceModel().load_zoo(model),
            ServingConfig(batch_size=4, top_n=3), broker=broker)
        worker = serving.start_background()

    inq = InputQueue(redis_url=args.redis_url, broker=broker)
    outq = OutputQueue(redis_url=args.redis_url, broker=broker)

    try:
        if args.image is not None:
            inq.enqueue_image(args.uri, args.image)   # path accepted
        else:
            arr = (np.random.RandomState(0)
                   .rand(32, 32, 3).astype(np.float32))
            inq.enqueue(args.uri, arr)

        t0 = time.time()
        result = outq.query(args.uri, timeout_s=args.timeout)
        if result is None:
            print(f"no result for {args.uri} within {args.timeout}s "
                  "(is the serving worker running?)")
        else:
            print(f"top-N for {args.uri} ({time.time() - t0:.2f}s): "
                  f"{result}")
    finally:
        if serving is not None:
            serving.stop()
            worker.join(timeout=10)
    return result


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
