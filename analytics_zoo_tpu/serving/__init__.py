from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.server import ClusterServing
from analytics_zoo_tpu.serving.supervisor import (
    ServingSupervisor, cli_worker_factory)

__all__ = ["InputQueue", "OutputQueue", "ClusterServing",
           "ServingSupervisor", "cli_worker_factory"]
