from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
from analytics_zoo_tpu.serving.server import ClusterServing

__all__ = ["InputQueue", "OutputQueue", "ClusterServing"]
