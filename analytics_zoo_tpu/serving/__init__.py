from analytics_zoo_tpu.serving.client import (
    InputQueue, OutputQueue, ServingHttpClient, predict_http)
from analytics_zoo_tpu.serving.engine import ServingEngine
from analytics_zoo_tpu.serving.server import ClusterServing
from analytics_zoo_tpu.serving.supervisor import (
    ServingSupervisor, cli_worker_factory)

__all__ = ["InputQueue", "OutputQueue", "ServingHttpClient",
           "predict_http", "ServingEngine", "ClusterServing",
           "ServingSupervisor", "cli_worker_factory"]
