"""Cluster Serving engine.

Reference: zoo/serving/ClusterServing.scala:33-342 — a streaming loop:
Redis stream ``image_stream`` → base64 JPEG decode → batched
InferenceModel predict → top-N postprocess → write to the ``result``
table with backpressure retry; Redis OOM guard via XTRIM (:128-134);
throughput scalars to the inference summary (:294-317).  Config comes
from config.yaml (ClusterServingHelper).

TPU version: the worker is a host process driving the one compiled XLA
predict program; batching pads to a fixed shape so one executable
serves all traffic.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.serving.redis_client import connect
from analytics_zoo_tpu.utils.summary import InferenceSummary

log = logging.getLogger("analytics_zoo_tpu.serving")

INPUT_STREAM = "serving_stream"
RESULT_PREFIX = "result:"
STOP_KEY = "zoo-serving-stop"   # cross-process stop signal
                                # (ClusterServingManager.listenTermination)


def decode_field(fields: Dict[str, bytes]):
    """Decode one stream record: 'data' (b64 ndarray .npy bytes) or
    'image' (b64 JPEG) + 'uri'."""
    uri = fields["uri"].decode() if isinstance(fields["uri"], bytes) \
        else fields["uri"]
    if "image" in fields:
        import cv2
        raw = base64.b64decode(fields["image"])
        img = cv2.imdecode(np.frombuffer(raw, np.uint8),
                           cv2.IMREAD_COLOR)
        return uri, img.astype(np.float32)
    raw = base64.b64decode(fields["data"])
    import io
    arr = np.load(io.BytesIO(raw), allow_pickle=False)
    return uri, arr


class ServingConfig:
    """config.yaml contract (scripts/cluster-serving/config.yaml)."""

    def __init__(self, redis_url: Optional[str] = None,
                 batch_size: int = 4, top_n: int = 1,
                 max_stream_len: int = 100000,
                 log_dir: Optional[str] = None,
                 extra: Optional[Dict[str, str]] = None):
        self.redis_url = redis_url
        self.batch_size = int(batch_size)
        self.top_n = int(top_n)
        self.max_stream_len = int(max_stream_len)
        self.log_dir = log_dir
        self.extra = extra or {}   # raw section.key entries (model.* etc)

    @classmethod
    def from_yaml(cls, path: str) -> "ServingConfig":
        cfg: Dict[str, Any] = {}
        section = None
        with open(path) as f:
            for line in f:
                raw = line.rstrip()
                if not raw or raw.lstrip().startswith("#"):
                    continue
                if not raw.startswith(" "):
                    section = raw.rstrip(":").strip()
                    continue
                k, _, v = raw.strip().partition(":")
                cfg[f"{section}.{k.strip()}"] = v.strip()
        return cls(
            redis_url=cfg.get("data.src"),
            batch_size=int(cfg.get("params.batch_size", 4) or 4),
            top_n=int(cfg.get("params.top_n", 1) or 1),
            log_dir=cfg.get("params.log_dir") or None,
            extra=cfg,
        )


class ClusterServing:
    """The serving worker loop."""

    def __init__(self, inference_model, config: ServingConfig = None,
                 broker=None):
        self.model = inference_model
        self.config = config or ServingConfig()
        self.broker = broker if broker is not None else connect(
            self.config.redis_url)
        self.summary = (InferenceSummary(self.config.log_dir, "serving")
                        if self.config.log_dir else None)
        self._stop = threading.Event()
        self._last_id = "0-0"
        self.total_records = 0

    # ------------------------------------------------------------ main loop
    def run_once(self, block_ms: int = 100) -> int:
        """One poll/predict/write cycle; returns #records served."""
        entries = self.broker.xread(INPUT_STREAM, self._last_id,
                                    count=self.config.batch_size,
                                    block_ms=block_ms)
        if not entries:
            return 0
        t0 = time.time()
        uris, arrays = [], []
        for entry_id, fields in entries:
            self._last_id = entry_id
            try:
                uri, arr = decode_field(fields)
            except Exception:
                log.exception("undecodable record %s", entry_id)
                continue
            uris.append(uri)
            arrays.append(arr)
        if not arrays:
            return 0
        # fixed-shape batch: pad to batch_size so ONE executable serves
        # all traffic (the reference's non-BLAS batched path, :186-237)
        bs = self.config.batch_size
        x = np.stack(arrays)
        real = len(arrays)
        if real < bs:
            x = np.concatenate(
                [x, np.zeros((bs - real,) + x.shape[1:], x.dtype)])
        out = np.asarray(self.model.predict(x))[:real]
        # top-N postprocess (PostProcessing.scala)
        exp = np.exp(out - out.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        top = np.argsort(-probs, axis=-1)[:, :self.config.top_n]
        for uri, t, p in zip(uris, top, probs):
            value = json.dumps([[int(i), float(p[i])] for i in t])
            self._write_result(uri, value)
        self.total_records += real
        wall = time.time() - t0
        if self.summary is not None:
            self.summary.add_scalar("Serving Throughput",
                                    real / max(wall, 1e-9),
                                    self.total_records)
            self.summary.add_scalar("Total Records Number",
                                    self.total_records,
                                    self.total_records)
        # OOM guard (ClusterServing.scala:128-134)
        if self.broker.xlen(INPUT_STREAM) > self.config.max_stream_len:
            self.broker.xtrim(INPUT_STREAM, self.config.max_stream_len)
        return real

    def _write_result(self, uri: str, value: str,
                      retries: int = 100) -> None:
        # infinite-ish retry backpressure (:254-289)
        for attempt in range(retries):
            try:
                self.broker.hset(RESULT_PREFIX + uri, {"value": value})
                return
            except Exception:
                time.sleep(min(0.1 * (attempt + 1), 2.0))
        raise RuntimeError(f"could not write result for {uri}")

    def run(self, poll_ms: int = 100) -> None:
        log.info("cluster serving started (batch=%d)",
                 self.config.batch_size)
        # honor only stop signals issued at/after startup so a stale
        # key from a previous shutdown can't kill a fresh worker, and a
        # signal sent while we were still booting isn't lost
        started = time.time()
        while not self._stop.is_set():
            self.run_once(block_ms=poll_ms)
            sig = self.broker.hgetall(STOP_KEY)
            if sig:
                raw = sig.get(b"stop", sig.get("stop", b"0"))
                try:
                    ts = float(raw)
                except (TypeError, ValueError):
                    ts = float("inf")   # unparseable → explicit stop
                if ts >= started - 1.0:   # small clock-skew allowance
                    log.info("stop signal received; shutting down")
                    self.broker.delete(STOP_KEY)
                    break

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        """(ref ClusterServingManager.listenTermination :335)"""
        self._stop.set()
