"""Cluster Serving engine.

Reference: zoo/serving/ClusterServing.scala:33-342 — a streaming loop:
Redis stream ``image_stream`` → base64 JPEG decode → batched
InferenceModel predict → top-N postprocess → write to the ``result``
table with backpressure retry; Redis OOM guard via XTRIM (:128-134);
throughput scalars to the inference summary (:294-317).  Config comes
from config.yaml (ClusterServingHelper).

TPU version (serving engine v2): ``ClusterServing`` is the Redis
*transport* — it owns the stream read / shed / decode-pool / ack /
reclaim / dead-letter lifecycle — composed over the
``serving.engine`` batcher/executor layers: decoded records are
submitted as atomic groups to a :class:`~analytics_zoo_tpu.serving.
engine.ServingEngine`, whose continuous batcher pads each in-flight
batch to the nearest AOT-warmed bucket size and co-batches them with
the HTTP fast path's singles (``params.http_port``).  Multi-model:
every record may carry an ``endpoint`` field routing it to a
registered model (``register_endpoint`` / ``params.endpoints``).
"""

from __future__ import annotations

import base64
import itertools
import json
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.common.fsutil import atomic_write_text
from analytics_zoo_tpu.data.stages import WorkerPool
from analytics_zoo_tpu.observability import flightrec
from analytics_zoo_tpu.observability import (
    MetricsServer, TelemetrySampler, get_registry, get_tracer)
from analytics_zoo_tpu.observability.reqtrace import (
    TRACE_FIELD, TraceContext, get_request_log)
from analytics_zoo_tpu.resilience.chaos import (
    SITE_SERVING_DECODE, SITE_SERVING_PREDICT, active_chaos)
from analytics_zoo_tpu.resilience.detector import HostHeartbeat
from analytics_zoo_tpu.serving.engine.batcher import (Request,
                                                      ShedError)
from analytics_zoo_tpu.serving.engine.core import (
    DEFAULT_ENDPOINT, ServingEngine)
from analytics_zoo_tpu.serving.engine.transport import HttpTransport
from analytics_zoo_tpu.serving.redis_client import (
    BREAKER_OPEN, CircuitOpenError, _breaker_failure_excs, connect,
    with_breaker)
from analytics_zoo_tpu.utils.summary import InferenceSummary

log = logging.getLogger("analytics_zoo_tpu.serving")

INPUT_STREAM = "serving_stream"
RESULT_PREFIX = "result:"
STOP_KEY = "zoo-serving-stop"   # cross-process stop signal
                                # (ClusterServingManager.listenTermination)
# results whose write was abandoned after the bounded backoff, shed
# requests, and quarantined poison records: the request_id/uri land
# here with a ``reason`` field (write_abandoned | shed | poison) so an
# operator (or a replaying client) can find every record the fleet
# gave up on — losing a result beats losing the worker loop
DEAD_LETTER_STREAM = "serving_dead_letter"
# delivery-attempt counts for records on the crash-recovery (reclaim)
# path, keyed by request_id (entry id when absent) — the poison-
# quarantine bookkeeping must survive the very worker deaths it counts
POISON_ATTEMPTS_KEY = "serving_poison_attempts"

# the broker-outage class: breaker fast-fails plus the transport
# failures the breaker counts (socket errors, injected serving.redis
# faults) — the run loop idles on these instead of crashing
_BROKER_OUTAGE_EXCS = (CircuitOpenError,) + _breaker_failure_excs()


def decode_field(fields: Dict[str, bytes]):
    """Decode one stream record: 'data' (b64 ndarray .npy bytes) or
    'image' (b64 JPEG) + 'uri' [+ optional 'request_id' for
    cross-process correlation].  Returns ``(uri, array, request_id)``
    (request_id None for records enqueued without one)."""
    uri = fields["uri"].decode() if isinstance(fields["uri"], bytes) \
        else fields["uri"]
    rid = fields.get("request_id")
    if isinstance(rid, bytes):
        rid = rid.decode()
    if "image" in fields:
        from analytics_zoo_tpu.feature.image import decode_image_bytes
        raw = base64.b64decode(fields["image"])
        # serving consumes BGR, matching the reference's OpenCV path
        # (ImageProcessing.scala:24)
        img = decode_image_bytes(raw, to_rgb=False, context=uri)
        return uri, img.astype(np.float32), rid
    raw = base64.b64decode(fields["data"])
    import io
    arr = np.load(io.BytesIO(raw), allow_pickle=False)
    return uri, arr, rid


class ServingConfig:
    """config.yaml contract (scripts/cluster-serving/config.yaml)."""

    def __init__(self, redis_url: Optional[str] = None,
                 batch_size: int = 4, top_n: int = 1,
                 max_stream_len: int = 100000,
                 log_dir: Optional[str] = None,
                 consumer_group: Optional[str] = None,
                 consumer_name: str = "worker-0",
                 pipeline_depth: int = 2,
                 metrics_port: Optional[int] = None,
                 metrics_host: Optional[str] = None,
                 healthz_max_queue: Optional[int] = None,
                 healthz_max_error_rate: Optional[float] = None,
                 result_write_retries: Optional[int] = None,
                 request_deadline_ms: Optional[int] = None,
                 reclaim_min_idle_ms: Optional[int] = None,
                 poison_max_attempts: Optional[int] = None,
                 breaker_failures: Optional[int] = None,
                 breaker_cooldown_s: Optional[float] = None,
                 input_shape=None,
                 batch_buckets=None,
                 batch_max_wait_ms: Optional[float] = None,
                 http_port: Optional[int] = None,
                 http_timeout_s: Optional[float] = None,
                 endpoints: Optional[str] = None,
                 extra: Optional[Dict[str, str]] = None):
        self.redis_url = redis_url
        self.batch_size = int(batch_size)
        self.top_n = int(top_n)
        self.max_stream_len = int(max_stream_len)
        self.log_dir = log_dir
        # Prometheus scrape endpoint: None = off, 0 = ephemeral port
        # (tests / multi-worker hosts), N = fixed port.  The endpoint
        # is UNAUTHENTICATED — on shared networks bind metrics_host to
        # 127.0.0.1 (or a scrape-only interface) instead of all
        # interfaces.  None defers to observability.bind_host.
        self.metrics_port = (None if metrics_port is None
                             else int(metrics_port))
        if metrics_host is None:
            from analytics_zoo_tpu.observability.exporter import (
                default_bind_host)
            metrics_host = default_bind_host()
        self.metrics_host = metrics_host
        # how many batches may be read-ahead into the decode pipeline.
        # Each read-ahead batch waits ~1 predict before its own turn, so
        # depth trades tail latency for decode/predict overlap: 2 keeps
        # the overlap (decode N+1 under predict N) at roughly half the
        # queue-wait p50 of deeper pipelines.  Clamped to >= 1: depth 0
        # would make the run loop read nothing, forever.
        self.pipeline_depth = max(1, int(pipeline_depth))
        # /healthz readiness thresholds (0 = that check disabled):
        # the probe flips to 503 when the input-stream backlog exceeds
        # healthz_max_queue, or when the error fraction over the most
        # recent records exceeds healthz_max_error_rate — so an
        # orchestrator stops routing to a drowning/poisoned worker
        # instead of killing a merely-busy one
        if healthz_max_queue is None:
            healthz_max_queue = get_config().get(
                "serving.healthz_max_queue", 0)
        if healthz_max_error_rate is None:
            healthz_max_error_rate = get_config().get(
                "serving.healthz_max_error_rate", 0.0)
        self.healthz_max_queue = int(healthz_max_queue or 0)
        self.healthz_max_error_rate = float(healthz_max_error_rate or 0.0)
        # bounded result-write backpressure: attempts before a result
        # is abandoned to the dead-letter stream (never < 1)
        if result_write_retries is None:
            result_write_retries = get_config().get(
                "serving.result_write_retries", 8)
        self.result_write_retries = max(int(result_write_retries), 1)
        # admission control: a record older than request_deadline_ms
        # is shed (dead-lettered reason=shed + error result) instead
        # of burning predict capacity on a response nobody is waiting
        # for.  0 disables shedding entirely.  While the stream
        # backlog exceeds healthz_max_queue (the worker is already
        # 503-not-ready), records past HALF the deadline are shed too:
        # behind a >threshold queue they would age out before their
        # predict anyway.
        if request_deadline_ms is None:
            request_deadline_ms = get_config().get(
                "serving.request_deadline_ms", 0)
        self.request_deadline_ms = int(request_deadline_ms or 0)
        # crash recovery: minimum idle time before another worker's
        # un-acked pending entries are claimed.  Must comfortably
        # exceed one worst-case batch (decode + predict + result
        # writes) so an alive-but-slow replica is not robbed, and
        # should stay BELOW the supervisor's restart window (backoff +
        # respawn + warm start): then a dead replica's in-flight
        # records are already re-served by its peers by the time its
        # replacement comes up.  The reclaim poll tick is derived from
        # it (min_idle/2, clamped to [0.25s, 10s]).
        if reclaim_min_idle_ms is None:
            reclaim_min_idle_ms = get_config().get(
                "serving.reclaim_min_idle_ms", 30000)
        self.reclaim_min_idle_ms = max(int(reclaim_min_idle_ms or 0), 0)
        # poison quarantine: total delivery attempts (the original
        # XREADGROUP delivery + reclaim re-deliveries, tracked by
        # request_id in POISON_ATTEMPTS_KEY) before a record that
        # keeps killing its worker is quarantined to the dead-letter
        # stream with reason=poison instead of being served again
        if poison_max_attempts is None:
            poison_max_attempts = get_config().get(
                "serving.poison_max_attempts", 2)
        self.poison_max_attempts = max(int(poison_max_attempts or 0), 1)
        # circuit breaker around broker ops: open after k consecutive
        # transport failures, half-open probe after cooldown.  0
        # disables (raw broker, pre-PR-9 behavior).
        if breaker_failures is None:
            breaker_failures = get_config().get(
                "serving.breaker_failures", 5)
        self.breaker_failures = int(breaker_failures or 0)
        if breaker_cooldown_s is None:
            breaker_cooldown_s = get_config().get(
                "serving.breaker_cooldown_s", 2.0)
        self.breaker_cooldown_s = max(float(breaker_cooldown_s or 0.0),
                                      0.05)
        # consumer_group set → multiple workers SHARE the stream, each
        # record served exactly once (the reference parallelizes per
        # Spark partition; redis-native scale-out uses XREADGROUP)
        self.consumer_group = consumer_group
        self.consumer_name = consumer_name
        # per-record input shape (no batch dim), e.g. (224, 224, 3):
        # when set, the worker AOT warm-starts the padded-batch predict
        # program at startup — from the persistent executable cache
        # when one is configured — instead of compiling inside the
        # first client's request (config.yaml ``params.input_shape:
        # 224,224,3``)
        if isinstance(input_shape, str):
            input_shape = tuple(
                int(d) for d in input_shape.replace("x", ",").split(",")
                if d.strip())
        self.input_shape = tuple(input_shape) if input_shape else None
        # continuous-batching knobs (serving engine v2): the bucket
        # ladder the batcher pads in-flight batches to ("1,4,16"; None
        # = powers of two up to batch_size), and how long the
        # empty-queue edge may wait for co-riders before dispatching a
        # partial bucket (0 = dispatch immediately — a lone request is
        # always served within batch_max_wait_ms plus one predict)
        if batch_max_wait_ms is None:
            batch_max_wait_ms = get_config().get(
                "serving.batch_max_wait_ms", 0.0)
        self.batch_max_wait_ms = max(float(batch_max_wait_ms or 0.0),
                                     0.0)
        self.batch_buckets = batch_buckets or None
        # HTTP/JSON fast path beside the Redis bulk path (None = off,
        # 0 = ephemeral port).  Binds metrics_host — the same
        # unauthenticated-endpoint caveat applies.
        self.http_port = None if http_port is None else int(http_port)
        if http_timeout_s is None:
            http_timeout_s = get_config().get(
                "serving.http_timeout_s", 30.0)
        self.http_timeout_s = float(http_timeout_s or 30.0)
        # multi-model endpoint spec: "name=pkg.module:builder" entries
        # separated by commas/whitespace, built + registered by the
        # CLI beside the primary model (which serves as 'default')
        self.endpoints = endpoints or None
        self.extra = extra or {}   # raw section.key entries (model.* etc)

    @classmethod
    def from_yaml(cls, path: str) -> "ServingConfig":
        cfg: Dict[str, Any] = {}
        section = None
        with open(path) as f:
            for line in f:
                raw = line.rstrip()
                if not raw or raw.lstrip().startswith("#"):
                    continue
                if not raw.startswith(" "):
                    section = raw.rstrip(":").strip()
                    continue
                k, _, v = raw.strip().partition(":")
                cfg[f"{section}.{k.strip()}"] = v.strip()
        return cls(
            redis_url=cfg.get("data.src"),
            batch_size=int(cfg.get("params.batch_size", 4) or 4),
            top_n=int(cfg.get("params.top_n", 1) or 1),
            log_dir=cfg.get("params.log_dir") or None,
            consumer_group=cfg.get("params.consumer_group") or None,
            consumer_name=cfg.get("params.consumer_name", "worker-0")
            or "worker-0",
            pipeline_depth=int(cfg.get("params.pipeline_depth", 2) or 2),
            metrics_port=(int(cfg["params.metrics_port"])
                          if cfg.get("params.metrics_port") not in
                          (None, "") else None),
            metrics_host=cfg.get("params.metrics_host") or None,
            healthz_max_queue=int(
                cfg.get("params.healthz_max_queue") or 0) or None,
            healthz_max_error_rate=float(
                cfg.get("params.healthz_max_error_rate") or 0.0) or None,
            result_write_retries=int(
                cfg.get("params.result_write_retries") or 0) or None,
            request_deadline_ms=int(
                cfg.get("params.request_deadline_ms") or 0) or None,
            reclaim_min_idle_ms=(
                int(cfg["params.reclaim_min_idle_ms"])
                if cfg.get("params.reclaim_min_idle_ms")
                not in (None, "") else None),   # explicit 0 = claim
                                                # stale entries now
            poison_max_attempts=int(
                cfg.get("params.poison_max_attempts") or 0) or None,
            breaker_failures=(int(cfg["params.breaker_failures"])
                              if cfg.get("params.breaker_failures")
                              not in (None, "") else None),
            breaker_cooldown_s=(
                float(cfg["params.breaker_cooldown_s"])
                if cfg.get("params.breaker_cooldown_s")
                not in (None, "") else None),   # explicit 0 clamps to
                                                # the 0.05s floor
            input_shape=cfg.get("params.input_shape") or None,
            batch_buckets=cfg.get("params.batch_buckets") or None,
            batch_max_wait_ms=(
                float(cfg["params.batch_max_wait_ms"])
                if cfg.get("params.batch_max_wait_ms")
                not in (None, "") else None),
            http_port=(int(cfg["params.http_port"])
                       if cfg.get("params.http_port")
                       not in (None, "") else None),   # explicit 0 =
                                                       # ephemeral port
            http_timeout_s=float(
                cfg.get("params.http_timeout_s") or 0.0) or None,
            endpoints=cfg.get("params.endpoints") or None,
            extra=cfg,
        )


class ClusterServing:
    """The Redis transport + composition root of the serving engine.

    The worker loop owns broker IO (read / shed / ack / reclaim /
    result writes); predicts happen on the engine's batcher thread,
    which continuously batches this transport's bulk groups with the
    HTTP fast path's singles and pads to AOT-warmed buckets."""

    def __init__(self, inference_model, config: ServingConfig = None,
                 broker=None):
        self.model = inference_model
        self.config = config or ServingConfig()
        cfg = self.config
        # ---- engine: batcher + executor + endpoint registry --------
        self.engine = ServingEngine(
            max_wait_ms=cfg.batch_max_wait_ms,
            default_timeout_s=max(cfg.http_timeout_s, 60.0))
        if inference_model is not None:
            self.engine.register(
                DEFAULT_ENDPOINT, inference_model, top_n=cfg.top_n,
                buckets=cfg.batch_buckets, batch_size=cfg.batch_size,
                input_shape=cfg.input_shape)
        self.engine.start()
        # ---- HTTP/JSON fast path (shares the engine queue) ---------
        self.http_transport: Optional[HttpTransport] = None
        if cfg.http_port is not None:
            self.http_transport = HttpTransport(
                self.engine, port=cfg.http_port,
                host=cfg.metrics_host or "127.0.0.1",
                timeout_s=cfg.http_timeout_s).start()
        # breaker-wrapped broker (serving.breaker_failures=0 for the
        # raw connection): a broker outage opens the circuit and every
        # op fast-fails until a half-open probe reconnects — the run
        # loop idles on CircuitOpenError instead of crash-looping
        self.broker = with_breaker(
            url=self.config.redis_url, broker=broker,
            failures=self.config.breaker_failures,
            cooldown_s=self.config.breaker_cooldown_s)
        self.summary = (InferenceSummary(self.config.log_dir, "serving")
                        if self.config.log_dir else None)
        self._stop = threading.Event()
        self._last_id = "0-0"
        self.total_records = 0
        self._group_ready = not self.config.consumer_group
        if self.config.consumer_group:
            try:
                self._ensure_group()
            except _BROKER_OUTAGE_EXCS as e:
                # broker down at bring-up: crashing here would make
                # the supervisor restart-loop the replica against a
                # dead broker — exactly what the breaker exists to
                # prevent.  The group is created lazily by the first
                # successful read attempt once the probe reconnects;
                # until then reads fail into the run loop's outage
                # idle path like any other broker op.
                log.warning(
                    "broker unavailable at startup (%s: %s); consumer "
                    "group %r will be created once it recovers",
                    type(e).__name__, e, self.config.consumer_group)
        # per-record arrival→result latencies (seconds), bounded
        self.latencies: deque = deque(maxlen=10000)
        self._serve_start: Optional[float] = None
        # entry ids read by THIS worker and not yet acked (in the
        # decode/predict pipeline) — the reclaim pass must not treat
        # them as another worker's stale pending
        self._inflight: set = set()
        # last time the (extra-broker-op) group-lag gauge refreshed
        self._backlog_obs_at = 0.0
        # THIS worker's last observed backlog.  /healthz and admission
        # control read this instance field, not the shared
        # ``serving_queue_depth`` gauge: the gauge is one registry-wide
        # series, so any other serving instance still draining in the
        # same process (tests, embedded multi-worker setups) could
        # overwrite it between a refresh and a readiness probe —
        # flipping this worker's verdict on someone else's traffic
        self._backlog_seen = 0.0
        # ---- observability: shared-registry instruments + /metrics --
        reg = get_registry()
        self._m_latency = reg.histogram(
            "serving_request_latency_seconds",
            "stream-arrival to result-write latency per record")
        self._m_records = reg.counter(
            "serving_records_total", "records served")
        self._m_errors = reg.counter(
            "serving_errors_total",
            "records acked with an error result (decode/poison)")
        self._m_queue = reg.gauge(
            "serving_queue_depth", "input stream length at last poll")
        self._m_redis_retry = reg.counter(
            "serving_redis_retry_total",
            "result-write attempts retried after a broker error")
        self._m_write_abandoned = reg.counter(
            "serving_result_write_abandoned_total",
            "results abandoned (dead-lettered) after the bounded "
            "write-backoff was exhausted")
        self._m_reclaimed = reg.counter(
            "serving_reclaimed_total",
            "stale pending records reclaimed from dead workers")
        self._m_shed = reg.counter(
            "serving_shed_total",
            "records shed by admission control instead of predicted",
            labels=("cause",))
        self._m_quarantined = reg.counter(
            "serving_quarantined_total",
            "poison records quarantined to the dead-letter stream "
            "after repeatedly killing their worker")
        self._m_dead_letter = reg.counter(
            "serving_dead_letter_total",
            "records written to the serving_dead_letter stream, by "
            "reason", labels=("reason",))
        self._tracer = get_tracer()
        self._telemetry: Optional[TelemetrySampler] = None
        # readiness window: 1 per recently served record, 0 per record
        # acked with an error result — the error-rate half of /healthz.
        # The lock pairs the worker thread's extend with the /healthz
        # thread's snapshot: list(deque) raises if the deque mutates
        # mid-iteration, which would flip a healthy worker to 503.
        self._recent_outcomes: deque = deque(maxlen=200)
        self._outcomes_lock = threading.Lock()
        # True while warm_start() compiles/loads the predict program:
        # /healthz answers 503 warming_up (alive, not routable)
        self._warming = False
        # chaos-site step counters (decode runs in the pool →
        # itertools.count.__next__ is atomic under the GIL)
        self._decode_seq = itertools.count()
        self._predict_seq = itertools.count()
        self.metrics_server: Optional[MetricsServer] = None
        if self.config.metrics_port is not None:
            self.metrics_server = MetricsServer(
                port=self.config.metrics_port,
                host=self.config.metrics_host,
                health_check=self.readiness).start()

    # ------------------------------------------------------------ endpoints
    def register_endpoint(self, name: str, model, *,
                          top_n: Optional[int] = None,
                          buckets=None, input_shape=None,
                          weight: int = 1):
        """Register an additional model under ``name`` (multi-model
        serving): records carrying an ``endpoint`` field — and HTTP
        ``POST /predict/<name>`` — route to it.  Per-endpoint knobs
        default to this worker's config."""
        cfg = self.config
        return self.engine.register(
            name, model,
            top_n=cfg.top_n if top_n is None else top_n,
            buckets=buckets or cfg.batch_buckets,
            batch_size=cfg.batch_size,
            input_shape=input_shape or cfg.input_shape,
            weight=weight)

    def register_generative_endpoint(self, name: str, model, *,
                                     enc_len: int, start_sign: int,
                                     stop_sign: Optional[int] = None,
                                     max_seq_len: int = 32,
                                     slots: Optional[int] = None,
                                     buckets=None, weight: int = 1):
        """Register a *generative* model (``Seq2seq``'s decode
        contract) under ``name``: records routed to it are token
        SEQUENCES served by the decode-step scheduler — admitted into
        a device-resident slot pool, decoded one iteration at a time
        with EOS early-exit and same-iteration backfill, their results
        written as the emitted token list.  Stream records may carry a
        ``max_tokens`` field (client ``enqueue(..., max_tokens=)``)
        to cap their own sequence."""
        cfg = self.config
        # the worker's request_deadline_ms covers this endpoint too:
        # queued (not-yet-admitted) sequences past the deadline are
        # shed at the slot-pool gate instead of bypassing the PR 9
        # admission-control contract the stateless path honors
        return self.engine.register_generative(
            name, model, enc_len=enc_len, start_sign=start_sign,
            stop_sign=stop_sign, max_seq_len=max_seq_len,
            slots=cfg.batch_size if slots is None else slots,
            buckets=buckets or cfg.batch_buckets or (),
            weight=weight,
            request_deadline_ms=cfg.request_deadline_ms)

    # ----------------------------------------------------------- warm-start
    def warm_start(self) -> bool:
        """AOT warm-start of EVERY endpoint's full bucket ladder (the
        batcher pads in-flight batches to the nearest bucket, so each
        rung is its own executable — warm them all and a post-warm-up
        run never compiles, whatever the fill level).  With a
        persistent executable cache configured
        (``ZOO_TPU_COMPILE_CACHE`` / ``compile.cache_dir``), a replica
        respawn deserializes in seconds instead of recompiling — the
        serving half of the 141s-cold-start fix.  No-op for endpoints
        without an ``input_shape``."""
        t0 = time.perf_counter()
        warmed = self.engine.warm_start()
        total = sum(warmed.values())
        if total:
            log.info("predict warm start: %d bucket program(s) ready "
                     "in %.2fs (%s)", total, time.perf_counter() - t0,
                     warmed)
        return total > 0

    # ----------------------------------------------------------- dead letter
    def dead_letter(self, reason: str, *, uri: Optional[str] = None,
                    request_id: Optional[str] = None,
                    cause: Optional[str] = None,
                    error: Optional[BaseException] = None,
                    extra: Optional[Dict[str, str]] = None) -> bool:
        """The ONE write path to the ``serving_dead_letter`` stream
        (reasons: ``write_abandoned`` | ``shed`` | ``poison``): builds
        the entry, counts it under
        ``serving_dead_letter_total{reason}``, and absorbs broker
        failures — giving up on a record must never also kill the
        worker loop.  Returns whether the entry landed."""
        entry: Dict[str, str] = {
            "uri": uri or "",
            "request_id": request_id or "",
            "reason": reason,
        }
        if cause:
            entry["cause"] = cause
        if error is not None:
            entry["error"] = f"{type(error).__name__}: {error}"
        entry.update(extra or {})
        self._m_dead_letter.labels(reason).inc()
        if reason != "shed":
            # flight-record the rare, diagnosis-bearing dead letters
            # (write_abandoned = broker trouble, poison = quarantine);
            # shed is normal overload control and would flood the ring
            flightrec.record_event(
                "dead_letter", reason=reason, uri=uri or "",
                request_id=request_id or "")
        try:
            self.broker.xadd(DEAD_LETTER_STREAM, entry)
            return True
        except Exception:   # noqa: BLE001 — the broker may be down
            log.exception(
                "dead-letter write failed for %s (reason=%s; broker "
                "down?); the request_id above is the only record",
                uri, reason)
            return False

    # ------------------------------------------------------------ main loop
    def run_once(self, block_ms: int = 100) -> int:
        """One poll/predict/write cycle; returns #records served."""
        # zoolint: disable=RACE016 — serve-loop confined: run()/run_once() are driven by exactly ONE thread (foreground main or the single background runner), never both
        self._serve_start = self._serve_start or time.perf_counter()
        entries = self._read_entries(self.config.batch_size, block_ms)
        if not entries:
            return 0
        t0 = time.perf_counter()
        real = self._serve_entries(entries, t0)
        if self.summary is not None and real:
            self.summary.add_scalar(
                "Serving Throughput",
                real / max(time.perf_counter() - t0, 1e-9),
                # zoolint: disable=RACE016 — serve-loop confined counter (single driver thread)
                self.total_records)
        self._observe_queue()
        return real

    def _backlog(self) -> int:
        """The input-stream BACKLOG this worker group still owes:
        undelivered + pending via ``xlag`` in consumer-group mode
        (served entries stay in the stream until trimmed, so ``XLEN``
        reads high forever), stream length otherwise (a solo reader
        advances ``_last_id`` but legacy dashboards key on length).
        Transport failures propagate like any broker op."""
        cfg = self.config
        if cfg.consumer_group:
            xlag = getattr(self.broker, "xlag", None)
            if xlag is not None:
                try:
                    return int(xlag(INPUT_STREAM, cfg.consumer_group))
                except _BROKER_OUTAGE_EXCS:
                    raise
                except Exception:   # noqa: BLE001 — duck broker
                    pass
        return self.broker.xlen(INPUT_STREAM)

    def _observe_queue(self) -> None:
        """Refresh ``serving_queue_depth`` (the /healthz, shedding,
        and autoscaler signal) and apply the stream OOM guard
        (ClusterServing.scala:128-134).  In consumer-group mode the
        gauge is the true lag (``xlag`` = one extra broker op), so it
        is throttled to ~4 Hz — the per-batch hot path stays at the
        single XLEN round trip it always paid; solo-reader mode keeps
        xlen, which the XLEN below already fetched."""
        qlen = self.broker.xlen(INPUT_STREAM)
        if not self.config.consumer_group:
            self._note_backlog(qlen)
        elif time.perf_counter() - self._backlog_obs_at >= 0.25:
            self._note_backlog(self._backlog())
            # zoolint: disable=ATOM017 — serve-loop confined throttle clock: only the single driver thread runs _observe_queue
            self._backlog_obs_at = time.perf_counter()
        if qlen > self.config.max_stream_len:
            self.broker.xtrim(INPUT_STREAM, self.config.max_stream_len)

    def _note_backlog(self, depth: float) -> None:
        """Record an observed input-stream backlog: the exported gauge
        (autoscaler / dashboards) AND this worker's own readiness/
        admission view of it."""
        self._backlog_seen = float(depth)
        self._m_queue.set(depth)

    def _write_result(self, uri: str, value: str,
                      retries: Optional[int] = None,
                      request_id: Optional[str] = None) -> bool:
        """Write one result with BOUNDED backpressure (ref :254-289
        retried "infinite-ish" and then raised, killing the worker
        loop with the rest of the batch un-acked): exponential backoff
        with jitter between attempts (jitter de-synchronizes the
        worker fleet hammering a recovering broker), then the record
        is ABANDONED — counted, logged, and dead-lettered with its
        request_id — so one unwritable result can never crash the
        loop.  The request_id from the matching enqueue is echoed
        beside the result so a client can correlate response <->
        request across processes.  Returns True when the write
        landed."""
        fields = {"value": value}
        if request_id:
            fields["request_id"] = request_id
        if retries is None:
            retries = self.config.result_write_retries
        attempts = max(int(retries), 1)
        delay = 0.05
        last_exc: Optional[Exception] = None
        for attempt in range(attempts):
            try:
                self.broker.hset(RESULT_PREFIX + uri, fields)
                return True
            except Exception as e:   # noqa: BLE001 — broker flake class
                last_exc = e
                self._m_redis_retry.inc()
                if attempt + 1 >= attempts:
                    break
                import random
                time.sleep(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, 2.0)
        self._m_write_abandoned.inc()
        log.error("abandoning result write for %s after %d attempts "
                  "(%s: %s); dead-lettering", uri, attempts,
                  type(last_exc).__name__, last_exc)
        self.dead_letter("write_abandoned", uri=uri,
                         request_id=request_id, error=last_exc,
                         extra={"abandoned_unix": f"{time.time():.3f}"})
        return False

    # -------------------------------------------------- pipelined serving
    def _ensure_group(self) -> None:
        """Create the consumer group if this worker has not managed to
        yet (idempotent; deferred past __init__ when the broker was
        down at bring-up)."""
        if not self._group_ready:
            self.broker.xgroup_create(INPUT_STREAM,
                                      self.config.consumer_group)
            # zoolint: disable=ATOM017 — serve-loop confined lazy init (and xgroup_create is idempotent MKSTREAM)
            self._group_ready = True

    def _read_entries(self, count: int, block_ms: int):
        """Read the next batch: plain XREAD (single worker owns the
        stream) or XREADGROUP (workers share it, exactly-once
        delivery)."""
        cfg = self.config
        if cfg.consumer_group:
            self._ensure_group()
            return self.broker.xreadgroup(
                cfg.consumer_group, cfg.consumer_name, INPUT_STREAM,
                count=count, block_ms=block_ms)
        entries = self.broker.xread(INPUT_STREAM, self._last_id,
                                    count=count, block_ms=block_ms)
        for entry_id, _f in entries:
            self._last_id = entry_id
        return entries

    def _ack(self, entries) -> None:
        if self.config.consumer_group and entries:
            self.broker.xack(INPUT_STREAM, self.config.consumer_group,
                             *[i for i, _ in entries])

    def _reclaim_stale(self, min_idle_ms: Optional[int] = None):
        """Crash recovery: claim entries another worker read but never
        acknowledged (died between XREADGROUP and XACK) and serve them
        — without this, records in a dead worker's pending list would
        wait forever.

        Reclaimed records are served ONE AT A TIME under the poison-
        quarantine contract: a record on this path has already been
        delivered and never acknowledged (its worker likely died on
        it), so before each individual serve its delivery count is
        persisted to ``POISON_ATTEMPTS_KEY`` — a crash mid-serve still
        counts.  A record whose total deliveries would exceed
        ``poison_max_attempts`` is quarantined to the dead-letter
        stream (reason=poison) instead of killing this replica too.
        Individual serving also shields the innocent co-batched
        records: they are served (and their count cleared) before or
        after the poison one dies, instead of sharing its fate
        forever."""
        cfg = self.config
        if not cfg.consumer_group:
            return 0
        if min_idle_ms is None:
            min_idle_ms = cfg.reclaim_min_idle_ms
        try:
            entries = self.broker.xautoclaim(
                INPUT_STREAM, cfg.consumer_group, cfg.consumer_name,
                min_idle_ms, count=cfg.batch_size)
        except Exception:
            log.exception("xautoclaim failed")
            return 0
        # XAUTOCLAIM does not exclude the caller: under a deep backlog
        # (pipeline_depth batches waiting > min_idle_ms) it hands back
        # THIS worker's own un-acked in-flight entries — serving those
        # here would double-predict and double-write them.
        entries = [e for e in entries if e[0] not in self._inflight]
        if not entries:
            return 0
        try:
            counts = self.broker.hgetall(POISON_ATTEMPTS_KEY)
        except Exception:   # noqa: BLE001 — count-less reclaim is fine
            counts = {}
        real = served = 0
        for entry_id, fields in entries:
            key = self._rid_of(fields) or str(entry_id)
            # idempotent completion (found by the ISSUE 14 storm
            # harness): a record whose result ALREADY sits in the
            # result table under its own request_id was fully served
            # by a pass whose ACK the broker outage swallowed — the
            # only thing left to do is finish the ack.  Re-serving it
            # would double-predict; worse, letting it ride the poison
            # judgment would eventually QUARANTINE an innocent record
            # and overwrite its delivered result with an error (the
            # mark-before-serve attempt count below persists across
            # the interrupted pass by design — a crash mid-serve must
            # count — so outage-interrupted passes accumulate blame
            # the record never earned).
            if self._reclaim_already_served(entry_id, fields, key):
                served += 1
                continue
            attempts = int(counts.get(key, 0) or 0)
            # total deliveries so far = the original XREADGROUP
            # delivery + `attempts` reclaim re-serves; would this
            # re-serve exceed the budget?
            if attempts + 1 >= cfg.poison_max_attempts:
                self._quarantine(entry_id, fields, attempts + 1)
                continue
            try:
                self.broker.hset(POISON_ATTEMPTS_KEY,
                                 {key: str(attempts + 1)})
            except Exception:   # noqa: BLE001 — serve counts anyway
                log.exception("poison-attempt mark failed for %s", key)
            # a reclaimed record can be the very poison that killed
            # its original worker — an in-process failure is absorbed
            # by _serve_entries' poison contract; a process-killing
            # one leaves the count above persisted for the NEXT
            # reclaimer's verdict
            real += self._serve_entries([(entry_id, fields)],
                                        time.perf_counter())
            served += 1
            try:
                self.broker.hdel(POISON_ATTEMPTS_KEY, key)
            except Exception:   # noqa: BLE001 — stale count is benign
                pass
        self._m_reclaimed.inc(served)
        log.info("reclaimed %d stale pending records (%d served, "
                 "%d error-resulted, %d quarantined)", len(entries),
                 real, served - real, len(entries) - served)
        return real

    def _reclaim_already_served(self, entry_id, fields,
                                key: str) -> bool:
        """Whether this reclaimed record's result is already written
        UNDER ITS OWN request_id — i.e. an earlier serve completed
        and only the ack was lost to a broker outage.  If so, finish
        the ack and clear the poison-attempt mark; returns True
        (nothing left to serve).  Records without a request_id cannot
        be safely matched (result keys are per-uri, and a client may
        legitimately reuse a uri), so they take the normal path.
        Broker failures while CHECKING propagate like any reclaim op
        — the run loop's outage idle handles them."""
        rid = self._rid_of(fields)
        uri = self._uri_of(fields)
        if not rid or not uri:
            return False
        existing = self.broker.hgetall(RESULT_PREFIX + uri)
        got = existing.get("request_id",
                           existing.get(b"request_id"))
        if isinstance(got, bytes):
            got = got.decode()
        if got != rid:
            return False
        log.info("reclaimed record %s (request_id=%s) was already "
                 "served; finishing its lost ack instead of "
                 "re-serving", entry_id, rid)
        self._ack([(entry_id, fields)])
        try:
            self.broker.hdel(POISON_ATTEMPTS_KEY, key)
        except Exception:   # noqa: BLE001 — orphan count is benign
            pass            # once the record is acked out of the PEL
        return True

    def _quarantine(self, entry_id, fields, deliveries: int) -> None:
        """Dead-letter a record that keeps killing its workers
        (reason=poison), give its client an explicit error result, and
        ack it out of the PEL so it can never be delivered again."""
        uri, rid = self._uri_of(fields), self._rid_of(fields)
        log.error("quarantining poison record %s (uri=%s, request_id="
                  "%s) after %d deliveries", entry_id, uri, rid,
                  deliveries)
        self.dead_letter(
            "poison", uri=uri, request_id=rid,
            extra={"entry_id": str(entry_id),
                   "deliveries": str(deliveries),
                   "quarantined_unix": f"{time.time():.3f}"})
        flightrec.record_event(
            "quarantine", entry_id=str(entry_id), uri=uri or "",
            request_id=rid or "", deliveries=deliveries)
        if uri:
            self._write_result(uri, json.dumps({
                "error": f"poison: quarantined after "
                         f"{deliveries} deliveries"}),
                request_id=rid)
        self._m_quarantined.inc()
        self._m_errors.inc()
        ctx = TraceContext.from_wire(self._trace_of(fields),
                                     request_id=rid)
        if ctx is not None:
            reqlog = get_request_log()
            reqlog.begin(ctx, transport="redis",
                         station="transport_receive")
            reqlog.finish(ctx, "quarantined", station="result_write",
                          deliveries=deliveries)
        with self._outcomes_lock:
            self._recent_outcomes.append(0)
        self._ack([(entry_id, fields)])
        try:
            self.broker.hdel(POISON_ATTEMPTS_KEY,
                             rid or str(entry_id))
        except Exception:   # noqa: BLE001 — stale count is benign
            pass

    def _decode_batch(self, entries):
        """Decode one batch of raw stream entries (runs in the decode
        pool — pure CPU, no broker IO, so no connection sharing across
        threads).  Undecodable records are collected into ``failed``
        (uri, request_id, exception) rather than silently dropped —
        the serve path writes them an error result, because acking
        consumes the record and a consumed record with no result
        strands its client."""
        chaos = active_chaos()
        if chaos is not None:
            chaos.trip(SITE_SERVING_DECODE, next(self._decode_seq))
        uris, arrays, rids, eps, mts, failed = [], [], [], [], [], []
        traces = []
        for entry_id, fields in entries:
            try:
                uri, arr, rid = decode_field(fields)
            except Exception as e:
                log.exception("undecodable record %s", entry_id)
                failed.append((self._uri_of(fields),
                               self._rid_of(fields), e))
                ctx = TraceContext.from_wire(
                    self._trace_of(fields),
                    request_id=self._rid_of(fields))
                if ctx is not None:
                    reqlog = get_request_log()
                    reqlog.begin(ctx, transport="redis",
                                 station="transport_receive")
                    reqlog.finish(ctx, "error",
                                  station="result_write")
                continue
            uris.append(uri)
            arrays.append(arr)
            rids.append(rid)
            eps.append(self._endpoint_of(fields))
            mts.append(self._max_tokens_of(fields))
            traces.append(self._trace_of(fields))
        return uris, arrays, failed, rids, eps, mts, traces

    @staticmethod
    def _uri_of(fields) -> str:
        uri = fields.get("uri", b"") if hasattr(fields, "get") else b""
        return uri.decode() if isinstance(uri, bytes) else uri

    @staticmethod
    def _rid_of(fields):
        rid = fields.get("request_id") if hasattr(fields, "get") \
            else None
        return rid.decode() if isinstance(rid, bytes) else rid

    @staticmethod
    def _trace_of(fields):
        """The record's ``trace`` wire string (client-stamped
        TraceContext); None for records enqueued without one.  Rides
        XAUTOCLAIM unchanged, so a reclaimed record keeps its original
        trace_id."""
        tw = fields.get(TRACE_FIELD) if hasattr(fields, "get") \
            else None
        return tw.decode() if isinstance(tw, bytes) else tw

    @staticmethod
    def _endpoint_of(fields) -> str:
        """Multi-model routing: the record's ``endpoint`` field (the
        client's ``enqueue(..., endpoint=)``), defaulting to the
        single-model endpoint."""
        ep = fields.get("endpoint") if hasattr(fields, "get") else None
        if isinstance(ep, bytes):
            ep = ep.decode()
        return ep or DEFAULT_ENDPOINT

    @staticmethod
    def _max_tokens_of(fields) -> Optional[int]:
        """Generative records may cap their own sequence length
        (client ``enqueue(..., max_tokens=)``); None elsewhere."""
        mt = fields.get("max_tokens") if hasattr(fields, "get") \
            else None
        if isinstance(mt, bytes):
            mt = mt.decode()
        try:
            return int(mt) if mt else None
        except (TypeError, ValueError):
            return None

    # ------------------------------------------------- admission control
    @staticmethod
    def _entry_age_ms(entry_id, now_ms: float) -> Optional[float]:
        """Age of a stream entry from the ms half of its id (stream
        ids are ``<epoch-ms>-<seq>``); None when unparseable."""
        if isinstance(entry_id, bytes):
            entry_id = entry_id.decode()
        try:
            ms = int(str(entry_id).partition("-")[0])
        except (TypeError, ValueError):
            return None
        return now_ms - ms

    def _shed_expired(self, entries):
        """Deadline-aware load shedding (``params.request_deadline_ms``
        > 0 opts in): a record older than its deadline is shed —
        dead-lettered with reason=shed + an explicit error result +
        acked — instead of burning predict capacity on a response its
        client stopped waiting for.  While the backlog at the last
        poll exceeds ``params.healthz_max_queue`` (the same threshold
        that 503s `/healthz`), records past HALF the deadline are shed
        too: behind a >threshold queue they would age out before their
        own predict anyway — shedding them is what lets a drowning
        worker catch back up to fresh traffic.  Returns the admitted
        entries."""
        cfg = self.config
        deadline = float(cfg.request_deadline_ms)
        if not entries or deadline <= 0:
            return entries
        overloaded = (cfg.healthz_max_queue > 0
                      and self._backlog_seen > cfg.healthz_max_queue)
        cut = deadline / 2.0 if overloaded else deadline
        now_ms = time.time() * 1000.0
        keep, shed = [], []
        for entry_id, fields in entries:
            age = self._entry_age_ms(entry_id, now_ms)
            if age is None or age <= cut:
                keep.append((entry_id, fields))
            else:
                cause = "deadline" if age > deadline else "overload"
                shed.append((entry_id, fields, age, cause))
        for entry_id, fields, age, cause in shed:
            uri, rid = self._uri_of(fields), self._rid_of(fields)
            self.dead_letter(
                "shed", uri=uri, request_id=rid, cause=cause,
                extra={"age_ms": f"{age:.0f}",
                       "deadline_ms": f"{deadline:.0f}"})
            if uri:
                self._write_result(uri, json.dumps({
                    "error": f"shed: {cause} ({age:.0f}ms old, "
                             f"deadline {deadline:.0f}ms)"}),
                    request_id=rid)
            self._m_shed.labels(cause).inc()
            ctx = TraceContext.from_wire(self._trace_of(fields),
                                         request_id=rid)
            if ctx is not None:
                reqlog = get_request_log()
                reqlog.begin(ctx, transport="redis",
                             station="transport_receive")
                reqlog.finish(ctx, "shed", station="result_write",
                              cause=cause, age_ms=round(age, 1))
        if shed:
            # shed records are deliberate drops, not worker errors —
            # they are acked (consumed) but kept OUT of the /healthz
            # error-rate window: admission control under overload must
            # not also flip the probe that is already watching the
            # queue-depth threshold
            self._ack([(i, f) for i, f, _a, _c in shed])
            log.warning("shed %d records (%s)", len(shed),
                        ", ".join(sorted({c for *_x, c in shed})))
        return keep

    def _serve_entries(self, entries, t_arrival: float) -> int:
        """Decode + serve one raw batch with admission control and the
        poison-batch contract applied (shared by run_once and
        _reclaim_stale; the pipelined loop sheds BEFORE submitting
        decode work instead, so an expired record costs no decode
        either).  Returns #served."""
        entries = self._shed_expired(entries)
        if not entries:
            return 0
        try:
            decoded = self._decode_batch(entries)
        except Exception as e:
            log.exception("decode failed for batch (%d records)",
                          len(entries))
            decoded = ([], [], [(self._uri_of(f), self._rid_of(f), e)
                                for _, f in entries])
        return self._serve_decoded(decoded, t_arrival, entries)

    def _serve_decoded(self, decoded, t_arrival: float, entries) -> int:
        """Predict + write a decoded batch, then ack it.  The poison
        contract: NO failure in predict/write may escape (it would kill
        the worker loop with the batch un-acked), and every record that
        is acked without a prediction gets an explicit ERROR result so
        its client never blocks forever on a consumed record.
        ``decoded`` is (uris, arrays[, failed[, request_ids[,
        endpoints[, max_tokens[, traces]]]]])."""
        uris, arrays, *rest = decoded
        failed = list(rest[0]) if rest else []
        rids = list(rest[1]) if len(rest) > 1 else [None] * len(uris)
        eps = list(rest[2]) if len(rest) > 2 else \
            [DEFAULT_ENDPOINT] * len(uris)
        mts = list(rest[3]) if len(rest) > 3 else [None] * len(uris)
        traces = list(rest[4]) if len(rest) > 4 else [None] * len(uris)
        real = 0
        try:
            real = self._predict_write(uris, arrays, t_arrival, rids,
                                       eps, mts, traces)
        except Exception as e:
            log.exception("poison batch skipped (%d records)",
                          len(entries))
            failed += [(u, r, e) for u, r in zip(uris, rids)]
        for uri, rid, exc in failed:
            try:
                if uri:
                    self._write_result(uri, json.dumps(
                        {"error": f"{type(exc).__name__}: {exc}"}),
                        request_id=rid)
            except Exception:
                log.exception("could not write error result for %s", uri)
        self._m_errors.inc(len(failed))
        # readiness window: successes then failures, per record
        with self._outcomes_lock:
            self._recent_outcomes.extend([1] * real + [0] * len(failed))
        self._ack(entries)
        return real

    def _predict_write(self, uris, arrays, t_arrival: float,
                       rids=None, endpoints=None,
                       max_tokens=None, traces=None) -> int:
        """Submit one decoded bulk batch to the engine as atomic
        per-endpoint groups, wait for the batcher's bucket-padded
        predicts, and write every result; returns #served.

        The engine fails (rather than raises) model errors, so a
        poisoned group costs error results for exactly its own
        records; a non-``Exception`` escape (the simulated-process-
        death class) re-raises here so the loop dies with the batch
        un-acked — the PEL-reclaim trigger, exactly as before the
        engine split."""
        if not arrays:
            return 0
        if rids is None:
            rids = [None] * len(uris)
        if endpoints is None:
            endpoints = [DEFAULT_ENDPOINT] * len(uris)
        if max_tokens is None:
            max_tokens = [None] * len(uris)
        if traces is None:
            traces = [None] * len(uris)
        real = len(arrays)
        # the chaos site fires BEFORE the engine hand-off: a ``kill``
        # here is a replica dying mid-batch with the batch un-acked —
        # the scripted trigger for PEL reclaim and poison quarantine
        chaos = active_chaos()
        if chaos is not None:
            chaos.trip(SITE_SERVING_PREDICT, next(self._predict_seq))
        # group by endpoint (a bulk read may interleave models); each
        # group rides the engine as one atomic unit
        reqlog = get_request_log()
        now = time.perf_counter()
        groups: Dict[str, List[Request]] = {}
        for uri, arr, rid, ep, mt, tw in zip(uris, arrays, rids,
                                             endpoints, max_tokens,
                                             traces):
            ctx = None
            if reqlog.enabled:
                # a client-stamped trace rides the record's ``trace``
                # field; untraced records get a server-side context so
                # the replica's forensics cover ALL traffic (malformed
                # wires stay untraced, per from_wire's contract)
                ctx = (TraceContext.from_wire(tw, request_id=rid)
                       if tw else TraceContext.new(rid))
                if ctx is not None:
                    reqlog.begin(
                        ctx, transport="redis",
                        endpoint=ep or DEFAULT_ENDPOINT,
                        station="transport_receive", t=t_arrival)
                    reqlog.mark(ctx, "decode", t=now)
            groups.setdefault(ep or DEFAULT_ENDPOINT, []).append(
                Request(endpoint=ep or DEFAULT_ENDPOINT, uri=uri,
                        data=arr, request_id=rid, arrival=t_arrival,
                        max_tokens=mt, trace=ctx))
        # the span carries the batch's request ids, so a trace viewer
        # (or the merged cluster timeline) can follow one request from
        # client enqueue through its predict to its result write
        with self._tracer.span(
                "serving_predict", records=real,
                request_ids=[r for r in rids if r][:16]):
            requests: List[Request] = []
            for reqs in groups.values():
                requests.extend(self.engine.submit(reqs))
            self.engine.wait_all(requests)
        fatal = next((r.error for r in requests
                      if r.error is not None
                      and not isinstance(r.error, Exception)), None)
        if fatal is not None:
            raise fatal
        done = time.perf_counter()
        written = predicted = failed = 0
        for req in requests:
            if req.error is not None:
                if isinstance(req.error, ShedError):
                    # an ENGINE-level admission drop (generative
                    # queue-wait past request_deadline_ms): the same
                    # contract as the stream path's _shed_expired —
                    # dead-lettered with its age/deadline evidence
                    # (the verdict proves every shed was
                    # deadline-earned from these fields), an explicit
                    # error result, and kept OUT of the error
                    # accounting/readiness window: a deliberate drop
                    # is not a worker failure
                    self.dead_letter(
                        "shed", uri=req.uri,
                        request_id=req.request_id, cause="deadline",
                        extra={
                            "age_ms": f"{req.error.age_ms:.0f}",
                            "deadline_ms":
                                f"{req.error.deadline_ms:.0f}"})
                    # serving_shed_total{deadline} was already
                    # counted by the engine at the moment it shed
                    try:
                        if req.uri:
                            self._write_result(req.uri, json.dumps(
                                {"error": str(req.error)}),
                                request_id=req.request_id)
                    except Exception:
                        log.exception("could not write shed result "
                                      "for %s", req.uri)
                    reqlog.finish(req.trace, "shed",
                                  station="result_write")
                    continue
                # predict failed for this record's group: explicit
                # error result, error accounting, readiness window 0
                # — same consumed-record contract as a decode failure
                failed += 1
                try:
                    if req.uri:
                        self._write_result(req.uri, json.dumps(
                            {"error": f"{type(req.error).__name__}: "
                                      f"{req.error}"}),
                            request_id=req.request_id)
                except Exception:
                    log.exception("could not write error result "
                                  "for %s", req.uri)
                reqlog.finish(req.trace, "error",
                              station="result_write")
                continue
            predicted += 1
            if self._write_result(req.uri, json.dumps(req.result),
                                  request_id=req.request_id):
                written += 1
                self.latencies.append(done - t_arrival)
                self._m_latency.observe(done - t_arrival,
                                        exemplar=req.trace_id)
                reqlog.finish(req.trace, "ok",
                              station="result_write")
            else:
                # abandoned write: the client never sees this result
                reqlog.finish(req.trace, "error",
                              station="result_write")
        if failed:
            self._m_errors.inc(failed)
            with self._outcomes_lock:
                self._recent_outcomes.extend([0] * failed)
        abandoned = predicted - written
        if abandoned:
            # a dead-lettered result is a FAILURE to error accounting
            # and the /healthz error-rate window — the bounded path
            # must keep the readiness probe honest during a result-
            # write outage (an orchestrator should pull a worker whose
            # results never land)
            self._m_errors.inc(abandoned)
            with self._outcomes_lock:
                self._recent_outcomes.extend([0] * abandoned)
        # total_records counts records PROCESSED (drain/progress
        # bookkeeping); the return value counts records actually
        # DELIVERED — the outcome window gets its 1s from the caller
        self.total_records += predicted
        self._m_records.inc(predicted)
        if self.summary is not None:
            self.summary.add_scalar("Total Records Number",
                                    self.total_records,
                                    self.total_records)
        return written

    def readiness(self) -> Optional[Dict[str, Any]]:
        """The /healthz readiness probe (wired into the
        MetricsServer): None when ready, else a JSON-able reason dict
        — the endpoint answers 503 with it.  Thresholds come from
        config.yaml ``params.healthz_max_queue`` /
        ``params.healthz_max_error_rate`` (0 = check disabled).  An
        OPEN circuit breaker is always not-ready: the broker is down,
        so routing here is pointless — but the process is alive and
        fast-failing, which is exactly why the supervisor watches
        /healthz for liveness yet only restarts on *unreachable*
        (restarting cannot fix a dead broker)."""
        cfg = self.config
        if self._warming:
            # predict program compiling / cache-loading: alive (the
            # supervisor must not no-port kill a cold replica) but
            # not ready for routing yet
            return {"reason": "warming_up"}
        breaker = getattr(self.broker, "breaker", None)
        if breaker is not None and breaker.state == BREAKER_OPEN:
            return {"reason": "breaker_open",
                    "cooldown_s": breaker.cooldown_s}
        if cfg.healthz_max_queue > 0:
            depth = self._backlog_seen
            if depth > cfg.healthz_max_queue:
                return {"reason": "queue_depth",
                        "queue_depth": int(depth),
                        "threshold": cfg.healthz_max_queue}
        if cfg.healthz_max_error_rate > 0 and self._recent_outcomes:
            with self._outcomes_lock:
                outcomes = list(self._recent_outcomes)
            rate = 1.0 - sum(outcomes) / len(outcomes)
            if rate > cfg.healthz_max_error_rate:
                return {"reason": "error_rate",
                        "error_rate": round(rate, 4),
                        "window": len(outcomes),
                        "threshold": cfg.healthz_max_error_rate}
        return None

    def stats(self) -> Dict[str, float]:
        """Throughput + latency percentiles over the records served so
        far (the reference's TensorBoard serving scalars, :294-317,
        plus percentiles)."""
        lat = sorted(self.latencies)
        pct = lambda p: (lat[min(int(p / 100 * len(lat)),
                                 len(lat) - 1)] * 1e3) if lat else 0.0
        wall = (time.perf_counter() - self._serve_start) \
            if self._serve_start else 0.0
        return {
            "total_records": self.total_records,
            "throughput_rps": self.total_records / wall if wall else 0.0,
            "latency_p50_ms": pct(50),
            "latency_p95_ms": pct(95),
            "latency_p99_ms": pct(99),
        }

    def _should_stop(self, started: float) -> bool:
        if self._stop.is_set():
            return True
        try:
            sig = self.broker.hgetall(STOP_KEY)
        except _BROKER_OUTAGE_EXCS:
            # the cross-process stop signal is unreadable during an
            # outage; the local stop() path above still works
            return False
        if sig:
            raw = sig.get(b"stop", sig.get("stop", b"0"))
            try:
                ts = float(raw)
            except (TypeError, ValueError):
                ts = float("inf")   # unparseable → explicit stop
            if ts >= started - 1.0:   # small clock-skew allowance
                log.info("stop signal received; shutting down")
                self.broker.delete(STOP_KEY)
                return True
        return False

    def install_signal_handlers(self, signals=None) -> bool:
        """SIGTERM → graceful drain: ``stop()`` is set, the run loop
        finishes + acks every in-flight batch, flushes metrics, and
        returns normally (exit 0 from the CLI) — no request stranded
        in the PEL.  Signal handlers are a main-thread-only facility;
        returns False when this is not the main thread (background
        serving keeps using ``stop()`` directly)."""
        import signal as _signal
        if signals is None:
            signals = (_signal.SIGTERM,)
        try:
            for s in signals:
                _signal.signal(s, lambda _sig, _frame: self.stop())
            return True
        except ValueError:
            return False

    def run(self, poll_ms: int = 100, decode_workers: int = 2,
            pipeline_depth: Optional[int] = None) -> None:
        """Pipelined loop: the decode POOL works batch N+1..N+depth
        while the device predicts batch N (the reference parallelizes
        decode per partition, ClusterServing.scala:156-237; here decode
        threads overlap the XLA execute, which releases the GIL).  All
        broker IO stays on this thread — the RESP socket is not
        thread-safe.

        Broker-outage contract: transport failures (and the circuit
        breaker's fast-fails once it opens) never kill the loop — the
        worker idles, keeps heartbeating and answering ``/healthz``
        (503 ``breaker_open``), and resumes when a half-open probe
        reconnects.  Un-acked records ride the PEL through the outage.
        """
        if pipeline_depth is None:
            pipeline_depth = self.config.pipeline_depth
        log.info("cluster serving started (batch=%d, decode_workers=%d, "
                 "depth=%d)", self.config.batch_size, decode_workers,
                 pipeline_depth)
        # wall clock for the cross-process stop-signal comparison
        # (clients stamp STOP_KEY with time.time()); monotonic clock
        # for every interval below
        started = time.time()
        self._serve_start = self._serve_start or time.perf_counter()
        # publish /healthz BEFORE the warm start: a cold compile can
        # run minutes (the 141s north star), far past any supervisor
        # startup grace — the port must be discoverable and answering
        # (503 warming_up = alive, deliberately not-ready) while the
        # predict program compiles, or every cold-cache replica would
        # be no-port killed mid-compile and respawned into the same
        # cold compile, forever
        if self.metrics_server is not None:
            self.metrics_server.start()   # no-op if already listening
        # the engine layers restart too (a closed worker can serve
        # again): batcher thread + HTTP fast-path listener
        self.engine.start()
        if self.http_transport is not None:
            self.http_transport.start()
        self._publish_port()
        # the queue gauge must be honest BEFORE the (possibly
        # minutes-long) warm start: /metrics is already answering, and
        # a supervisor reading a never-set 0 while a real backlog
        # waits behind the compile would scale the fleet DOWN at the
        # exact moment it needs capacity
        try:
            self._observe_queue()
        except _BROKER_OUTAGE_EXCS:
            pass          # broker down at boot: gauge stays unset
        # pre-pay the predict compile (or the ~seconds cache load)
        # BEFORE polling: the first client's request must not carry
        # the cold-start
        self._warming = True
        try:
            self.warm_start()
        finally:
            self._warming = False
        # replica liveness for the supervisor / launcher plane
        # (ZOO_TPU_METRICS_DIR names this worker's host-<k>/ slot)
        heartbeat = HostHeartbeat.from_env()
        # zoolint: disable=RACE016 — serve-loop confined: run() holds the sampler, close() runs on the same driver (run's finally / the context owner)
        self._telemetry = TelemetrySampler(
            float(get_config().get(
                "observability.telemetry_interval_s", 10.0))).start()
        # the input-pipeline worker pool (data/stages.py): serving's
        # decode stage is the same shape of work as a train pipeline's
        # map stage — CPU-bound host transforms overlapping the chip
        pool = WorkerPool(decode_workers, name="serving-decode")
        pending: deque = deque()   # (future, t_arrival, entries)
        reclaim_tick = max(0.25, min(
            10.0, self.config.reclaim_min_idle_ms / 2000.0))
        last_reclaim = time.perf_counter()
        # the queue gauge must keep tracking the backlog while IDLE
        # too: it naturally refreshes per consumed batch, but once
        # traffic stops it would freeze at the last busy value — and
        # the autoscaler's idle detection (queue == 0) would never
        # fire, pinning the fleet at its peak forever
        queue_obs_tick = 0.5
        last_queue_obs = 0.0
        outage = False
        try:
            while True:
                if heartbeat is not None:
                    heartbeat.beat(step=self.total_records)
                try:
                    if time.perf_counter() - last_reclaim \
                            > reclaim_tick:
                        self._reclaim_stale()
                        last_reclaim = time.perf_counter()
                    # keep the decode pipeline full (admission control
                    # BEFORE the decode submit: an expired record
                    # costs neither decode nor predict)
                    while len(pending) < pipeline_depth:
                        entries = self._read_entries(
                            self.config.batch_size,
                            0 if pending else poll_ms)
                        if not entries:
                            break
                        entries = self._shed_expired(entries)
                        if not entries:
                            # fully-shed batch: yield to the OUTER
                            # loop instead of reading again — purging
                            # a deep expired backlog must not starve
                            # the heartbeat, the stop/drain check, or
                            # reclaim (a supervisor would TERM a
                            # replica whose beat stalls mid-purge)
                            break
                        self._inflight.update(i for i, _ in entries)
                        pending.append((pool.submit(self._decode_batch,
                                                    entries),
                                        time.perf_counter(), entries))
                    if pending:
                        fut, t_arrival, entries = pending.popleft()
                        self._consume_batch(fut, t_arrival, entries)
                        if self.summary is not None and self.latencies:
                            s = self.stats()
                            self.summary.add_scalar(
                                "Serving Throughput",
                                s["throughput_rps"],
                                self.total_records)
                        self._observe_queue()
                        last_queue_obs = time.perf_counter()
                    elif time.perf_counter() - last_queue_obs \
                            > queue_obs_tick:
                        self._observe_queue()
                        last_queue_obs = time.perf_counter()
                    if outage:
                        outage = False
                        log.warning("broker recovered; serving resumed")
                except _BROKER_OUTAGE_EXCS as e:
                    # fast-fail idle: one bounded sleep per failed
                    # attempt (the breaker already swallowed the
                    # per-op connect cost), not a crash that would
                    # make the supervisor restart-loop the replica
                    # against a dead broker
                    if not outage:
                        outage = True
                        log.warning(
                            "broker unavailable (%s: %s); idling until "
                            "the breaker's half-open probe reconnects",
                            type(e).__name__, e)
                    time.sleep(min(
                        0.25, self.config.breaker_cooldown_s / 2.0))
                if self._should_stop(started):
                    self._drain(pending)
                    break
        finally:
            pool.shutdown(wait=False)
            self._flush_observability()
            self.close()

    def _drain(self, pending: deque) -> None:
        """Graceful drain: every batch already read past (_last_id
        advanced / PEL-delivered) MUST still be predicted, written,
        and acked, or its clients wait forever.  Under a broker
        outage the remaining batches are left UN-acked — the PEL keeps
        them for the surviving replicas to reclaim, which beats
        blocking shutdown on a dead broker."""
        while pending:
            fut, t_arrival, entries = pending.popleft()
            try:
                self._consume_batch(fut, t_arrival, entries)
            except _BROKER_OUTAGE_EXCS:
                log.warning(
                    "drain: broker unavailable; leaving %d batch(es) "
                    "in the PEL for peer reclaim", len(pending) + 1)
                break

    def _publish_port(self) -> None:
        """Replica→supervisor port discovery: atomically write the
        bound /metrics (+/healthz) port to the file named by
        ``ZOO_TPU_SERVING_PORT_FILE`` (the supervisor injects it and
        polls readiness on the discovered port — metrics_port=0 keeps
        replicas collision-free on one host)."""
        path = os.environ.get("ZOO_TPU_SERVING_PORT_FILE")
        if path and self.metrics_server is not None \
                and self.metrics_server.port:
            try:
                atomic_write_text(path, str(self.metrics_server.port))
            except OSError:
                log.exception("could not publish serving port to %s",
                              path)
        # the HTTP fast path publishes its own (ephemeral) port the
        # same way, for supervisors / load balancers fronting it
        http_path = os.environ.get("ZOO_TPU_SERVING_HTTP_PORT_FILE")
        if http_path and self.http_transport is not None \
                and self.http_transport.port:
            try:
                atomic_write_text(http_path,
                                  str(self.http_transport.port))
            except OSError:
                log.exception("could not publish serving http port "
                              "to %s", http_path)

    def _flush_observability(self) -> None:
        """Drain-time metrics flush: inside a launcher-managed run dir
        the worker's registry snapshot is persisted so fleet
        aggregation sees the final counts (no-op anywhere else —
        ``flush_worker_observability`` guards on its own init)."""
        if not os.environ.get("ZOO_TPU_METRICS_DIR"):
            return
        try:
            from analytics_zoo_tpu.observability.aggregator import (
                flush_worker_observability)
            flush_worker_observability()
        except Exception:   # noqa: BLE001 — flush is best-effort
            log.exception("observability flush failed")

    def _consume_batch(self, fut, t_arrival, entries) -> None:
        """Serve one pipelined batch whose decode ran in the pool:
        resolve the decode future (a future that raised becomes an
        all-failed decode) and hand off to the shared poison-safe serve
        path, then clear the batch's in-flight ids."""
        try:
            try:
                decoded = fut.result()
            except Exception as e:
                log.exception("decode future failed (%d records)",
                              len(entries))
                decoded = ([], [],
                           [(self._uri_of(f), self._rid_of(f), e)
                            for _, f in entries])
            self._serve_decoded(decoded, t_arrival, entries)
        finally:
            self._inflight.difference_update(i for i, _ in entries)

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        """(ref ClusterServingManager.listenTermination :335)"""
        self._stop.set()

    def close(self) -> None:
        """Release held resources: summary file handles, the telemetry
        sampler, the /metrics listener, the HTTP fast path, and the
        engine's batcher thread.  Idempotent; called by ``run()`` on
        every exit path.  A closed engine can serve again (summaries
        reopen on write; ``run()`` restarts the listeners and the
        batcher)."""
        if self.summary is not None:
            self.summary.close()
        if self._telemetry is not None:
            self._telemetry.stop()
            # zoolint: disable=ATOM017 — idempotent teardown: a second closer re-stops an already-stopped sampler, which is a no-op
            self._telemetry = None
        if self.metrics_server is not None:
            self.metrics_server.stop()
        if self.http_transport is not None:
            self.http_transport.stop()
        self.engine.stop()

    def __enter__(self) -> "ClusterServing":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()
        self.close()
