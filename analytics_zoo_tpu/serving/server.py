"""Cluster Serving engine.

Reference: zoo/serving/ClusterServing.scala:33-342 — a streaming loop:
Redis stream ``image_stream`` → base64 JPEG decode → batched
InferenceModel predict → top-N postprocess → write to the ``result``
table with backpressure retry; Redis OOM guard via XTRIM (:128-134);
throughput scalars to the inference summary (:294-317).  Config comes
from config.yaml (ClusterServingHelper).

TPU version: the worker is a host process driving the one compiled XLA
predict program; batching pads to a fixed shape so one executable
serves all traffic.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.common.config import get_config
from analytics_zoo_tpu.data.stages import WorkerPool, pad_to_batch
from analytics_zoo_tpu.observability import (
    MetricsServer, TelemetrySampler, get_registry, get_tracer)
from analytics_zoo_tpu.serving.redis_client import connect
from analytics_zoo_tpu.utils.summary import InferenceSummary

log = logging.getLogger("analytics_zoo_tpu.serving")

INPUT_STREAM = "serving_stream"
RESULT_PREFIX = "result:"
STOP_KEY = "zoo-serving-stop"   # cross-process stop signal
                                # (ClusterServingManager.listenTermination)
# results whose write was abandoned after the bounded backoff: the
# request_id/uri land here so an operator (or a replaying client) can
# find them — losing a result beats losing the worker loop
DEAD_LETTER_STREAM = "serving_dead_letter"


def decode_field(fields: Dict[str, bytes]):
    """Decode one stream record: 'data' (b64 ndarray .npy bytes) or
    'image' (b64 JPEG) + 'uri' [+ optional 'request_id' for
    cross-process correlation].  Returns ``(uri, array, request_id)``
    (request_id None for records enqueued without one)."""
    uri = fields["uri"].decode() if isinstance(fields["uri"], bytes) \
        else fields["uri"]
    rid = fields.get("request_id")
    if isinstance(rid, bytes):
        rid = rid.decode()
    if "image" in fields:
        from analytics_zoo_tpu.feature.image import decode_image_bytes
        raw = base64.b64decode(fields["image"])
        # serving consumes BGR, matching the reference's OpenCV path
        # (ImageProcessing.scala:24)
        img = decode_image_bytes(raw, to_rgb=False, context=uri)
        return uri, img.astype(np.float32), rid
    raw = base64.b64decode(fields["data"])
    import io
    arr = np.load(io.BytesIO(raw), allow_pickle=False)
    return uri, arr, rid


class ServingConfig:
    """config.yaml contract (scripts/cluster-serving/config.yaml)."""

    def __init__(self, redis_url: Optional[str] = None,
                 batch_size: int = 4, top_n: int = 1,
                 max_stream_len: int = 100000,
                 log_dir: Optional[str] = None,
                 consumer_group: Optional[str] = None,
                 consumer_name: str = "worker-0",
                 pipeline_depth: int = 2,
                 metrics_port: Optional[int] = None,
                 metrics_host: Optional[str] = None,
                 healthz_max_queue: Optional[int] = None,
                 healthz_max_error_rate: Optional[float] = None,
                 result_write_retries: Optional[int] = None,
                 input_shape=None,
                 extra: Optional[Dict[str, str]] = None):
        self.redis_url = redis_url
        self.batch_size = int(batch_size)
        self.top_n = int(top_n)
        self.max_stream_len = int(max_stream_len)
        self.log_dir = log_dir
        # Prometheus scrape endpoint: None = off, 0 = ephemeral port
        # (tests / multi-worker hosts), N = fixed port.  The endpoint
        # is UNAUTHENTICATED — on shared networks bind metrics_host to
        # 127.0.0.1 (or a scrape-only interface) instead of all
        # interfaces.  None defers to observability.bind_host.
        self.metrics_port = (None if metrics_port is None
                             else int(metrics_port))
        if metrics_host is None:
            from analytics_zoo_tpu.observability.exporter import (
                default_bind_host)
            metrics_host = default_bind_host()
        self.metrics_host = metrics_host
        # how many batches may be read-ahead into the decode pipeline.
        # Each read-ahead batch waits ~1 predict before its own turn, so
        # depth trades tail latency for decode/predict overlap: 2 keeps
        # the overlap (decode N+1 under predict N) at roughly half the
        # queue-wait p50 of deeper pipelines.  Clamped to >= 1: depth 0
        # would make the run loop read nothing, forever.
        self.pipeline_depth = max(1, int(pipeline_depth))
        # /healthz readiness thresholds (0 = that check disabled):
        # the probe flips to 503 when the input-stream backlog exceeds
        # healthz_max_queue, or when the error fraction over the most
        # recent records exceeds healthz_max_error_rate — so an
        # orchestrator stops routing to a drowning/poisoned worker
        # instead of killing a merely-busy one
        if healthz_max_queue is None:
            healthz_max_queue = get_config().get(
                "serving.healthz_max_queue", 0)
        if healthz_max_error_rate is None:
            healthz_max_error_rate = get_config().get(
                "serving.healthz_max_error_rate", 0.0)
        self.healthz_max_queue = int(healthz_max_queue or 0)
        self.healthz_max_error_rate = float(healthz_max_error_rate or 0.0)
        # bounded result-write backpressure: attempts before a result
        # is abandoned to the dead-letter stream (never < 1)
        if result_write_retries is None:
            result_write_retries = get_config().get(
                "serving.result_write_retries", 8)
        self.result_write_retries = max(int(result_write_retries), 1)
        # consumer_group set → multiple workers SHARE the stream, each
        # record served exactly once (the reference parallelizes per
        # Spark partition; redis-native scale-out uses XREADGROUP)
        self.consumer_group = consumer_group
        self.consumer_name = consumer_name
        # per-record input shape (no batch dim), e.g. (224, 224, 3):
        # when set, the worker AOT warm-starts the padded-batch predict
        # program at startup — from the persistent executable cache
        # when one is configured — instead of compiling inside the
        # first client's request (config.yaml ``params.input_shape:
        # 224,224,3``)
        if isinstance(input_shape, str):
            input_shape = tuple(
                int(d) for d in input_shape.replace("x", ",").split(",")
                if d.strip())
        self.input_shape = tuple(input_shape) if input_shape else None
        self.extra = extra or {}   # raw section.key entries (model.* etc)

    @classmethod
    def from_yaml(cls, path: str) -> "ServingConfig":
        cfg: Dict[str, Any] = {}
        section = None
        with open(path) as f:
            for line in f:
                raw = line.rstrip()
                if not raw or raw.lstrip().startswith("#"):
                    continue
                if not raw.startswith(" "):
                    section = raw.rstrip(":").strip()
                    continue
                k, _, v = raw.strip().partition(":")
                cfg[f"{section}.{k.strip()}"] = v.strip()
        return cls(
            redis_url=cfg.get("data.src"),
            batch_size=int(cfg.get("params.batch_size", 4) or 4),
            top_n=int(cfg.get("params.top_n", 1) or 1),
            log_dir=cfg.get("params.log_dir") or None,
            consumer_group=cfg.get("params.consumer_group") or None,
            consumer_name=cfg.get("params.consumer_name", "worker-0")
            or "worker-0",
            pipeline_depth=int(cfg.get("params.pipeline_depth", 2) or 2),
            metrics_port=(int(cfg["params.metrics_port"])
                          if cfg.get("params.metrics_port") not in
                          (None, "") else None),
            metrics_host=cfg.get("params.metrics_host") or None,
            healthz_max_queue=int(
                cfg.get("params.healthz_max_queue") or 0) or None,
            healthz_max_error_rate=float(
                cfg.get("params.healthz_max_error_rate") or 0.0) or None,
            result_write_retries=int(
                cfg.get("params.result_write_retries") or 0) or None,
            input_shape=cfg.get("params.input_shape") or None,
            extra=cfg,
        )


class ClusterServing:
    """The serving worker loop."""

    def __init__(self, inference_model, config: ServingConfig = None,
                 broker=None):
        self.model = inference_model
        self.config = config or ServingConfig()
        self.broker = broker if broker is not None else connect(
            self.config.redis_url)
        self.summary = (InferenceSummary(self.config.log_dir, "serving")
                        if self.config.log_dir else None)
        self._stop = threading.Event()
        self._last_id = "0-0"
        self.total_records = 0
        if self.config.consumer_group:
            self.broker.xgroup_create(INPUT_STREAM,
                                      self.config.consumer_group)
        # per-record arrival→result latencies (seconds), bounded
        self.latencies: deque = deque(maxlen=10000)
        self._serve_start: Optional[float] = None
        # entry ids read by THIS worker and not yet acked (in the
        # decode/predict pipeline) — the reclaim pass must not treat
        # them as another worker's stale pending
        self._inflight: set = set()
        # ---- observability: shared-registry instruments + /metrics --
        reg = get_registry()
        self._m_latency = reg.histogram(
            "serving_request_latency_seconds",
            "stream-arrival to result-write latency per record")
        self._m_fill = reg.gauge(
            "serving_batch_fill_ratio",
            "real records / batch capacity of the last served batch")
        self._m_records = reg.counter(
            "serving_records_total", "records served")
        self._m_errors = reg.counter(
            "serving_errors_total",
            "records acked with an error result (decode/poison)")
        self._m_queue = reg.gauge(
            "serving_queue_depth", "input stream length at last poll")
        self._m_redis_retry = reg.counter(
            "serving_redis_retry_total",
            "result-write attempts retried after a broker error")
        self._m_write_abandoned = reg.counter(
            "serving_result_write_abandoned_total",
            "results abandoned (dead-lettered) after the bounded "
            "write-backoff was exhausted")
        self._m_reclaimed = reg.counter(
            "serving_reclaimed_total",
            "stale pending records reclaimed from dead workers")
        self._tracer = get_tracer()
        self._telemetry: Optional[TelemetrySampler] = None
        # readiness window: 1 per recently served record, 0 per record
        # acked with an error result — the error-rate half of /healthz.
        # The lock pairs the worker thread's extend with the /healthz
        # thread's snapshot: list(deque) raises if the deque mutates
        # mid-iteration, which would flip a healthy worker to 503.
        self._recent_outcomes: deque = deque(maxlen=200)
        self._outcomes_lock = threading.Lock()
        self.metrics_server: Optional[MetricsServer] = None
        if self.config.metrics_port is not None:
            self.metrics_server = MetricsServer(
                port=self.config.metrics_port,
                host=self.config.metrics_host,
                health_check=self.readiness).start()

    # ----------------------------------------------------------- warm-start
    def warm_start(self) -> bool:
        """AOT warm-start of the padded-batch predict program (serving
        pads every batch to ``batch_size``, so ONE executable serves
        all traffic — warm exactly that one).  With a persistent
        executable cache configured (``ZOO_TPU_COMPILE_CACHE`` /
        ``compile.cache_dir``), a replica respawn deserializes in
        seconds instead of recompiling — the serving half of the
        141s-cold-start fix.  No-op without ``params.input_shape``."""
        if self.config.input_shape is None:
            return False
        warm = getattr(self.model, "warm", None)
        if warm is None:
            return False
        t0 = time.perf_counter()
        ok = bool(warm(self.config.input_shape, self.config.batch_size))
        log.info("predict warm start %s in %.2fs (batch=%d, shape=%s)",
                 "ready" if ok else "unavailable",
                 time.perf_counter() - t0, self.config.batch_size,
                 self.config.input_shape)
        return ok

    # ------------------------------------------------------------ main loop
    def run_once(self, block_ms: int = 100) -> int:
        """One poll/predict/write cycle; returns #records served."""
        self._serve_start = self._serve_start or time.perf_counter()
        entries = self._read_entries(self.config.batch_size, block_ms)
        if not entries:
            return 0
        t0 = time.perf_counter()
        real = self._serve_entries(entries, t0)
        if self.summary is not None and real:
            self.summary.add_scalar(
                "Serving Throughput",
                real / max(time.perf_counter() - t0, 1e-9),
                self.total_records)
        # OOM guard (ClusterServing.scala:128-134)
        qlen = self.broker.xlen(INPUT_STREAM)
        self._m_queue.set(qlen)
        if qlen > self.config.max_stream_len:
            self.broker.xtrim(INPUT_STREAM, self.config.max_stream_len)
        return real

    def _write_result(self, uri: str, value: str,
                      retries: Optional[int] = None,
                      request_id: Optional[str] = None) -> bool:
        """Write one result with BOUNDED backpressure (ref :254-289
        retried "infinite-ish" and then raised, killing the worker
        loop with the rest of the batch un-acked): exponential backoff
        with jitter between attempts (jitter de-synchronizes the
        worker fleet hammering a recovering broker), then the record
        is ABANDONED — counted, logged, and dead-lettered with its
        request_id — so one unwritable result can never crash the
        loop.  The request_id from the matching enqueue is echoed
        beside the result so a client can correlate response <->
        request across processes.  Returns True when the write
        landed."""
        fields = {"value": value}
        if request_id:
            fields["request_id"] = request_id
        if retries is None:
            retries = self.config.result_write_retries
        attempts = max(int(retries), 1)
        delay = 0.05
        last_exc: Optional[Exception] = None
        for attempt in range(attempts):
            try:
                self.broker.hset(RESULT_PREFIX + uri, fields)
                return True
            except Exception as e:   # noqa: BLE001 — broker flake class
                last_exc = e
                self._m_redis_retry.inc()
                if attempt + 1 >= attempts:
                    break
                import random
                time.sleep(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, 2.0)
        self._m_write_abandoned.inc()
        log.error("abandoning result write for %s after %d attempts "
                  "(%s: %s); dead-lettering", uri, attempts,
                  type(last_exc).__name__, last_exc)
        try:
            self.broker.xadd(DEAD_LETTER_STREAM, {
                "uri": uri,
                "request_id": request_id or "",
                "error": f"{type(last_exc).__name__}: {last_exc}",
                "abandoned_unix": f"{time.time():.3f}",
            })
        except Exception:   # noqa: BLE001 — the broker may be fully down
            log.exception("dead-letter write failed for %s (broker "
                          "down?); the request_id above is the only "
                          "record", uri)
        return False

    # -------------------------------------------------- pipelined serving
    def _read_entries(self, count: int, block_ms: int):
        """Read the next batch: plain XREAD (single worker owns the
        stream) or XREADGROUP (workers share it, exactly-once
        delivery)."""
        cfg = self.config
        if cfg.consumer_group:
            return self.broker.xreadgroup(
                cfg.consumer_group, cfg.consumer_name, INPUT_STREAM,
                count=count, block_ms=block_ms)
        entries = self.broker.xread(INPUT_STREAM, self._last_id,
                                    count=count, block_ms=block_ms)
        for entry_id, _f in entries:
            self._last_id = entry_id
        return entries

    def _ack(self, entries) -> None:
        if self.config.consumer_group and entries:
            self.broker.xack(INPUT_STREAM, self.config.consumer_group,
                             *[i for i, _ in entries])

    def _reclaim_stale(self, min_idle_ms: int = 30000):
        """Crash recovery: claim entries another worker read but never
        acknowledged (died between XREADGROUP and XACK) and serve them
        — without this, records in a dead worker's pending list would
        wait forever."""
        cfg = self.config
        if not cfg.consumer_group:
            return 0
        try:
            entries = self.broker.xautoclaim(
                INPUT_STREAM, cfg.consumer_group, cfg.consumer_name,
                min_idle_ms, count=cfg.batch_size)
        except Exception:
            log.exception("xautoclaim failed")
            return 0
        # XAUTOCLAIM does not exclude the caller: under a deep backlog
        # (pipeline_depth batches waiting > min_idle_ms) it hands back
        # THIS worker's own un-acked in-flight entries — serving those
        # here would double-predict and double-write them.
        entries = [e for e in entries if e[0] not in self._inflight]
        if not entries:
            return 0
        # a reclaimed batch can be the very poison that killed its
        # original worker — _serve_entries guarantees it cannot kill
        # THIS one too (no crash-loop across reclaiming workers)
        real = self._serve_entries(entries, time.perf_counter())
        self._m_reclaimed.inc(len(entries))
        log.info("reclaimed %d stale pending records (%d poison)",
                 real, len(entries) - real)
        return real

    def _decode_batch(self, entries):
        """Decode one batch of raw stream entries (runs in the decode
        pool — pure CPU, no broker IO, so no connection sharing across
        threads).  Undecodable records are collected into ``failed``
        (uri, request_id, exception) rather than silently dropped —
        the serve path writes them an error result, because acking
        consumes the record and a consumed record with no result
        strands its client."""
        uris, arrays, rids, failed = [], [], [], []
        for entry_id, fields in entries:
            try:
                uri, arr, rid = decode_field(fields)
            except Exception as e:
                log.exception("undecodable record %s", entry_id)
                failed.append((self._uri_of(fields),
                               self._rid_of(fields), e))
                continue
            uris.append(uri)
            arrays.append(arr)
            rids.append(rid)
        return uris, arrays, failed, rids

    @staticmethod
    def _uri_of(fields) -> str:
        uri = fields.get("uri", b"") if hasattr(fields, "get") else b""
        return uri.decode() if isinstance(uri, bytes) else uri

    @staticmethod
    def _rid_of(fields):
        rid = fields.get("request_id") if hasattr(fields, "get") \
            else None
        return rid.decode() if isinstance(rid, bytes) else rid

    def _serve_entries(self, entries, t_arrival: float) -> int:
        """Decode + serve one raw batch with the poison-batch contract
        applied (shared by run_once, the pipelined loop via
        _consume_batch, and _reclaim_stale).  Returns #served."""
        try:
            decoded = self._decode_batch(entries)
        except Exception as e:
            log.exception("decode failed for batch (%d records)",
                          len(entries))
            decoded = ([], [], [(self._uri_of(f), self._rid_of(f), e)
                                for _, f in entries])
        return self._serve_decoded(decoded, t_arrival, entries)

    def _serve_decoded(self, decoded, t_arrival: float, entries) -> int:
        """Predict + write a decoded batch, then ack it.  The poison
        contract: NO failure in predict/write may escape (it would kill
        the worker loop with the batch un-acked), and every record that
        is acked without a prediction gets an explicit ERROR result so
        its client never blocks forever on a consumed record.
        ``decoded`` is (uris, arrays[, failed[, request_ids]])."""
        uris, arrays, *rest = decoded
        failed = list(rest[0]) if rest else []
        rids = list(rest[1]) if len(rest) > 1 else [None] * len(uris)
        real = 0
        try:
            real = self._predict_write(uris, arrays, t_arrival, rids)
        except Exception as e:
            log.exception("poison batch skipped (%d records)",
                          len(entries))
            failed += [(u, r, e) for u, r in zip(uris, rids)]
        for uri, rid, exc in failed:
            try:
                if uri:
                    self._write_result(uri, json.dumps(
                        {"error": f"{type(exc).__name__}: {exc}"}),
                        request_id=rid)
            except Exception:
                log.exception("could not write error result for %s", uri)
        self._m_errors.inc(len(failed))
        # readiness window: successes then failures, per record
        with self._outcomes_lock:
            self._recent_outcomes.extend([1] * real + [0] * len(failed))
        self._ack(entries)
        return real

    def _predict_write(self, uris, arrays, t_arrival: float,
                       rids=None) -> int:
        """Pad/predict/top-N/write one decoded batch; returns #served."""
        if not arrays:
            return 0
        if rids is None:
            rids = [None] * len(uris)
        bs = self.config.batch_size
        x = np.stack(arrays)
        real = len(arrays)
        self._m_fill.set(real / bs)
        # same fixed-shape padding primitive the train pipeline's
        # pad-remainder mode uses (data/stages.py)
        x = pad_to_batch(x, bs)
        # the span carries the batch's request ids, so a trace viewer
        # (or the merged cluster timeline) can follow one request from
        # client enqueue through this predict to its result write
        with self._tracer.span(
                "serving_predict", records=real,
                request_ids=[r for r in rids if r][:16]):
            out = np.asarray(self.model.predict(x))[:real]
        exp = np.exp(out - out.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        top = np.argsort(-probs, axis=-1)[:, :self.config.top_n]
        done = time.perf_counter()
        written = 0
        for uri, t, p, rid in zip(uris, top, probs, rids):
            value = json.dumps([[int(i), float(p[i])] for i in t])
            if self._write_result(uri, value, request_id=rid):
                written += 1
                self.latencies.append(done - t_arrival)
                self._m_latency.observe(done - t_arrival)
        abandoned = real - written
        if abandoned:
            # a dead-lettered result is a FAILURE to error accounting
            # and the /healthz error-rate window — the old raise made
            # that implicit; the bounded path must keep the readiness
            # probe honest during a result-write outage (an orchestrator
            # should pull a worker whose results never land)
            self._m_errors.inc(abandoned)
            with self._outcomes_lock:
                self._recent_outcomes.extend([0] * abandoned)
        # total_records counts records PROCESSED (drain/progress
        # bookkeeping); the return value counts records actually
        # DELIVERED — the outcome window gets its 1s from the caller
        self.total_records += real
        self._m_records.inc(real)
        if self.summary is not None:
            self.summary.add_scalar("Total Records Number",
                                    self.total_records,
                                    self.total_records)
        return written

    def readiness(self) -> Optional[Dict[str, Any]]:
        """The /healthz readiness probe (wired into the
        MetricsServer): None when ready, else a JSON-able reason dict
        — the endpoint answers 503 with it.  Thresholds come from
        config.yaml ``params.healthz_max_queue`` /
        ``params.healthz_max_error_rate`` (0 = check disabled)."""
        cfg = self.config
        if cfg.healthz_max_queue > 0:
            depth = self._m_queue.value
            if depth > cfg.healthz_max_queue:
                return {"reason": "queue_depth",
                        "queue_depth": int(depth),
                        "threshold": cfg.healthz_max_queue}
        if cfg.healthz_max_error_rate > 0 and self._recent_outcomes:
            with self._outcomes_lock:
                outcomes = list(self._recent_outcomes)
            rate = 1.0 - sum(outcomes) / len(outcomes)
            if rate > cfg.healthz_max_error_rate:
                return {"reason": "error_rate",
                        "error_rate": round(rate, 4),
                        "window": len(outcomes),
                        "threshold": cfg.healthz_max_error_rate}
        return None

    def stats(self) -> Dict[str, float]:
        """Throughput + latency percentiles over the records served so
        far (the reference's TensorBoard serving scalars, :294-317,
        plus percentiles)."""
        lat = sorted(self.latencies)
        pct = lambda p: (lat[min(int(p / 100 * len(lat)),
                                 len(lat) - 1)] * 1e3) if lat else 0.0
        wall = (time.perf_counter() - self._serve_start) \
            if self._serve_start else 0.0
        return {
            "total_records": self.total_records,
            "throughput_rps": self.total_records / wall if wall else 0.0,
            "latency_p50_ms": pct(50),
            "latency_p95_ms": pct(95),
            "latency_p99_ms": pct(99),
        }

    def _should_stop(self, started: float) -> bool:
        if self._stop.is_set():
            return True
        sig = self.broker.hgetall(STOP_KEY)
        if sig:
            raw = sig.get(b"stop", sig.get("stop", b"0"))
            try:
                ts = float(raw)
            except (TypeError, ValueError):
                ts = float("inf")   # unparseable → explicit stop
            if ts >= started - 1.0:   # small clock-skew allowance
                log.info("stop signal received; shutting down")
                self.broker.delete(STOP_KEY)
                return True
        return False

    def run(self, poll_ms: int = 100, decode_workers: int = 2,
            pipeline_depth: Optional[int] = None) -> None:
        """Pipelined loop: the decode POOL works batch N+1..N+depth
        while the device predicts batch N (the reference parallelizes
        decode per partition, ClusterServing.scala:156-237; here decode
        threads overlap the XLA execute, which releases the GIL).  All
        broker IO stays on this thread — the RESP socket is not
        thread-safe."""
        if pipeline_depth is None:
            pipeline_depth = self.config.pipeline_depth
        log.info("cluster serving started (batch=%d, decode_workers=%d, "
                 "depth=%d)", self.config.batch_size, decode_workers,
                 pipeline_depth)
        # wall clock for the cross-process stop-signal comparison
        # (clients stamp STOP_KEY with time.time()); monotonic clock
        # for every interval below
        started = time.time()
        self._serve_start = self._serve_start or time.perf_counter()
        # pre-pay the predict compile (or the ~seconds cache load)
        # BEFORE polling: the first client's request must not carry
        # the cold-start
        self.warm_start()
        if self.metrics_server is not None:
            self.metrics_server.start()   # no-op if already listening
        self._telemetry = TelemetrySampler(
            float(get_config().get(
                "observability.telemetry_interval_s", 10.0))).start()
        # the input-pipeline worker pool (data/stages.py): serving's
        # decode stage is the same shape of work as a train pipeline's
        # map stage — CPU-bound host transforms overlapping the chip
        pool = WorkerPool(decode_workers, name="serving-decode")
        pending: deque = deque()   # (future, t_arrival, entries)
        last_reclaim = time.perf_counter()
        try:
            while True:
                if time.perf_counter() - last_reclaim > 10.0:
                    self._reclaim_stale()
                    last_reclaim = time.perf_counter()
                # keep the decode pipeline full
                while len(pending) < pipeline_depth:
                    entries = self._read_entries(
                        self.config.batch_size,
                        0 if pending else poll_ms)
                    if not entries:
                        break
                    self._inflight.update(i for i, _ in entries)
                    pending.append((pool.submit(self._decode_batch,
                                                entries),
                                    time.perf_counter(), entries))
                if pending:
                    fut, t_arrival, entries = pending.popleft()
                    self._consume_batch(fut, t_arrival, entries)
                    if self.summary is not None and self.latencies:
                        s = self.stats()
                        self.summary.add_scalar(
                            "Serving Throughput", s["throughput_rps"],
                            self.total_records)
                    qlen = self.broker.xlen(INPUT_STREAM)
                    self._m_queue.set(qlen)
                    if qlen > self.config.max_stream_len:
                        self.broker.xtrim(INPUT_STREAM,
                                          self.config.max_stream_len)
                if self._should_stop(started):
                    # drain: every batch already read past (_last_id
                    # advanced) MUST still be predicted + written, or
                    # its clients wait forever
                    while pending:
                        fut, t_arrival, entries = pending.popleft()
                        self._consume_batch(fut, t_arrival, entries)
                    break
        finally:
            pool.shutdown(wait=False)
            self.close()

    def _consume_batch(self, fut, t_arrival, entries) -> None:
        """Serve one pipelined batch whose decode ran in the pool:
        resolve the decode future (a future that raised becomes an
        all-failed decode) and hand off to the shared poison-safe serve
        path, then clear the batch's in-flight ids."""
        try:
            try:
                decoded = fut.result()
            except Exception as e:
                log.exception("decode future failed (%d records)",
                              len(entries))
                decoded = ([], [],
                           [(self._uri_of(f), self._rid_of(f), e)
                            for _, f in entries])
            self._serve_decoded(decoded, t_arrival, entries)
        finally:
            self._inflight.difference_update(i for i, _ in entries)

    def start_background(self) -> threading.Thread:
        t = threading.Thread(target=self.run, daemon=True)
        t.start()
        return t

    def stop(self) -> None:
        """(ref ClusterServingManager.listenTermination :335)"""
        self._stop.set()

    def close(self) -> None:
        """Release held resources: summary file handles, the telemetry
        sampler, and the /metrics listener.  Idempotent; called by
        ``run()`` on every exit path.  A closed engine can serve again
        (summaries reopen on write; ``run()`` restarts the listener)."""
        if self.summary is not None:
            self.summary.close()
        if self._telemetry is not None:
            self._telemetry.stop()
            self._telemetry = None
        if self.metrics_server is not None:
            self.metrics_server.stop()

    def __enter__(self) -> "ClusterServing":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()
        self.close()
