"""Serving client API.

Reference: pyzoo/zoo/serving/client.py — ``InputQueue.enqueue_image``
(:58, base64 → XADD) and ``OutputQueue.query``/``dequeue`` (:127).
"""

from __future__ import annotations

import base64
import io
import json
import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from analytics_zoo_tpu.observability.reqtrace import (
    TRACE_FIELD, TRACE_HEADER, TraceContext, get_request_log)
from analytics_zoo_tpu.serving.redis_client import connect
from analytics_zoo_tpu.serving.server import INPUT_STREAM, RESULT_PREFIX


def _stamp_trace(rid: str, trace=None,
                 transport: str = "redis") -> Optional[TraceContext]:
    """The client half of request tracing: resolve the context this
    send carries (an explicit :class:`TraceContext`, a wire string, or
    a freshly stamped one when tracing is on) and record its
    ``enqueue`` station.  None when tracing is off and no explicit
    trace was given — the request is served untraced."""
    if isinstance(trace, TraceContext):
        ctx = trace
    elif isinstance(trace, str) and trace:
        ctx = TraceContext.from_wire(trace, request_id=rid)
    else:
        reqlog = get_request_log()
        ctx = TraceContext.new(rid) if reqlog.enabled else None
    if ctx is not None:
        get_request_log().begin(ctx, transport=transport,
                                station="enqueue")
    return ctx


class InputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.broker = broker if broker is not None else connect(redis_url)

    @staticmethod
    def _request_id(request_id: Optional[str]) -> str:
        # the client half of cross-process tracing: the id rides the
        # stream record, threads through the server's decode/batch/
        # predict spans, and is echoed next to the result
        return request_id if request_id else uuid.uuid4().hex

    def enqueue_image(self, uri: str, image,
                      request_id: Optional[str] = None,
                      endpoint: Optional[str] = None,
                      trace=None) -> str:
        """image: ndarray (HWC uint8) or path or raw JPEG bytes.
        Returns the record's ``request_id`` (generated when not
        given) — correlate it against the server's spans and the
        ``request_id`` field echoed beside the result.  ``endpoint``
        routes the record to a registered model on a multi-model
        worker (absent = the worker's default model).  ``trace`` (a
        :class:`TraceContext` or wire string) propagates an existing
        trace; absent, one is stamped automatically while tracing is
        on."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                raw = f.read()
        elif isinstance(image, (bytes, bytearray)):
            raw = bytes(image)
        else:
            import cv2
            ok, enc = cv2.imencode(".jpg", np.asarray(image))
            if not ok:
                raise ValueError("cannot encode image")
            raw = enc.tobytes()
        rid = self._request_id(request_id)
        fields = {"uri": uri, "image": base64.b64encode(raw),
                  "request_id": rid}
        if endpoint:
            fields["endpoint"] = endpoint
        ctx = _stamp_trace(rid, trace)
        if ctx is not None:
            fields[TRACE_FIELD] = ctx.to_wire()
        self.broker.xadd(INPUT_STREAM, fields)
        return rid

    def enqueue(self, uri: str, data: np.ndarray,
                request_id: Optional[str] = None,
                endpoint: Optional[str] = None,
                max_tokens: Optional[int] = None,
                trace=None) -> str:
        """Arbitrary ndarray input (npy-serialized); returns the
        record's ``request_id``.  ``endpoint`` routes to a registered
        model on a multi-model worker; ``max_tokens`` caps the
        sequence a *generative* endpoint decodes for this record
        (ignored by stateless endpoints); ``trace`` propagates an
        existing :class:`TraceContext` (absent, one is stamped while
        tracing is on — its wire string rides the record's ``trace``
        field)."""
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(data), allow_pickle=False)
        rid = self._request_id(request_id)
        fields = {"uri": uri, "data": base64.b64encode(buf.getvalue()),
                  "request_id": rid}
        if endpoint:
            fields["endpoint"] = endpoint
        if max_tokens:
            fields["max_tokens"] = str(int(max_tokens))
        ctx = _stamp_trace(rid, trace)
        if ctx is not None:
            fields[TRACE_FIELD] = ctx.to_wire()
        self.broker.xadd(INPUT_STREAM, fields)
        return rid


class OutputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.redis_url = redis_url
        self.broker = broker if broker is not None else connect(redis_url)

    def _reconnect(self) -> None:
        """Replace a dead socket (url-constructed queues only; an
        injected broker has nothing to reconnect).  A failed reconnect
        is left for the next poll to count — the retry budget, not
        this helper, decides when to give up."""
        if self.redis_url is None:
            return
        try:
            self.broker.close()
        except Exception:   # noqa: BLE001 — already broken
            pass
        try:
            self.broker = connect(self.redis_url)
        except (OSError, RuntimeError):
            pass

    def query(self, uri: str, timeout_s: float = 0.0,
              retries: int = 8):
        """Result for one uri (list of [class, prob]), or None."""
        meta = self.query_meta(uri, timeout_s, retries=retries)
        return meta["value"] if meta else None

    def query_meta(self, uri: str, timeout_s: float = 0.0,
                   retries: int = 8) -> Optional[Dict[str, Any]]:
        """Result plus correlation metadata: ``{"value": ...,
        "request_id": str | None}`` — the id the server echoed from
        the matching enqueue.

        Polling backs off exponentially (20 ms → 250 ms cap) instead
        of hammering a fixed 20 ms, and a transient broker error no
        longer raises straight through: up to ``retries`` consecutive
        connection failures are absorbed with the same bounded
        exponential backoff + jitter the server's result-write path
        uses (reconnecting between attempts), after which the last
        error is re-raised.  A positive ``timeout_s`` is the per-call
        deadline and wins over the retry ladder: when it expires
        mid-retry the call returns ``None`` cleanly, exactly like an
        absent result.  ``timeout_s=0`` (the default) polls for the
        result without blocking but has NO deadline, so broker-blip
        retries may still block up to a few seconds — callers that
        need fail-fast on a dead broker pass ``retries=1``."""
        import random
        deadline = time.monotonic() + timeout_s
        poll_delay, retry_delay, failures = 0.02, 0.05, 0
        while True:
            try:
                fields = self.broker.hgetall(RESULT_PREFIX + uri)
            except OSError:
                # connection-class trouble only: a redis COMMAND error
                # (RuntimeError) is an application bug and re-raises
                # immediately — retrying cannot fix it
                failures += 1
                if failures >= max(int(retries), 1):
                    raise
                if timeout_s > 0 and time.monotonic() >= deadline:
                    return None
                self._reconnect()
                time.sleep(retry_delay * (0.5 + random.random()))
                retry_delay = min(retry_delay * 2.0, 2.0)
                continue
            failures, retry_delay = 0, 0.05
            if fields:
                def dec(v):
                    return v.decode() if isinstance(v, bytes) else v
                rid = fields.get("request_id")
                # received_monotonic: stamped INSIDE the client the
                # moment the result hash was read, so an open-loop
                # load generator can compute latency from its own
                # scheduled time without wrapping (and re-timing) the
                # poll/retry ladder
                return {"value": json.loads(dec(fields.get("value"))),
                        "request_id": dec(rid) if rid else None,
                        "received_monotonic": time.monotonic()}
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll_delay)
            poll_delay = min(poll_delay * 1.5, 0.25)

    def dequeue(self, uris) -> Dict[str, Any]:
        """Fetch-and-delete results for many uris (client.py dequeue)."""
        out = {}
        for uri in uris:
            res = self.query(uri)
            if res is not None:
                out[uri] = res
                self.broker.delete(RESULT_PREFIX + uri)
        return out


# ------------------------------------------------------ HTTP fast path
class ServingHttpClient:
    """Client for the serving engine's HTTP/JSON fast path
    (``params.http_port``): one POST per record, the response returns
    on the same connection — no broker round trip.

    Same bounded retry/backoff contract as ``OutputQueue.query_meta``:
    connection-class trouble (socket errors — the server is gone or
    mid-restart) is absorbed up to ``retries`` consecutive failures
    with exponential backoff + jitter, then the last error re-raises;
    an HTTP *status* error means the server answered — an application
    outcome, not an outage — and raises :class:`ServingHttpError`
    immediately, retrying cannot fix it."""

    def __init__(self, base_url: str, retries: int = 8,
                 timeout_s: float = 30.0):
        self.base_url = base_url.rstrip("/")
        if "://" not in self.base_url:
            self.base_url = "http://" + self.base_url
        self.retries = int(retries)
        self.timeout_s = float(timeout_s)

    def _open_with_retries(self, req, timeout_s: float, retries: int,
                           consume=None, ts=None):
        """The ONE retry ladder both calls share: connection-class
        failures (socket errors — the server is gone or mid-restart)
        are absorbed up to ``retries`` consecutive attempts with
        exponential backoff + jitter, then the last error re-raises;
        an HTTP *status* error means the server answered — an
        application outcome, not an outage — and raises
        :class:`ServingHttpError` immediately.

        With ``consume`` (a ``response -> value`` callable) the WHOLE
        exchange retries — a connection dying mid-body-read re-POSTs
        the idempotent request.  Without it the open response is
        returned and only *establishing* it retried (the streaming
        caller: tokens already delivered must not replay).

        ``ts`` (a dict) receives monotonic timestamps stamped AT the
        socket, not around the ladder: ``sent_monotonic`` (the start
        of the attempt that ultimately landed — overwritten per
        retry), ``first_byte_monotonic`` (response headers arrived),
        ``received_monotonic`` (body consumed; only with
        ``consume``).  Open-loop load generators read these instead
        of re-timing the whole call, which would fold backoff sleeps
        into the server-facing number."""
        import random
        from urllib import error as urlerror
        from urllib import request as urlrequest
        delay, failures = 0.05, 0
        while True:
            try:
                if ts is not None:
                    ts["sent_monotonic"] = time.monotonic()
                r = urlrequest.urlopen(req, timeout=timeout_s)
                if ts is not None:
                    ts["first_byte_monotonic"] = time.monotonic()
                if consume is None:
                    return r
                with r:
                    out = consume(r)
                if ts is not None:
                    ts["received_monotonic"] = time.monotonic()
                return out
            except urlerror.HTTPError as e:
                try:
                    doc = json.loads(e.read().decode())
                except Exception:   # noqa: BLE001
                    doc = {}
                finally:
                    e.close()
                raise ServingHttpError(
                    e.code, doc.get("error") or str(e), doc) from None
            except (urlerror.URLError, OSError):
                failures += 1
                if failures >= max(int(retries), 1):
                    raise
                time.sleep(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, 2.0)

    def predict_http(self, endpoint: str, payload, *,
                     uri: str = "", request_id: Optional[str] = None,
                     timeout_s: Optional[float] = None,
                     retries: Optional[int] = None,
                     trace=None) -> Dict[str, Any]:
        """Predict one record: ``payload`` is an ndarray (or nested
        list).  Returns the response doc ``{"value": [[class, prob],
        ...], "request_id": ..., "endpoint": ...}``.  ``trace``
        propagates an existing :class:`TraceContext` in the
        traceparent header; absent, one is stamped while tracing is
        on (the same wire string re-sent on every retry)."""
        from urllib import request as urlrequest
        if timeout_s is None:
            timeout_s = self.timeout_s
        if retries is None:
            retries = self.retries
        rid = request_id or uuid.uuid4().hex
        body = json.dumps({
            "data": np.asarray(payload).tolist(),
            "dtype": str(np.asarray(payload).dtype),
            "uri": uri,
            "request_id": rid,
        }).encode()
        headers = {"Content-Type": "application/json"}
        ctx = _stamp_trace(rid, trace, transport="http")
        if ctx is not None:
            headers[TRACE_HEADER] = ctx.to_wire()
        req = urlrequest.Request(
            f"{self.base_url}/predict/{endpoint}", data=body,
            headers=headers)
        # the whole exchange retries: the request was idempotent
        ts: Dict[str, float] = {}
        doc = self._open_with_retries(
            req, timeout_s, retries,
            consume=lambda r: json.loads(r.read().decode()), ts=ts)
        if isinstance(doc, dict):
            # socket-level monotonic stamps for open-loop measurement
            doc.setdefault("client_ts", ts)
        return doc

    def generate(self, endpoint: str, token_ids, *,
                 max_tokens: Optional[int] = None,
                 on_token=None, uri: str = "",
                 request_id: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 trace=None) -> Dict[str, Any]:
        """Streaming generate against a generative endpoint
        (``POST /generate/<endpoint>``, chunked per-token responses):
        ``token_ids`` is the int input sequence (padded to the
        endpoint's ``enc_len``).  Each token is surfaced through
        ``on_token(index, token)`` the moment its chunk arrives;
        returns the final doc ``{"tokens": [...], "request_id": ...,
        "endpoint": ...}``.

        Retry contract matches :meth:`predict_http` (they share one
        ladder): connection-class failures *establishing* the stream
        are absorbed up to ``retries`` attempts with exponential
        backoff + jitter (the request was not admitted yet — retrying
        is safe); an HTTP status error raises
        :class:`ServingHttpError` immediately.  A connection dropped
        MID-stream re-raises without retry: tokens were already
        delivered, and replaying the sequence is the caller's call,
        not the client's."""
        from urllib import request as urlrequest
        if timeout_s is None:
            timeout_s = self.timeout_s
        if retries is None:
            retries = self.retries
        rid = request_id or uuid.uuid4().hex
        payload: Dict[str, Any] = {
            "data": np.asarray(token_ids, np.int64).tolist(),
            "dtype": "int32",
            "uri": uri,
            "request_id": rid,
        }
        if max_tokens:
            payload["max_tokens"] = int(max_tokens)
        headers = {"Content-Type": "application/json"}
        ctx = _stamp_trace(rid, trace, transport="http")
        if ctx is not None:
            headers[TRACE_HEADER] = ctx.to_wire()
        req = urlrequest.Request(
            f"{self.base_url}/generate/{endpoint}",
            data=json.dumps(payload).encode(),
            headers=headers)
        # only ESTABLISHING the stream retries; once chunks flow the
        # relay below runs exactly once
        ts: Dict[str, float] = {}
        r = self._open_with_retries(req, timeout_s, retries, ts=ts)
        # relay chunks (urllib undoes the chunked framing; each line
        # is one JSON event)
        with r:
            tokens = []
            for raw in r:
                line = raw.strip()
                if not line:
                    continue
                doc = json.loads(line.decode())
                if "token" in doc:
                    tokens.append(doc["token"])
                    if on_token is not None:
                        on_token(doc.get("index", len(tokens) - 1),
                                 doc["token"])
                elif doc.get("error"):
                    raise ServingHttpError(200, doc["error"], doc)
                elif doc.get("done"):
                    doc.setdefault("tokens", tokens)
                    ts["received_monotonic"] = time.monotonic()
                    doc.setdefault("client_ts", ts)
                    return doc
            # stream ended without a final line: the server died
            # mid-generation
            raise ServingHttpError(
                200, "generate stream ended without a final "
                     "'done' event", {"tokens": tokens})

    def endpoints(self) -> Dict[str, Any]:
        """The worker's registered endpoints (``GET /endpoints``)."""
        from urllib import request as urlrequest
        with urlrequest.urlopen(f"{self.base_url}/endpoints",
                                timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())["endpoints"]


class ServingHttpError(RuntimeError):
    """The fast path answered with an HTTP error status."""

    def __init__(self, status: int, message: str, doc: Dict):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.doc = doc


def predict_http(base_url: str, endpoint: str, payload,
                 **kwargs) -> Dict[str, Any]:
    """One-shot convenience over :class:`ServingHttpClient`."""
    return ServingHttpClient(base_url).predict_http(
        endpoint, payload, **kwargs)
