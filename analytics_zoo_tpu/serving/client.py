"""Serving client API.

Reference: pyzoo/zoo/serving/client.py — ``InputQueue.enqueue_image``
(:58, base64 → XADD) and ``OutputQueue.query``/``dequeue`` (:127).
"""

from __future__ import annotations

import base64
import io
import json
import time
from typing import Any, Dict, Optional

import numpy as np

from analytics_zoo_tpu.serving.redis_client import connect
from analytics_zoo_tpu.serving.server import INPUT_STREAM, RESULT_PREFIX


class InputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.broker = broker if broker is not None else connect(redis_url)

    def enqueue_image(self, uri: str, image) -> None:
        """image: ndarray (HWC uint8) or path or raw JPEG bytes."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                raw = f.read()
        elif isinstance(image, (bytes, bytearray)):
            raw = bytes(image)
        else:
            import cv2
            ok, enc = cv2.imencode(".jpg", np.asarray(image))
            if not ok:
                raise ValueError("cannot encode image")
            raw = enc.tobytes()
        self.broker.xadd(INPUT_STREAM, {
            "uri": uri, "image": base64.b64encode(raw)})

    def enqueue(self, uri: str, data: np.ndarray) -> None:
        """Arbitrary ndarray input (npy-serialized)."""
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(data), allow_pickle=False)
        self.broker.xadd(INPUT_STREAM, {
            "uri": uri, "data": base64.b64encode(buf.getvalue())})


class OutputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.broker = broker if broker is not None else connect(redis_url)

    def query(self, uri: str, timeout_s: float = 0.0):
        """Result for one uri (list of [class, prob]), or None."""
        deadline = time.time() + timeout_s
        while True:
            fields = self.broker.hgetall(RESULT_PREFIX + uri)
            if fields:
                raw = fields.get("value")
                return json.loads(raw.decode()
                                  if isinstance(raw, bytes) else raw)
            if time.time() >= deadline:
                return None
            time.sleep(0.02)

    def dequeue(self, uris) -> Dict[str, Any]:
        """Fetch-and-delete results for many uris (client.py dequeue)."""
        out = {}
        for uri in uris:
            res = self.query(uri)
            if res is not None:
                out[uri] = res
                self.broker.delete(RESULT_PREFIX + uri)
        return out
