"""Serving client API.

Reference: pyzoo/zoo/serving/client.py — ``InputQueue.enqueue_image``
(:58, base64 → XADD) and ``OutputQueue.query``/``dequeue`` (:127).
"""

from __future__ import annotations

import base64
import io
import json
import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from analytics_zoo_tpu.serving.redis_client import connect
from analytics_zoo_tpu.serving.server import INPUT_STREAM, RESULT_PREFIX


class InputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.broker = broker if broker is not None else connect(redis_url)

    @staticmethod
    def _request_id(request_id: Optional[str]) -> str:
        # the client half of cross-process tracing: the id rides the
        # stream record, threads through the server's decode/batch/
        # predict spans, and is echoed next to the result
        return request_id if request_id else uuid.uuid4().hex

    def enqueue_image(self, uri: str, image,
                      request_id: Optional[str] = None) -> str:
        """image: ndarray (HWC uint8) or path or raw JPEG bytes.
        Returns the record's ``request_id`` (generated when not
        given) — correlate it against the server's spans and the
        ``request_id`` field echoed beside the result."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                raw = f.read()
        elif isinstance(image, (bytes, bytearray)):
            raw = bytes(image)
        else:
            import cv2
            ok, enc = cv2.imencode(".jpg", np.asarray(image))
            if not ok:
                raise ValueError("cannot encode image")
            raw = enc.tobytes()
        rid = self._request_id(request_id)
        self.broker.xadd(INPUT_STREAM, {
            "uri": uri, "image": base64.b64encode(raw),
            "request_id": rid})
        return rid

    def enqueue(self, uri: str, data: np.ndarray,
                request_id: Optional[str] = None) -> str:
        """Arbitrary ndarray input (npy-serialized); returns the
        record's ``request_id``."""
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(data), allow_pickle=False)
        rid = self._request_id(request_id)
        self.broker.xadd(INPUT_STREAM, {
            "uri": uri, "data": base64.b64encode(buf.getvalue()),
            "request_id": rid})
        return rid


class OutputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.broker = broker if broker is not None else connect(redis_url)

    def query(self, uri: str, timeout_s: float = 0.0):
        """Result for one uri (list of [class, prob]), or None."""
        meta = self.query_meta(uri, timeout_s)
        return meta["value"] if meta else None

    def query_meta(self, uri: str, timeout_s: float = 0.0
                   ) -> Optional[Dict[str, Any]]:
        """Result plus correlation metadata: ``{"value": ...,
        "request_id": str | None}`` — the id the server echoed from
        the matching enqueue."""
        deadline = time.time() + timeout_s
        while True:
            fields = self.broker.hgetall(RESULT_PREFIX + uri)
            if fields:
                def dec(v):
                    return v.decode() if isinstance(v, bytes) else v
                rid = fields.get("request_id")
                return {"value": json.loads(dec(fields.get("value"))),
                        "request_id": dec(rid) if rid else None}
            if time.time() >= deadline:
                return None
            time.sleep(0.02)

    def dequeue(self, uris) -> Dict[str, Any]:
        """Fetch-and-delete results for many uris (client.py dequeue)."""
        out = {}
        for uri in uris:
            res = self.query(uri)
            if res is not None:
                out[uri] = res
                self.broker.delete(RESULT_PREFIX + uri)
        return out
