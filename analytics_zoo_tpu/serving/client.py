"""Serving client API.

Reference: pyzoo/zoo/serving/client.py — ``InputQueue.enqueue_image``
(:58, base64 → XADD) and ``OutputQueue.query``/``dequeue`` (:127).
"""

from __future__ import annotations

import base64
import io
import json
import time
import uuid
from typing import Any, Dict, Optional

import numpy as np

from analytics_zoo_tpu.serving.redis_client import connect
from analytics_zoo_tpu.serving.server import INPUT_STREAM, RESULT_PREFIX


class InputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.broker = broker if broker is not None else connect(redis_url)

    @staticmethod
    def _request_id(request_id: Optional[str]) -> str:
        # the client half of cross-process tracing: the id rides the
        # stream record, threads through the server's decode/batch/
        # predict spans, and is echoed next to the result
        return request_id if request_id else uuid.uuid4().hex

    def enqueue_image(self, uri: str, image,
                      request_id: Optional[str] = None) -> str:
        """image: ndarray (HWC uint8) or path or raw JPEG bytes.
        Returns the record's ``request_id`` (generated when not
        given) — correlate it against the server's spans and the
        ``request_id`` field echoed beside the result."""
        if isinstance(image, str):
            with open(image, "rb") as f:
                raw = f.read()
        elif isinstance(image, (bytes, bytearray)):
            raw = bytes(image)
        else:
            import cv2
            ok, enc = cv2.imencode(".jpg", np.asarray(image))
            if not ok:
                raise ValueError("cannot encode image")
            raw = enc.tobytes()
        rid = self._request_id(request_id)
        self.broker.xadd(INPUT_STREAM, {
            "uri": uri, "image": base64.b64encode(raw),
            "request_id": rid})
        return rid

    def enqueue(self, uri: str, data: np.ndarray,
                request_id: Optional[str] = None) -> str:
        """Arbitrary ndarray input (npy-serialized); returns the
        record's ``request_id``."""
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(data), allow_pickle=False)
        rid = self._request_id(request_id)
        self.broker.xadd(INPUT_STREAM, {
            "uri": uri, "data": base64.b64encode(buf.getvalue()),
            "request_id": rid})
        return rid


class OutputQueue:
    def __init__(self, redis_url: Optional[str] = None, broker=None):
        self.redis_url = redis_url
        self.broker = broker if broker is not None else connect(redis_url)

    def _reconnect(self) -> None:
        """Replace a dead socket (url-constructed queues only; an
        injected broker has nothing to reconnect).  A failed reconnect
        is left for the next poll to count — the retry budget, not
        this helper, decides when to give up."""
        if self.redis_url is None:
            return
        try:
            self.broker.close()
        except Exception:   # noqa: BLE001 — already broken
            pass
        try:
            self.broker = connect(self.redis_url)
        except (OSError, RuntimeError):
            pass

    def query(self, uri: str, timeout_s: float = 0.0,
              retries: int = 8):
        """Result for one uri (list of [class, prob]), or None."""
        meta = self.query_meta(uri, timeout_s, retries=retries)
        return meta["value"] if meta else None

    def query_meta(self, uri: str, timeout_s: float = 0.0,
                   retries: int = 8) -> Optional[Dict[str, Any]]:
        """Result plus correlation metadata: ``{"value": ...,
        "request_id": str | None}`` — the id the server echoed from
        the matching enqueue.

        Polling backs off exponentially (20 ms → 250 ms cap) instead
        of hammering a fixed 20 ms, and a transient broker error no
        longer raises straight through: up to ``retries`` consecutive
        connection failures are absorbed with the same bounded
        exponential backoff + jitter the server's result-write path
        uses (reconnecting between attempts), after which the last
        error is re-raised.  A positive ``timeout_s`` is the per-call
        deadline and wins over the retry ladder: when it expires
        mid-retry the call returns ``None`` cleanly, exactly like an
        absent result.  ``timeout_s=0`` (the default) polls for the
        result without blocking but has NO deadline, so broker-blip
        retries may still block up to a few seconds — callers that
        need fail-fast on a dead broker pass ``retries=1``."""
        import random
        deadline = time.monotonic() + timeout_s
        poll_delay, retry_delay, failures = 0.02, 0.05, 0
        while True:
            try:
                fields = self.broker.hgetall(RESULT_PREFIX + uri)
            except OSError:
                # connection-class trouble only: a redis COMMAND error
                # (RuntimeError) is an application bug and re-raises
                # immediately — retrying cannot fix it
                failures += 1
                if failures >= max(int(retries), 1):
                    raise
                if timeout_s > 0 and time.monotonic() >= deadline:
                    return None
                self._reconnect()
                time.sleep(retry_delay * (0.5 + random.random()))
                retry_delay = min(retry_delay * 2.0, 2.0)
                continue
            failures, retry_delay = 0, 0.05
            if fields:
                def dec(v):
                    return v.decode() if isinstance(v, bytes) else v
                rid = fields.get("request_id")
                return {"value": json.loads(dec(fields.get("value"))),
                        "request_id": dec(rid) if rid else None}
            if time.monotonic() >= deadline:
                return None
            time.sleep(poll_delay)
            poll_delay = min(poll_delay * 1.5, 0.25)

    def dequeue(self, uris) -> Dict[str, Any]:
        """Fetch-and-delete results for many uris (client.py dequeue)."""
        out = {}
        for uri in uris:
            res = self.query(uri)
            if res is not None:
                out[uri] = res
                self.broker.delete(RESULT_PREFIX + uri)
        return out
