"""Cluster Serving CLI — the scripts/cluster-serving entry points
(reference cluster-serving-start/stop shells + ClusterServing.main,
serving/ClusterServing.scala:44).

``start`` reads config.yaml, builds the model from ``model: builder:``
(a "pkg.module:function" returning a built KerasNet), optionally loads
``model: weights:`` (a save_model checkpoint), and runs the serving
loop against Redis.  ``stop`` sets the cross-process stop key.
"""

from __future__ import annotations

import argparse
import importlib
import sys


def _build_model(spec: str, weights: str = None):
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise SystemExit(
            f"model builder {spec!r} must look like pkg.module:function")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    model = fn()
    if weights:
        model.load_weights(weights)
    else:
        model.init()
    return model


def _send_stop(cfg):
    import time

    from analytics_zoo_tpu.serving.redis_client import connect
    from analytics_zoo_tpu.serving.server import STOP_KEY
    broker = connect(cfg.redis_url)
    broker.hset(STOP_KEY, {"stop": str(time.time())})
    return broker


def _parse_endpoints(spec: str):
    """``params.endpoints`` / ``--endpoints``: comma/whitespace-
    separated ``name=pkg.module:builder`` entries."""
    out = []
    for item in spec.replace(",", " ").split():
        name, sep, builder = item.partition("=")
        if not sep or not name or not builder:
            raise SystemExit(
                f"endpoint spec {item!r} must look like "
                "name=pkg.module:builder")
        out.append((name.strip(), builder.strip()))
    return out


def _start(cfg, args):
    builder = args.builder or cfg.extra.get("model.builder")
    if not builder:
        raise SystemExit("start needs --builder or config model: builder:")
    weights = args.weights or cfg.extra.get("model.weights")
    model = _build_model(builder, weights)

    from analytics_zoo_tpu.pipeline.inference import InferenceModel
    from analytics_zoo_tpu.serving.server import ClusterServing
    im = InferenceModel().load_zoo(model, quantize=args.quantize)
    serving = ClusterServing(im, cfg)
    # multi-model endpoints beside the default model: records with an
    # ``endpoint`` field (and HTTP /predict/<name>) route to these
    if cfg.endpoints:
        for name, ep_builder in _parse_endpoints(cfg.endpoints):
            ep_model = InferenceModel().load_zoo(
                _build_model(ep_builder), quantize=args.quantize)
            serving.register_endpoint(name, ep_model)
    # graceful drain: SIGTERM (supervisor / orchestrator shutdown) →
    # finish + ack in-flight batches, flush metrics, exit 0
    serving.install_signal_handlers()
    serving.run()
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(prog="cluster-serving")
    p.add_argument("command",
                   choices=["init", "start", "stop", "restart",
                            "shutdown"])
    p.add_argument("--config", "-c", default="config.yaml")
    p.add_argument("--builder", default=None,
                   help="pkg.module:function returning a built model "
                        "(overrides config)")
    p.add_argument("--weights", default=None)
    p.add_argument("--redis", default=None, help="host:port")
    p.add_argument("--quantize", action="store_true")
    p.add_argument("--consumer-group", default=None,
                   help="shared consumer group for replica fleets "
                        "(overrides config params: consumer_group)")
    p.add_argument("--consumer-name", default=None,
                   help="this replica's unique consumer name "
                        "(overrides config params: consumer_name)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="expose Prometheus /metrics on this port "
                        "(0 = ephemeral; overrides config "
                        "params: metrics_port)")
    p.add_argument("--http-port", type=int, default=None,
                   help="HTTP/JSON fast-path port (0 = ephemeral; "
                        "overrides config params: http_port)")
    p.add_argument("--endpoints", default=None,
                   help="extra model endpoints, "
                        "'name=pkg.module:builder,...' (overrides "
                        "config params: endpoints)")
    args = p.parse_args(argv)

    import os
    from analytics_zoo_tpu.serving.server import ServingConfig
    from analytics_zoo_tpu.serving.redis_client import connect

    cfg = ServingConfig.from_yaml(args.config) \
        if os.path.exists(args.config) else ServingConfig()
    if args.redis:
        cfg.redis_url = args.redis
    if args.metrics_port is not None:
        cfg.metrics_port = args.metrics_port
    if args.http_port is not None:
        cfg.http_port = args.http_port
    if args.endpoints:
        cfg.endpoints = args.endpoints
    if args.consumer_group:
        cfg.consumer_group = args.consumer_group
    if args.consumer_name:
        cfg.consumer_name = args.consumer_name

    if args.command == "init":
        # validate the full setup without serving (ref
        # cluster-serving-init): broker reachable + model builds
        from analytics_zoo_tpu.serving.server import INPUT_STREAM
        connect(cfg.redis_url).xlen(INPUT_STREAM)
        builder = args.builder or cfg.extra.get("model.builder")
        if builder:
            _build_model(builder,
                         args.weights or cfg.extra.get("model.weights"))
        print("Cluster Serving has been properly set up.")
        return 0

    if args.command == "stop":
        _send_stop(cfg)
        print("stop signal sent")
        return 0

    if args.command == "shutdown":
        # stop the worker AND the broker (ref cluster-serving-shutdown:
        # stop + redis-cli shutdown).  Wait for the worker to ACK the
        # stop (it DELETEs STOP_KEY after draining) before killing the
        # broker — shutting redis down first would crash the worker
        # mid-drain and lose read-past records.
        import time

        from analytics_zoo_tpu.serving.redis_client import EmbeddedBroker
        from analytics_zoo_tpu.serving.server import STOP_KEY
        broker = _send_stop(cfg)
        if not isinstance(broker, EmbeddedBroker):
            deadline = time.time() + 30.0
            while broker.hgetall(STOP_KEY) and time.time() < deadline:
                time.sleep(0.1)
        try:
            broker.shutdown()
        except Exception:
            pass
        print("Cluster Serving is shutdown.")
        return 0

    if args.command == "restart":
        import time

        from analytics_zoo_tpu.serving.redis_client import EmbeddedBroker
        from analytics_zoo_tpu.serving.server import STOP_KEY
        broker = _send_stop(cfg)
        if isinstance(broker, EmbeddedBroker):
            # in-process broker: no external worker can be listening —
            # clear our own signal and start directly
            broker.delete(STOP_KEY)
        else:
            # wait for the old worker to acknowledge (it DELETEs
            # STOP_KEY on shutdown) — starting immediately would let
            # the new worker consume its own stop signal, or steal the
            # old worker's
            deadline = time.time() + 30.0
            while broker.hgetall(STOP_KEY) and time.time() < deadline:
                time.sleep(0.1)
            if broker.hgetall(STOP_KEY):
                # no worker was running — clear the stale signal
                broker.delete(STOP_KEY)
        print("stop acknowledged; restarting")
        return _start(cfg, args)

    return _start(cfg, args)


if __name__ == "__main__":
    sys.exit(main())
