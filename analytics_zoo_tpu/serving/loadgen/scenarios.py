"""Declarative traffic-scenario DSL + the canned adversarial storms.

A :class:`Scenario` is a list of :class:`Phase`\\ s (each a rate —
possibly ramping — over a duration, with endpoint / transport /
request-kind mixes and a heavy-tail knob), a list of *pinned*
requests (exact offsets for traffic the verdict must be able to
reason about deterministically, e.g. "exactly two poison records at
burst+0.4s"), and a list of :class:`ScenarioEvent`\\ s that fire
against the chaos machinery mid-run (broker outage windows, replica
kills, arbitrary :class:`~analytics_zoo_tpu.resilience.chaos
.FaultSpec` plans).

Everything is generated from ONE seeded RNG, so a scenario is
replayable: the same seed produces the same arrival offsets, the same
mix draws, the same pinned traffic — a failed verdict can be re-run
bit-identically.  ``compress`` scales *durations and event offsets*
only; rates are absolute (a 10× flash burst must exceed the fleet's
capacity whether the scenario runs for a minute or for four seconds).

``run_scenario`` wires a scenario to a :class:`~.loadgen
.LoadGenerator`: events become timeline callbacks through a *hook
table*, so the same scenario runs against an in-process worker
(default hooks script the ``serving.redis`` chaos site) or a real
supervised fleet (the test/CLI overrides ``broker_outage`` with a
real TCP-broker stop/restart and ``kill_replica`` with a SIGKILL).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.serving.loadgen.loadgen import (
    LoadGenerator, LoadgenRun, ScheduledRequest)
from analytics_zoo_tpu.serving.loadgen.verdict import SloSpec

log = logging.getLogger("analytics_zoo_tpu.serving.loadgen")


def _weighted(rng: np.random.RandomState,
              mix: Dict[str, float]) -> str:
    names = sorted(mix)
    weights = np.asarray([float(mix[n]) for n in names], np.float64)
    weights = weights / weights.sum()
    return names[int(rng.choice(len(names), p=weights))]


@dataclasses.dataclass
class Phase:
    """One traffic regime.  ``rate_rps`` → ``rate_end_rps`` ramps
    linearly across the phase (equal = steady).  ``heavy_tail`` mixes
    Pareto-multiplied gaps into the Poisson arrivals — the bursty
    think-time profile real users have and uniform load tools don't."""
    name: str
    duration_s: float
    rate_rps: float
    rate_end_rps: Optional[float] = None
    endpoints: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"default": 1.0})
    transports: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"redis": 1.0})
    kinds: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"ok": 1.0})
    heavy_tail: float = 0.1
    max_tokens: Optional[int] = None

    def arrivals(self, rng: np.random.RandomState,
                 compress: float) -> List[float]:
        """Offsets WITHIN the (compressed) phase."""
        duration = self.duration_s * compress
        end_rate = (self.rate_rps if self.rate_end_rps is None
                    else self.rate_end_rps)
        out, t = [], 0.0
        while t < duration:
            frac = t / duration if duration else 1.0
            rate = self.rate_rps + (end_rate - self.rate_rps) * frac
            if rate <= 0:
                break
            gap = rng.exponential(1.0 / rate)
            if self.heavy_tail > 0 and rng.random() < self.heavy_tail:
                # a heavy-tailed pause: most users click steadily,
                # some wander off and come back in a burst
                gap *= 1.0 + rng.pareto(1.5)
            t += gap
            if t < duration:
                out.append(t)
        return out


@dataclasses.dataclass
class ScenarioEvent:
    """A scripted mid-run action: ``kind`` names a hook
    (``broker_outage`` | ``kill_replica`` | ``chaos``), ``at_s`` is
    the uncompressed offset, ``duration_s`` > 0 fires the hook again
    with ``edge="end"`` when the window closes."""
    at_s: float
    kind: str
    duration_s: float = 0.0
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PinnedRequest:
    """A request at an EXACT offset (uncompressed), for traffic the
    verdict asserts on individually (the poison record that must be
    quarantined after exactly N deliveries)."""
    at_s: float
    kind: str = "ok"
    endpoint: str = "default"
    transport: str = "redis"
    max_tokens: Optional[int] = None


class Scenario:
    """Phases + pins + events + the SLO this scenario must meet.

    ``objectives`` are declarative :class:`~analytics_zoo_tpu
    .observability.slo.SloObjective` specs (or YAML entries loadable
    via ``--slo-spec``): the verdict evaluates each over the run's
    recorded window with the production burn-rate math and emits one
    ``slo:<name>`` check per objective.  Default scenarios declare
    none — a spec is an opt-in claim, not a free pass."""

    def __init__(self, name: str, phases: Sequence[Phase],
                 events: Sequence[ScenarioEvent] = (),
                 pins: Sequence[PinnedRequest] = (),
                 seed: int = 0, slo: Optional[SloSpec] = None,
                 objectives: Sequence[Any] = ()):
        self.name = name
        self.phases = list(phases)
        self.events = sorted(events, key=lambda e: e.at_s)
        self.pins = list(pins)
        self.seed = int(seed)
        self.slo = slo or SloSpec()
        self.objectives = list(objectives)

    # ------------------------------------------------------------- geometry
    def duration_s(self, compress: float = 1.0) -> float:
        return sum(p.duration_s for p in self.phases) * compress

    def phase_window(self, name: str, compress: float = 1.0):
        """(start, end) offsets of a named phase — the verdict anchors
        the autoscaler lag bound on the burst phase's start."""
        t = 0.0
        for p in self.phases:
            end = t + p.duration_s * compress
            if p.name == name:
                return t, end
            t = end
        raise KeyError(f"no phase {name!r} in scenario {self.name!r}")

    # ------------------------------------------------------------- schedule
    def schedule(self, compress: float = 1.0
                 ) -> List[ScheduledRequest]:
        rng = np.random.RandomState(self.seed)
        out: List[ScheduledRequest] = []
        t = 0.0
        for phase in self.phases:
            for off in phase.arrivals(rng, compress):
                out.append(ScheduledRequest(
                    offset_s=t + off,
                    endpoint=_weighted(rng, phase.endpoints),
                    transport=_weighted(rng, phase.transports),
                    kind=_weighted(rng, phase.kinds),
                    max_tokens=phase.max_tokens,
                    phase=phase.name))
            t += phase.duration_s * compress
        for pin in self.pins:
            out.append(ScheduledRequest(
                offset_s=pin.at_s * compress, endpoint=pin.endpoint,
                transport=pin.transport, kind=pin.kind,
                max_tokens=pin.max_tokens, phase="pinned"))
        out.sort(key=lambda s: s.offset_s)
        return out


# ------------------------------------------------------- event hook table
def default_hooks() -> Dict[str, Callable]:
    """In-process hooks: events script the existing chaos sites.  A
    ``broker_outage`` window arms ``serving.redis`` to fail every
    attempted broker op until the window closes (the worker's breaker
    opens, fast-fails, and recovers via its half-open probe — the
    PR 9 contract, now scriptable from a scenario timeline)."""
    from analytics_zoo_tpu.resilience.chaos import (
        SITE_SERVING_REDIS, ChaosPlan, FaultSpec, install_chaos)
    state: Dict[str, Any] = {}

    def broker_outage(event: ScenarioEvent, edge: str) -> None:
        if edge == "start":
            state["prev"] = install_chaos(ChaosPlan([FaultSpec(
                site=SITE_SERVING_REDIS, at_step=0, kind="raise",
                times=10 ** 9,
                message="scenario broker outage window")]))
        else:
            install_chaos(state.pop("prev", None))

    def chaos(event: ScenarioEvent, edge: str) -> None:
        if edge == "start":
            state.setdefault("chaos_prev", []).append(install_chaos(
                ChaosPlan([FaultSpec.from_dict(d)
                           for d in event.params.get("faults", [])])))
        elif state.get("chaos_prev"):
            install_chaos(state["chaos_prev"].pop())

    def kill_replica(event: ScenarioEvent, edge: str) -> None:
        log.warning("scenario event kill_replica ignored: no fleet "
                    "hook installed (in-process run)")

    return {"broker_outage": broker_outage, "chaos": chaos,
            "kill_replica": kill_replica}


def run_scenario(scenario: Scenario, *, compress: float = 1.0,
                 hooks: Optional[Dict[str, Callable]] = None,
                 **loadgen_kwargs) -> LoadgenRun:
    """Build the schedule, wire the events through the hook table,
    and run the load generator.  ``hooks`` entries override the
    in-process defaults (a fleet test passes a real broker
    stop/restart and a real replica SIGKILL)."""
    table = default_hooks()
    table.update(hooks or {})
    schedule = scenario.schedule(compress)
    events = []
    for ev in scenario.events:
        hook = table.get(ev.kind)
        if hook is None:
            log.warning("no hook for scenario event kind %r; skipped",
                        ev.kind)
            continue

        def _fire(hook=hook, ev=ev, edge="start"):
            hook(ev, edge)
        events.append((ev.at_s * compress, _fire))
        if ev.duration_s > 0:
            def _end(hook=hook, ev=ev):
                hook(ev, "end")
            events.append(((ev.at_s + ev.duration_s) * compress,
                           _end))
    gen = LoadGenerator(schedule, **loadgen_kwargs)
    return gen.run(events=events)


# ---------------------------------------------------------- canned storms
def diurnal(*, base_rate: float = 4.0, peak_rate: float = 30.0,
            period_s: float = 12.0, transport: str = "redis",
            seed: int = 7, slo: Optional[SloSpec] = None) -> Scenario:
    """A compressed day: ramp to peak, hold, ramp back down.  No
    faults — this is the capacity-planning scenario (the ramp sweeps
    offered load through the knee, which is exactly the data the
    replicas-per-rps fit needs)."""
    third = period_s / 3.0
    mix = {transport: 1.0}
    return Scenario(
        "diurnal",
        phases=[
            Phase("ramp_up", third, base_rate, peak_rate,
                  transports=mix),
            Phase("peak", third, peak_rate, transports=mix),
            Phase("ramp_down", third, peak_rate, base_rate,
                  transports=mix),
        ],
        seed=seed,
        slo=slo or SloSpec(p99_from_scheduled_ms=5000.0))


def flash_burst_with_outage(*, base_rate: float = 6.0,
                            burst_mult: float = 10.0,
                            warmup_s: float = 3.0,
                            burst_s: float = 5.0,
                            drain_s: float = 3.0,
                            outage_after_s: float = 1.0,
                            outage_s: float = 1.2,
                            poison: int = 1,
                            transport: str = "redis",
                            seed: int = 11,
                            slo: Optional[SloSpec] = None) -> Scenario:
    """The acceptance storm: steady warmup, a 10× flash burst with a
    broker outage window opening mid-burst, poison pinned inside the
    burst, then a slow drain.  A correct fleet rides the outage on
    the breaker, scales up on the burst backlog without flapping,
    quarantines the poison at exactly ``poison_max_attempts``
    deliveries, and loses nothing."""
    mix = {transport: 1.0}
    burst_start = warmup_s
    pins = [PinnedRequest(at_s=burst_start + 0.4 + 0.2 * i,
                          kind="poison", transport=transport)
            for i in range(poison)]
    return Scenario(
        "flash_burst_with_outage",
        phases=[
            Phase("warmup", warmup_s, base_rate, transports=mix),
            Phase("burst", burst_s, base_rate * burst_mult,
                  transports=mix, heavy_tail=0.15),
            Phase("drain", drain_s, base_rate / 2.0, transports=mix),
        ],
        events=[ScenarioEvent(at_s=burst_start + outage_after_s,
                              kind="broker_outage",
                              duration_s=outage_s)],
        pins=pins,
        seed=seed,
        slo=slo or SloSpec(p99_from_scheduled_ms=15000.0,
                           scale_up_lag_s=None))


def poison_flood_drain(*, base_rate: float = 8.0, steady_s: float = 2.5,
                       flood_s: float = 4.0, drain_s: float = 2.5,
                       flood_poison: float = 0.2,
                       flood_malformed: float = 0.2,
                       transport: str = "redis",
                       seed: int = 13,
                       slo: Optional[SloSpec] = None) -> Scenario:
    """A hostile-client flood: healthy steady-state, then a window
    where a fifth of the traffic is poison and another fifth is
    undecodable garbage, then back to healthy.  The verdict checks
    that every hostile record got an explicit terminal outcome (error
    result / quarantine — never silence), no poison resolved ok, and
    the healthy co-traffic still completed."""
    mix = {transport: 1.0}
    ok = max(1.0 - flood_poison - flood_malformed, 0.0)
    return Scenario(
        "poison_flood_drain",
        phases=[
            Phase("steady", steady_s, base_rate, transports=mix),
            Phase("flood", flood_s, base_rate * 2.0, transports=mix,
                  kinds={"ok": ok, "poison": flood_poison,
                         "malformed": flood_malformed}),
            Phase("drain", drain_s, base_rate, transports=mix),
        ],
        seed=seed,
        slo=slo or SloSpec(p99_from_scheduled_ms=15000.0,
                           max_error_fraction=1.0))


#: the canned registry the CLI and the storm bench run by name
SCENARIOS: Dict[str, Callable[..., Scenario]] = {
    "diurnal": diurnal,
    "flash_burst_with_outage": flash_burst_with_outage,
    "poison_flood_drain": poison_flood_drain,
}
