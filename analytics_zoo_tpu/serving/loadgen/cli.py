"""``zoo-loadtest`` — run a canned adversarial scenario against a
serving worker and print/write the SLO verdict + capacity report.

Self-contained mode (default): spins an in-process ``ClusterServing``
worker (embedded broker, numpy delay model) and storms it — the
one-command smoke an operator runs to sanity-check the harness and
produce a capacity-planning JSON on any machine.  Scenario events
script the in-process chaos sites (a ``broker_outage`` window arms
``serving.redis``; the breaker opens, fast-fails, recovers).

``--redis-url``/``--http-url`` target an EXTERNAL worker or fleet
instead (autoscaler checks are skipped — the supervisor's trajectory
is not visible from outside; the fleet acceptance test in
``tests/test_loadgen_fleet.py`` runs the full join).

Exit code: 0 when the verdict passes, 1 when it fails, 2 on usage
errors — so CI can gate on the storm directly.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time

import numpy as np


class DelayModel:
    """Numpy stand-in model: ``predict_delay`` seconds of simulated
    device time per batch; poison payloads (>1e8) raise — the
    in-process containment class (error results, never a crash; the
    process-killing poison class needs the real fleet test)."""

    def __init__(self, predict_delay: float = 0.0):
        self.predict_delay = float(predict_delay)

    def predict(self, x, batch_size=None):
        x = np.asarray(x, dtype=np.float32)
        if np.any(np.abs(x) > 1e8):
            raise ValueError("poison payload rejected")
        if self.predict_delay > 0:
            time.sleep(self.predict_delay)
        return np.tile(np.arange(4, dtype=np.float32), (len(x), 1))


def _self_contained_worker(args):
    """(serving, broker, worker_thread) — an in-process worker shaped
    like one replica of the production fleet."""
    from analytics_zoo_tpu.serving.redis_client import EmbeddedBroker
    from analytics_zoo_tpu.serving.server import (
        ClusterServing, ServingConfig)
    broker = EmbeddedBroker()
    cfg = ServingConfig(
        batch_size=args.batch_size,
        consumer_group="loadtest", consumer_name="w0",
        request_deadline_ms=args.deadline_ms,
        healthz_max_queue=args.healthz_max_queue or None,
        breaker_failures=3, breaker_cooldown_s=0.2,
        reclaim_min_idle_ms=500,
        http_port=0 if args.http else None,
        metrics_host="127.0.0.1")
    serving = ClusterServing(DelayModel(args.predict_delay), cfg,
                             broker=broker)
    t = threading.Thread(target=serving.run, kwargs={"poll_ms": 10},
                         daemon=True)
    t.start()
    return serving, broker, t


def _write_slo_report(path, run, scenario, verdict) -> None:
    """slo_report.json: the declarative objectives, their verdict
    checks, and the full replayed burn/budget timeline — what the CI
    storm stage archives beside capacity_report.json and
    ``obs_report --slo`` renders."""
    import json
    from analytics_zoo_tpu.observability.slo import evaluate_timeline
    from analytics_zoo_tpu.serving.loadgen.verdict import \
        run_series_store
    store = run_series_store(run)
    timeline = evaluate_timeline(store, scenario.objectives)
    doc = {
        "kind": "zoo_slo_report",
        "scenario": scenario.name,
        "objectives": [o.name for o in scenario.objectives],
        "checks": [c.to_dict() for c in verdict.checks
                   if c.name.startswith("slo:")],
        "timeline": [[s.to_dict() for s in row] for row in timeline],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)


def main(argv=None) -> int:
    from analytics_zoo_tpu.serving.loadgen import (
        SCENARIOS, evaluate, read_dead_letters, report_document,
        run_scenario, write_report)
    from analytics_zoo_tpu.serving.loadgen.verdict import \
        pending_count
    from analytics_zoo_tpu.serving.loadgen.loadgen import \
        PayloadFactory

    ap = argparse.ArgumentParser(
        prog="zoo-loadtest",
        description="open-loop adversarial traffic scenarios with an "
                    "SLO verdict and a capacity-planning report")
    ap.add_argument("scenario", choices=sorted(SCENARIOS),
                    help="canned scenario to run")
    ap.add_argument("--compress", type=float, default=1.0,
                    help="duration compression factor (rates stay "
                         "absolute; 0.5 = half as long)")
    ap.add_argument("--out", default=None,
                    help="write the verdict + capacity-planning JSON "
                         "here (render with scripts/obs_report.py)")
    ap.add_argument("--records-out", default=None,
                    help="write the per-request structured log "
                         "(JSONL) here")
    ap.add_argument("--requests-out", default=None,
                    help="export the request log's station timelines "
                         "(requests.json) here; self-contained mode "
                         "captures client AND server stations in one "
                         "process — render with scripts/obs_report.py "
                         "--requests")
    ap.add_argument("--redis-url", default=None,
                    help="target an external broker instead of the "
                         "self-contained worker")
    ap.add_argument("--http-url", default=None,
                    help="external HTTP fast-path base URL")
    ap.add_argument("--http", action="store_true",
                    help="self-contained mode: open the HTTP fast "
                         "path and route the scenario over it")
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--predict-delay", type=float, default=0.02,
                    help="self-contained model seconds per batch")
    ap.add_argument("--deadline-ms", type=int, default=2000,
                    help="worker request_deadline_ms (self-contained)")
    ap.add_argument("--healthz-max-queue", type=int, default=64)
    ap.add_argument("--result-timeout-s", type=float, default=30.0)
    ap.add_argument("--p99-ms", type=float, default=None,
                    help="override the scenario's p99-from-scheduled "
                         "SLO bound")
    ap.add_argument("--slo-spec", default=None,
                    help="YAML file of declarative SLO objectives "
                         "(slo.yaml); each becomes an slo:<name> "
                         "verdict check evaluated over the recorded "
                         "run with the production burn-rate math")
    ap.add_argument("--slo-scale", type=float, default=None,
                    help="scale every --slo-spec time window by this "
                         "factor (compressed storms reuse production "
                         "specs; default: the --compress factor)")
    ap.add_argument("--slo-out", default=None,
                    help="write the evaluated SLO statuses + burn "
                         "timeline JSON here (slo_report.json)")
    ap.add_argument("--run-dir", default=None,
                    help="attach the black-box flight recorder here: "
                         "breaker transitions, dead letters, "
                         "quarantines and chaos trips from the storm "
                         "spool to <dir>/host-0/events.jsonl — the "
                         "evidence `scripts/zoo-doctor <dir>` "
                         "diagnoses afterwards")
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args(argv)

    if args.run_dir:
        import os
        from analytics_zoo_tpu.observability import flightrec
        flightrec.init_flightrec(
            os.path.join(args.run_dir, "host-0"), process_index=0,
            install_hooks=False)
        print(f"flight recorder attached to {args.run_dir}",
              flush=True)

    builder = SCENARIOS[args.scenario]
    kwargs = {}
    if args.seed is not None:
        kwargs["seed"] = args.seed
    # the scenario must ride the transport the target actually
    # exposes: --http (self-contained) or an external --http-url with
    # no broker both mean the HTTP fast path carries the traffic
    if args.http or (args.http_url and not args.redis_url):
        kwargs["transport"] = "http"
    scenario = builder(**kwargs)
    if args.p99_ms is not None:
        scenario.slo.p99_from_scheduled_ms = float(args.p99_ms)
    scenario.slo.request_deadline_ms = float(args.deadline_ms)
    if args.slo_spec:
        from analytics_zoo_tpu.observability.slo import load_slo_yaml
        scale = (args.slo_scale if args.slo_scale is not None
                 else args.compress)
        scenario.objectives = [
            obj.scaled(scale) if scale != 1.0 else obj
            for obj in load_slo_yaml(args.slo_spec)]
        print(f"zoo-loadtest: {len(scenario.objectives)} SLO "
              f"objective(s) from {args.slo_spec} "
              f"(windows scaled x{scale:g})", flush=True)

    serving = worker_thread = None
    external = args.redis_url or args.http_url
    if external:
        from analytics_zoo_tpu.serving.redis_client import connect
        broker_factory = ((lambda: connect(args.redis_url))
                          if args.redis_url else None)
        broker = connect(args.redis_url) if args.redis_url else None
        http_url = args.http_url
    else:
        serving, broker, worker_thread = _self_contained_worker(args)
        broker_factory = lambda: broker     # noqa: E731 — embedded
        http_url = (f"http://127.0.0.1:"
                    f"{serving.http_transport.port}"
                    if serving.http_transport else None)

    print(f"zoo-loadtest: scenario={args.scenario} "
          f"compress={args.compress} duration="
          f"{scenario.duration_s(args.compress):.1f}s "
          f"target={'external' if external else 'self-contained'}",
          flush=True)
    try:
        run = run_scenario(
            scenario, compress=args.compress,
            broker_factory=broker_factory, http_url=http_url,
            payloads=PayloadFactory(shape=(4,)),
            result_timeout_s=args.result_timeout_s)
        pending = 0
        dead = []
        if broker is not None:
            # results are visible BEFORE the worker acks the batch —
            # poll the PEL down instead of reading a transient depth
            group = "loadtest" if not external else "serving"
            deadline = time.monotonic() + 5.0
            while pending_count(broker, group=group) \
                    and time.monotonic() < deadline:
                time.sleep(0.1)
            dead = read_dead_letters(broker)
            pending = pending_count(broker, group=group)
        burst = None
        try:
            burst = scenario.phase_window("burst",
                                          args.compress)[0]
        except KeyError:
            pass
        verdict = evaluate(run, scenario.slo, dead_letters=dead,
                           pending=pending,
                           burst_start_offset_s=burst,
                           objectives=scenario.objectives)
        print(verdict.render(), flush=True)
        if args.slo_out:
            _write_slo_report(args.slo_out, run, scenario, verdict)
            print(f"slo report written to {args.slo_out}", flush=True)
        cap = verdict.capacity or {}
        if cap.get("rps_per_replica_at_slo"):
            print(f"capacity: {cap['rps_per_replica_at_slo']:.1f} "
                  f"req/s per replica at p99<="
                  f"{cap['target_p99_ms']:.0f}ms; replicas needed: "
                  + "  ".join(f"{k}rps->{v}" for k, v in
                              cap["replicas_for"].items()),
                  flush=True)
        if args.records_out:
            run.to_jsonl(args.records_out)
        if args.requests_out:
            from analytics_zoo_tpu.observability.reqtrace import \
                get_request_log
            get_request_log().export(args.requests_out)
            print(f"request timelines written to {args.requests_out}",
                  flush=True)
        if args.out:
            write_report(args.out, report_document(
                args.scenario, verdict, slo=scenario.slo,
                compress=args.compress,
                extra={"duration_s": round(run.wall_s, 2)}))
            print(f"report written to {args.out}", flush=True)
        return 0 if verdict.passed else 1
    finally:
        if serving is not None:
            serving.stop()
        if worker_thread is not None:
            worker_thread.join(timeout=15)
        if args.run_dir:
            from analytics_zoo_tpu.observability import flightrec
            rec = flightrec.get_active_flightrec()
            if rec is not None:
                rec.close()


if __name__ == "__main__":
    sys.exit(main())
