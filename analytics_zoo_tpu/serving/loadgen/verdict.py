"""End-of-run SLO verdict + capacity-planning report.

The verdict joins three evidence streams:

* the **loadgen log** (per-request scheduled/sent/done timestamps and
  terminal statuses — the client's view);
* the **dead-letter stream** (every record the fleet deliberately
  gave up on, with its reason/cause/age bookkeeping — the server's
  confession);
* the **fleet introspection** (the supervisor's replica trajectory
  and scale events — the control plane's diary).

and asserts the production claims end to end:

* **p99 from scheduled** under the SLO bound — the coordinated-
  omission-safe basis (``latency_from_sent`` is reported beside it so
  the CO gap is visible, but it never gates);
* **exactly-once**: every scheduled request reached exactly one
  terminal outcome — nothing lost, nothing silently dropped, no
  request both served and dead-lettered, no duplicate dead letters;
* **shed correctness**: every ``reason=shed`` dead letter is
  deadline-justified by its own recorded age (``age_ms`` vs
  ``deadline_ms``, halved under overload — the PR 9 contract);
* **quarantine exactness**: every ``reason=poison`` dead letter took
  exactly ``poison_max_attempts`` deliveries — fewer means innocent
  records are being condemned, more means a poison record burned
  extra replica lives;
* **poison containment**: no poison-kind request resolved ``ok``;
* **autoscaler trajectory**: a scale-up landed within
  ``scale_up_lag_s`` of the burst start, and the fleet never flapped
  (no re-growth after a shrink during one run).

Checks whose evidence is absent (no poison scheduled, no autoscaler
bound configured) pass vacuously with a ``skipped`` note — the fleet
acceptance test asserts the load-bearing ones really ran.

The **capacity report** is fitted from the run itself: the run is
cut into windows, each window contributes (offered rps, achieved p99
from scheduled, live replicas); the highest per-replica offered rate
whose window still met the target p99 becomes the planning
coefficient, and the report tabulates replicas-needed-per-rps from
it.  Emitted as JSON and rendered by ``scripts/obs_report.py``.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from analytics_zoo_tpu.serving.loadgen.loadgen import LoadgenRun


@dataclasses.dataclass
class SloSpec:
    """The bounds a scenario must meet.  ``None``/0 disables a check
    (it reports as skipped, not passed-on-no-evidence)."""
    p99_from_scheduled_ms: float = 10000.0
    max_error_fraction: float = 0.05     # non-deliberate errors only
    scale_up_lag_s: Optional[float] = None
    max_scale_flaps: int = 0
    request_deadline_ms: float = 0.0
    poison_max_attempts: int = 2
    #: capacity fit target; None = reuse p99_from_scheduled_ms
    target_capacity_p99_ms: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CheckResult:
    name: str
    passed: bool
    detail: str
    skipped: bool = False
    #: forensic handles: the trace_ids of the requests that drove
    #: this finding (p99-region requests, exactly-once violators).
    #: Feed them to ``obs_report --requests RUN_DIR`` to see each
    #: one's station waterfall.
    trace_ids: Tuple[str, ...] = ()

    def to_dict(self) -> Dict[str, Any]:
        out = {"name": self.name, "passed": self.passed,
               "detail": self.detail, "skipped": self.skipped}
        if self.trace_ids:
            out["trace_ids"] = list(self.trace_ids)
        return out


class Verdict:
    """The run's pass/fail plus everything needed to argue about it."""

    def __init__(self, checks: List[CheckResult],
                 latency: Dict[str, float], counts: Dict[str, int],
                 capacity: Optional[Dict] = None):
        self.checks = checks
        self.latency = latency
        self.counts = counts
        self.capacity = capacity

    @property
    def passed(self) -> bool:
        return all(c.passed for c in self.checks)

    def check(self, name: str) -> CheckResult:
        for c in self.checks:
            if c.name == name:
                return c
        raise KeyError(name)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "passed": self.passed,
            "checks": [c.to_dict() for c in self.checks],
            "latency": self.latency,
            "counts": self.counts,
            "capacity_planning": self.capacity,
        }

    def render(self) -> str:
        lines = [f"== SLO verdict: "
                 f"{'PASS' if self.passed else 'FAIL'} =="]
        for c in self.checks:
            mark = ("SKIP" if c.skipped
                    else "ok  " if c.passed else "FAIL")
            lines.append(f"  [{mark}] {c.name}: {c.detail}")
        lines.append(
            "  latency: "
            + "  ".join(f"{k}={v:.1f}ms"
                        for k, v in sorted(self.latency.items())))
        lines.append("  outcomes: "
                     + "  ".join(f"{k}={v}" for k, v
                                 in sorted(self.counts.items())))
        return "\n".join(lines)


# ----------------------------------------------------------- dead letters
def read_dead_letters(broker, reason: Optional[str] = None
                      ) -> List[Dict[str, str]]:
    """Decode the ``serving_dead_letter`` stream into dicts (the
    verdict's server-side evidence)."""
    entries = broker.xread("serving_dead_letter", "0-0", count=100000)
    out = []
    for _id, fields in entries:
        rec = {k: (v.decode() if isinstance(v, bytes) else v)
               for k, v in fields.items()}
        if reason is None or rec.get("reason") == reason:
            out.append(rec)
    return out


def pending_count(broker, stream: str = "serving_stream",
                  group: str = "serving") -> int:
    """The group's remaining PEL depth after a run settled — the
    server-side half of the exactly-once evidence (a delivered but
    never-acked record is invisible to the client log until it is
    reclaimed... or never).  Embedded brokers expose the PEL
    directly; over the wire ``xlag`` (undelivered + pending) stands
    in — 0 after a fully drained run either way."""
    groups = getattr(broker, "_groups", None)
    if isinstance(groups, dict):
        return len(groups.get((stream, group), {})
                   .get("pending", {}))
    xlag = getattr(broker, "xlag", None)
    if xlag is not None:
        try:
            return int(xlag(stream, group))
        except Exception:   # noqa: BLE001 — absent group/old server
            return 0
    return 0


def fleet_snapshot(supervisor) -> Dict[str, Any]:
    """Freeze a supervisor's introspection surface for the verdict
    (duck-typed: anything with replica_trajectory / scale_events)."""
    return {
        "trajectory": [tuple(t) for t
                       in supervisor.replica_trajectory],
        "scale_events": list(supervisor.scale_events),
        "restarts_total": supervisor.restarts_total,
    }


# ---------------------------------------------------------------- checks
def _latency_summary(run: LoadgenRun) -> Dict[str, float]:
    return {
        "p50_from_scheduled_ms": run.percentile(50) * 1e3,
        "p99_from_scheduled_ms": run.percentile(99) * 1e3,
        "p50_from_sent_ms": run.percentile(50, basis="sent") * 1e3,
        "p99_from_sent_ms": run.percentile(99, basis="sent") * 1e3,
    }


def _check_latency(run: LoadgenRun, slo: SloSpec) -> CheckResult:
    p99 = run.percentile(99) * 1e3
    p99_sent = run.percentile(99, basis="sent") * 1e3
    ok = p99 <= slo.p99_from_scheduled_ms
    # name the requests that ARE the tail: everything at/above the
    # p99 value, slowest first — the handles a forensics pass feeds
    # to ``obs_report --requests`` to see where the time went
    tail = sorted(
        ((r.latency_from_scheduled_s or 0.0) * 1e3, r.trace_id)
        for r in run.records
        if r.latency_from_scheduled_s is not None
        and r.latency_from_scheduled_s * 1e3 >= p99)
    tail_ids = tuple(t for _lat, t in reversed(tail))[:5]
    return CheckResult(
        "p99_from_scheduled", ok,
        f"p99 {p99:.0f}ms from SCHEDULED (bound "
        f"{slo.p99_from_scheduled_ms:.0f}ms; from-sent p99 "
        f"{p99_sent:.0f}ms — the gap is the coordinated omission a "
        f"closed-loop bench would have hidden)"
        + (f"; slowest trace_ids {list(tail_ids)}" if tail_ids
           else ""),
        trace_ids=tail_ids)


def _check_exactly_once(run: LoadgenRun,
                        dead_letters: Sequence[Dict],
                        pending: int) -> CheckResult:
    counts = run.counts()
    lost = counts.get("lost", 0) + counts.get("send_failed", 0)
    by_rid: Dict[str, int] = {}
    for d in dead_letters:
        rid = d.get("request_id") or ""
        if rid:
            by_rid[rid] = by_rid.get(rid, 0) + 1
    dupes = sorted(r for r, n in by_rid.items() if n > 1)
    # a request that resolved OK must not ALSO have been given up on
    both = sorted(r.spec.request_id for r in run.records
                  if r.status == "ok"
                  and by_rid.get(r.spec.request_id))
    ok = lost == 0 and pending == 0 and not dupes and not both
    # the violators themselves, by trace_id (== request_id on the
    # loadgen wire): lost/unsent first, then duplicate/double-served
    lost_ids = [r.trace_id for r in run.records
                if r.status in ("lost", "send_failed")]
    violators = tuple((lost_ids + dupes + both)[:8])
    return CheckResult(
        "exactly_once", ok,
        f"{lost} lost/unsent of {len(run.records)}, {pending} still "
        f"pending in the PEL, {len(dupes)} duplicate dead-letter "
        f"request_ids, {len(both)} served-AND-dead-lettered"
        + (f"; violator trace_ids {list(violators)}"
           if violators else ""),
        trace_ids=violators)


def _check_error_fraction(run: LoadgenRun, slo: SloSpec
                          ) -> CheckResult:
    counts = run.counts()
    # deliberate hostile traffic (poison/malformed kinds) is EXPECTED
    # to error; only errors on well-formed requests count
    errors = sum(1 for r in run.records
                 if r.status == "error" and r.spec.kind == "ok")
    total = max(sum(1 for r in run.records if r.spec.kind == "ok"), 1)
    frac = errors / total
    ok = frac <= slo.max_error_fraction
    return CheckResult(
        "error_fraction", ok,
        f"{errors}/{total} well-formed requests errored "
        f"({frac:.1%}; bound {slo.max_error_fraction:.1%}); "
        f"outcomes {dict(sorted(counts.items()))}")


def _check_sheds_justified(dead_letters: Sequence[Dict]
                           ) -> CheckResult:
    sheds = [d for d in dead_letters if d.get("reason") == "shed"]
    if not sheds:
        return CheckResult("sheds_deadline_justified", True,
                           "no records shed", skipped=True)
    unjust = []
    for d in sheds:
        try:
            age = float(d.get("age_ms", "nan"))
            deadline = float(d.get("deadline_ms", "nan"))
        except ValueError:
            unjust.append(d)
            continue
        cut = deadline / 2.0 if d.get("cause") == "overload" \
            else deadline
        if not (age > cut > 0):
            unjust.append(d)
    return CheckResult(
        "sheds_deadline_justified", not unjust,
        f"{len(sheds)} shed, {len(unjust)} NOT past their deadline "
        f"cut (causes "
        f"{sorted({d.get('cause', '?') for d in sheds})})")


def _check_quarantine_exact(dead_letters: Sequence[Dict],
                            slo: SloSpec,
                            poison_scheduled: int) -> CheckResult:
    poisons = [d for d in dead_letters
               if d.get("reason") == "poison"]
    if not poisons and poison_scheduled == 0:
        return CheckResult("quarantine_exact", True,
                           "no poison in the scenario", skipped=True)
    wrong = [d for d in poisons
             if d.get("deliveries")
             != str(slo.poison_max_attempts)]
    return CheckResult(
        "quarantine_exact", not wrong,
        f"{len(poisons)} quarantined of {poison_scheduled} poison "
        f"scheduled; {len(wrong)} with deliveries != "
        f"{slo.poison_max_attempts} "
        f"({sorted({d.get('deliveries') for d in poisons})})")


def _check_poison_contained(run: LoadgenRun) -> CheckResult:
    poison = [r for r in run.records if r.spec.kind != "ok"]
    if not poison:
        return CheckResult("poison_contained", True,
                           "no hostile traffic scheduled",
                           skipped=True)
    leaked = [r for r in poison if r.status == "ok"]
    silent = [r for r in poison
              if r.status in ("lost", "send_failed")]
    return CheckResult(
        "poison_contained", not leaked and not silent,
        f"{len(poison)} hostile requests: {len(leaked)} resolved OK "
        f"(leak), {len(silent)} got no terminal outcome")


def _check_autoscaler(run: LoadgenRun, slo: SloSpec,
                      fleet: Optional[Dict],
                      burst_start_offset_s: Optional[float]
                      ) -> List[CheckResult]:
    if slo.scale_up_lag_s is None or fleet is None:
        return [CheckResult("autoscaler", True,
                            "no autoscaler bound configured",
                            skipped=True)]
    trajectory: List[Tuple[float, int, str]] = [
        tuple(t) for t in fleet.get("trajectory", [])]
    scaled = [(t, s) for (t, s, r) in trajectory if r == "scale_up"]
    out = []
    if burst_start_offset_s is None:
        out.append(CheckResult(
            "scale_up_lag", bool(scaled),
            f"{len(scaled)} scale-up(s) (no burst anchor given)"))
    else:
        burst_wall = run.wall_of(run.started_monotonic
                                 + burst_start_offset_s)
        lags = [t - burst_wall for (t, _s) in scaled
                if t >= burst_wall - 0.5]
        ok = any(0 <= lag <= slo.scale_up_lag_s for lag in lags) \
            if lags else False
        out.append(CheckResult(
            "scale_up_lag", ok,
            f"scale-up lag(s) from burst start: "
            f"{[round(x, 2) for x in lags] or 'NONE'} "
            f"(bound {slo.scale_up_lag_s:.1f}s)"))
    # flap: the fleet grew again after shrinking within one run —
    # the hysteresis the autoscaler promises makes this a defect
    reasons = [r for (_t, _s, r) in trajectory
               if r in ("scale_up", "scale_down")]
    flaps = 0
    seen_down = False
    for r in reasons:
        if r == "scale_down":
            seen_down = True
        elif seen_down:
            flaps += 1
    out.append(CheckResult(
        "no_flap", flaps <= slo.max_scale_flaps,
        f"{flaps} re-growth(s) after a shrink (bound "
        f"{slo.max_scale_flaps}); trajectory "
        f"{[s for (_t, s, _r) in trajectory]}"))
    return out


# ------------------------------------------------- declarative SLO specs
#: latency bucket bounds (seconds) for the synthesized run histogram —
#: a latency_quantile objective's threshold should sit on one of these
RUN_SERIES_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def run_series_store(run: LoadgenRun, *, max_samples: int = 240):
    """Synthesize an ``observability.tsdb.SeriesStore`` from the
    loadgen log, so declarative :class:`~analytics_zoo_tpu
    .observability.slo.SloObjective` specs evaluate against the run
    with the SAME burn-rate math production uses.

    Series (well-formed ``kind=="ok"`` traffic only — hostile traffic
    is EXPECTED to error and must not burn the availability budget):

    * ``loadgen_requests_total`` / ``loadgen_requests_bad_total``
      (bad = ANY non-ok outcome, the client's view) /
      ``loadgen_requests_error_total`` (broken responses only —
      deadline-justified sheds are admission control doing its job
      and have their own verdict check, so production specs usually
      burn availability on errors and let the latency objective
      carry the pain sheds trade away);
    * ``loadgen_latency_seconds_count`` / ``_sum`` /
      ``_bucket{le=...}`` over :data:`RUN_SERIES_BUCKETS`, from the
      scheduled basis (coordinated-omission-safe, same as the p99
      check).

    Completions are bucketed onto a bounded time grid (cumulative
    counters, one sample per grid point, a leading zero sample so the
    first window has a baseline)."""
    from analytics_zoo_tpu.observability.tsdb import (
        SeriesStore, format_series_key)
    events = []     # (wall_done, bad, error, latency_s)
    for r in run.records:
        if r.spec.kind != "ok":
            continue
        mono = r.done if r.done is not None else run.started_monotonic \
            + r.spec.offset_s
        bad = r.status != "ok"
        err = r.status not in ("ok", "shed")
        events.append((run.wall_of(mono), bad, err,
                       r.latency_from_scheduled_s))
    # timestamp only: the latency element can be None (never-completed
    # requests), which full-tuple sort would compare on a (t, bad, err)
    # tie and crash
    events.sort(key=lambda e: e[0])
    t_start = run.wall_of(run.started_monotonic)
    t_end = max([t for (t, _b, _e, _l) in events] + [t_start + 1e-3])
    grid = max((t_end - t_start) / max_samples, 1e-3)
    total = bad_n = err_n = lat_count = 0
    lat_sum = 0.0
    bucket_counts = [0] * len(RUN_SERIES_BUCKETS)

    def counters() -> Dict[str, float]:
        c = {"loadgen_requests_total": float(total),
             "loadgen_requests_bad_total": float(bad_n),
             "loadgen_requests_error_total": float(err_n),
             "loadgen_latency_seconds_count": float(lat_count),
             "loadgen_latency_seconds_sum": lat_sum}
        for le, n in zip(RUN_SERIES_BUCKETS, bucket_counts):
            c[format_series_key("loadgen_latency_seconds_bucket",
                                {"le": f"{le:g}"})] = float(n)
        c[format_series_key("loadgen_latency_seconds_bucket",
                            {"le": "+Inf"})] = float(lat_count)
        return c

    samples = [{"t": t_start, "counters": counters(), "gauges": {}}]
    cursor = t_start + grid
    for (t, bad, err, lat) in events:
        while t > cursor:
            samples.append({"t": cursor, "counters": counters(),
                            "gauges": {}})
            cursor += grid
        total += 1
        if bad:
            bad_n += 1
        if err:
            err_n += 1
        if lat is not None:
            lat_count += 1
            lat_sum += lat
            for i, le in enumerate(RUN_SERIES_BUCKETS):
                if lat <= le:
                    bucket_counts[i] += 1
    samples.append({"t": max(t_end, cursor), "counters": counters(),
                    "gauges": {}})
    return SeriesStore(samples)


def _check_slo_objectives(run: LoadgenRun, objectives: Sequence
                          ) -> List[CheckResult]:
    """One ``slo:<name>`` check per declared objective: the run's
    recorded window must not exhaust the objective's error budget.
    Violating requests are cited by trace_id (PR 16 forensic handles,
    same contract as the p99 check)."""
    if not objectives:
        return []
    from analytics_zoo_tpu.observability.slo import SloEngine
    store = run_series_store(run)
    _t0, t1 = store.time_range()
    engine = SloEngine(list(objectives), registry=None)
    statuses = engine.evaluate(store, now=t1)
    bad_ids = tuple(r.trace_id for r in run.records
                    if r.spec.kind == "ok" and r.status != "ok")[:5]
    err_ids = tuple(r.trace_id for r in run.records
                    if r.spec.kind == "ok"
                    and r.status not in ("ok", "shed"))[:5]
    slow_ids = tuple(
        t for (_lat, t) in sorted(
            ((r.latency_from_scheduled_s, r.trace_id)
             for r in run.records
             if r.latency_from_scheduled_s is not None),
            reverse=True))[:5]
    by_name = {o.name: o for o in objectives}
    out = []
    for st in statuses:
        ok = st.budget_remaining > 0.0
        obj = by_name.get(st.name)
        # cite the requests this OBJECTIVE counts as bad: sheds are
        # not violations of an errors-only availability spec
        errors_only = (obj is not None and
                       obj.bad == "loadgen_requests_error_total")
        ids = () if ok else (
            slow_ids if st.detail == "latency_quantile"
            else err_ids if errors_only else bad_ids)
        out.append(CheckResult(
            f"slo:{st.slo_key}", ok,
            f"bad_fraction {st.bad_fraction:.2%} vs target "
            f"{st.target:.2%} -> budget_remaining "
            f"{st.budget_remaining:.2f}, alert={st.alert}"
            + (f"; violating trace_ids {list(ids)}" if ids else ""),
            trace_ids=ids))
    return out


# ----------------------------------------------------------- capacity fit
def capacity_report(run: LoadgenRun, *, target_p99_ms: float,
                    trajectory: Optional[Sequence[Tuple]] = None,
                    windows: int = 12) -> Dict[str, Any]:
    """Fit replicas-needed-per-rps from the run: cut the schedule into
    ``windows`` equal slices, measure each slice's offered rate and
    achieved p99-from-scheduled, attribute the live replica count from
    the trajectory, and take the best per-replica rate that still met
    the target."""
    if not run.records:
        return {"target_p99_ms": target_p99_ms, "windows": [],
                "rps_per_replica_at_slo": None, "replicas_for": {}}
    offsets = [r.spec.offset_s for r in run.records]
    span = max(max(offsets), 1e-9)
    width = span / windows

    def replicas_at(offset_s: float) -> int:
        if not trajectory:
            return 1
        wall = run.wall_of(run.started_monotonic + offset_s)
        size = trajectory[0][1]
        for (t, s, _r) in trajectory:
            if t <= wall:
                size = s
            else:
                break
        return max(int(size), 1)

    rows = []
    for w in range(windows):
        lo, hi = w * width, (w + 1) * width
        recs = [r for r in run.records
                if lo <= r.spec.offset_s < hi]
        if not recs:
            continue
        lats = sorted(x for x in
                      (r.latency_from_scheduled_s for r in recs)
                      if x is not None)
        p99 = (lats[min(int(0.99 * len(lats)), len(lats) - 1)] * 1e3
               if lats else float("inf"))
        unresolved = sum(1 for r in recs
                         if r.status in ("lost", "send_failed"))
        replicas = replicas_at((lo + hi) / 2.0)
        offered = len(recs) / width
        rows.append({
            "window_s": [round(lo, 2), round(hi, 2)],
            "offered_rps": round(offered, 2),
            "p99_from_scheduled_ms": round(p99, 1),
            "replicas": replicas,
            "rps_per_replica": round(offered / replicas, 2),
            "met_slo": bool(p99 <= target_p99_ms
                            and unresolved == 0),
        })
    feasible = [r["rps_per_replica"] for r in rows if r["met_slo"]]
    per_replica = max(feasible) if feasible else None
    replicas_for = {}
    if per_replica:
        for rate in (10, 50, 100, 250, 500, 1000, 10000):
            replicas_for[str(rate)] = int(
                math.ceil(rate / per_replica))
    return {
        "target_p99_ms": target_p99_ms,
        "windows": rows,
        "rps_per_replica_at_slo": per_replica,
        "replicas_for": replicas_for,
    }


# ---------------------------------------------------------------- entry
def evaluate(run: LoadgenRun, slo: SloSpec, *,
             fleet: Optional[Dict] = None,
             dead_letters: Sequence[Dict] = (),
             pending: int = 0,
             burst_start_offset_s: Optional[float] = None,
             objectives: Sequence = (),
             trajectory_for_capacity: Optional[Sequence[Tuple]]
             = None) -> Verdict:
    """Compute the full verdict.  ``pending`` is the broker's
    remaining PEL depth after the run settled (exactly-once evidence
    the client log alone cannot provide); ``burst_start_offset_s``
    anchors the autoscaler lag bound on the scenario's burst phase;
    ``objectives`` are declarative SLO specs (scenario-declared or
    ``--slo-spec``-loaded) evaluated over the recorded window with
    the production burn-rate math."""
    poison_scheduled = sum(1 for r in run.records
                           if r.spec.kind == "poison")
    checks = [
        _check_latency(run, slo),
        _check_exactly_once(run, dead_letters, pending),
        _check_error_fraction(run, slo),
        _check_sheds_justified(dead_letters),
        _check_quarantine_exact(dead_letters, slo, poison_scheduled),
        _check_poison_contained(run),
    ]
    checks.extend(_check_autoscaler(run, slo, fleet,
                                    burst_start_offset_s))
    checks.extend(_check_slo_objectives(run, objectives))
    target = slo.target_capacity_p99_ms or slo.p99_from_scheduled_ms
    capacity = capacity_report(
        run, target_p99_ms=target,
        trajectory=(trajectory_for_capacity
                    or (fleet or {}).get("trajectory")))
    return Verdict(checks, _latency_summary(run), run.counts(),
                   capacity)


def report_document(scenario_name: str, verdict: Verdict, *,
                    slo: SloSpec, compress: float = 1.0,
                    extra: Optional[Dict] = None) -> Dict[str, Any]:
    """The JSON document ``zoo-loadtest`` writes and
    ``scripts/obs_report.py`` renders: verdict + capacity planning +
    a registry snapshot of the run's exported metrics."""
    from analytics_zoo_tpu.observability import get_registry
    doc = {
        "kind": "zoo_loadtest_report",
        "scenario": scenario_name,
        "compress": compress,
        "slo": slo.to_dict(),
        "verdict": verdict.to_dict(),
        "capacity_planning": verdict.capacity,
        "metrics": get_registry().snapshot(),
    }
    doc.update(extra or {})
    return doc


def write_report(path: str, doc: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
