"""Open-loop, coordinated-omission-safe load generator.

Every serving bench before this module was **closed-loop**: a client
submits, waits for the response, then submits again.  A closed-loop
client slows down exactly when the server does — during the stall the
client simply issues fewer requests, so the stall's cost lands on a
handful of samples instead of on every request a real user would have
sent on schedule.  That measurement artifact is *coordinated omission*
(Tene's hiccup analysis), and it is how a fleet "passes" a latency SLO
it would miss in production.

This generator is **open-loop**: requests fire at their *scheduled*
timestamp regardless of how many responses are outstanding, and every
latency is measured **from the scheduled time** — the moment a real
user would have clicked — not from the moment an unblocked client
thread finally got around to sending.  Both numbers are recorded
(``latency_from_scheduled_s`` / ``latency_from_sent_s``) so the gap
itself is observable: under a stalled server the scheduled-basis p99
grows with the stall while the sent-basis p99 stays flat, and the SLO
verdict (``loadgen.verdict``) deliberately reads the former.

Transports (mirroring the serving engine's ingress surface):

* ``redis``    — the bulk path: XADD onto ``serving_stream`` with a
  ``request_id``, results collected by ONE shared poller thread over
  the ``result:<uri>`` hashes (senders never block on responses — the
  open-loop property);
* ``http``     — the fast path: ``POST /predict/<endpoint>``, one
  sender thread held per in-flight request (the transport's own
  concurrency model);
* ``generate`` — the streaming path: ``POST /generate/<endpoint>``
  with per-token timestamps (``first_byte`` = first token on the
  wire).

Request *kinds* let scenarios script hostile traffic: ``ok`` (a
well-formed payload), ``poison`` (the process-killing payload class
the quarantine machinery exists for), ``malformed`` (undecodable
bytes — the decode-error path).

Per-request structured log: scheduled / sent / first-byte / done
monotonic timestamps + terminal status (``ok`` | ``error`` | ``shed``
| ``quarantined`` | ``lost`` | ``send_failed``), exportable as JSONL.
The clock is injectable so scenario engines and tests can anchor
timelines deterministically.
"""

from __future__ import annotations

import base64
import dataclasses
import io
import json
import logging
import queue as _queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.observability.reqtrace import (
    TRACE_FIELD, TRACE_HEADER, TraceContext)

log = logging.getLogger("analytics_zoo_tpu.serving.loadgen")

#: terminal statuses a record can end in.  ``lost`` (no result before
#: the per-request timeout) and ``send_failed`` (the send never landed
#: inside its retry budget) are the exactly-once violations the
#: verdict hunts; ``shed``/``quarantined`` are *deliberate* server
#: drops that must each be justified by a dead-letter record.
TERMINAL = ("ok", "error", "shed", "quarantined", "lost",
            "send_failed")


@dataclasses.dataclass
class ScheduledRequest:
    """One planned request: WHEN (offset from run start), WHERE
    (endpoint + transport), and WHAT (kind)."""
    offset_s: float
    endpoint: str = "default"
    transport: str = "redis"          # redis | http | generate
    kind: str = "ok"                  # ok | poison | malformed
    uri: str = ""
    request_id: str = ""
    max_tokens: Optional[int] = None
    phase: str = ""

    def __post_init__(self):
        import uuid
        if not self.request_id:
            self.request_id = uuid.uuid4().hex
        if not self.uri:
            self.uri = f"lg-{self.request_id[:12]}"


@dataclasses.dataclass
class RequestRecord:
    """One request's observed life.  All timestamps are the loadgen
    clock (monotonic by default); ``scheduled`` is the PLANNED fire
    time — latency from it charges dispatcher/sender lag to the
    server-facing number, which is the whole point."""
    spec: ScheduledRequest
    scheduled: float = 0.0
    sent: Optional[float] = None
    first_byte: Optional[float] = None
    done: Optional[float] = None
    status: str = "pending"
    error: str = ""
    tokens: int = 0

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL

    @property
    def trace_id(self) -> str:
        # request_id is uuid4().hex, which is already a valid 32-hex
        # trace id — the loadgen stamps it verbatim on the wire, so
        # the id in this record joins directly against the serving
        # plane's /requests.json timelines.
        return self.spec.request_id

    @property
    def latency_from_scheduled_s(self) -> Optional[float]:
        if self.done is None:
            return None
        return max(self.done - self.scheduled, 0.0)

    @property
    def latency_from_sent_s(self) -> Optional[float]:
        if self.done is None or self.sent is None:
            return None
        return max(self.done - self.sent, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "request_id": self.spec.request_id,
            "trace_id": self.trace_id,
            "uri": self.spec.uri,
            "endpoint": self.spec.endpoint,
            "transport": self.spec.transport,
            "kind": self.spec.kind,
            "phase": self.spec.phase,
            "offset_s": round(self.spec.offset_s, 6),
            "scheduled": self.scheduled,
            "sent": self.sent,
            "first_byte": self.first_byte,
            "done": self.done,
            "status": self.status,
            "error": self.error,
            "tokens": self.tokens,
        }


class PayloadFactory:
    """Builds the wire payload for each request kind.  ``shape`` is
    the stateless per-record input shape; generative requests get an
    int token row of ``enc_len``.  Poison follows the fleet-test
    contract (values > 1e8 kill a ``PoisonSensitiveModel`` replica);
    malformed is undecodable on purpose."""

    def __init__(self, shape: Sequence[int] = (4,),
                 poison_value: float = 1e9, enc_len: int = 8,
                 vocab: int = 64, seed: int = 0):
        self.shape = tuple(shape)
        self.poison_value = float(poison_value)
        self.enc_len = int(enc_len)
        self.vocab = int(vocab)
        self._rng = np.random.RandomState(seed)

    def array(self, spec: ScheduledRequest) -> np.ndarray:
        if spec.transport == "generate":
            return self._rng.randint(
                3, self.vocab, (self.enc_len,)).astype(np.int32)
        if spec.kind == "poison":
            return np.full(self.shape, self.poison_value, np.float32)
        return np.zeros(self.shape, np.float32)

    def redis_fields(self, spec: ScheduledRequest) -> Dict[str, Any]:
        fields: Dict[str, Any] = {"uri": spec.uri,
                                  "request_id": spec.request_id}
        if spec.kind == "malformed":
            # not valid base64-of-npy: the server's decode pool fails
            # it and the serve path writes an explicit error result
            fields["data"] = b"!!this-is-not-an-ndarray!!"
        else:
            buf = io.BytesIO()
            np.save(buf, np.ascontiguousarray(self.array(spec)),
                    allow_pickle=False)
            fields["data"] = base64.b64encode(buf.getvalue())
        if spec.endpoint and spec.endpoint != "default":
            fields["endpoint"] = spec.endpoint
        if spec.max_tokens:
            fields["max_tokens"] = str(int(spec.max_tokens))
        # trace context rides the record itself; the fields dict is
        # built ONCE per request, so a send retry after a broker
        # outage re-sends the byte-identical wire value
        fields[TRACE_FIELD] = TraceContext.new(
            spec.request_id).to_wire()
        return fields

    def http_body(self, spec: ScheduledRequest) -> bytes:
        if spec.kind == "malformed":
            return b"{this is not json"
        arr = self.array(spec)
        return json.dumps({
            "data": arr.tolist(), "dtype": str(arr.dtype),
            "uri": spec.uri, "request_id": spec.request_id,
        }).encode()


class LoadgenRun:
    """The finished run: the record log plus the clock anchors that
    let the verdict join monotonic loadgen timestamps against the
    fleet's wall-clock trajectory."""

    def __init__(self, records: List[RequestRecord],
                 started_monotonic: float, started_wall: float,
                 finished_monotonic: float):
        self.records = records
        self.started_monotonic = started_monotonic
        self.started_wall = started_wall
        self.finished_monotonic = finished_monotonic

    @property
    def wall_s(self) -> float:
        return self.finished_monotonic - self.started_monotonic

    def wall_of(self, monotonic_t: float) -> float:
        """Convert a run-clock timestamp to wall time (for joining
        against supervisor trajectories, which stamp time.time())."""
        return self.started_wall + (monotonic_t
                                    - self.started_monotonic)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for r in self.records:
            out[r.status] = out.get(r.status, 0) + 1
        return out

    def latencies(self, basis: str = "scheduled",
                  predicate: Optional[Callable[[RequestRecord], bool]]
                  = None) -> List[float]:
        out = []
        for r in self.records:
            if predicate is not None and not predicate(r):
                continue
            lat = (r.latency_from_scheduled_s if basis == "scheduled"
                   else r.latency_from_sent_s)
            if lat is not None:
                out.append(lat)
        return sorted(out)

    def percentile(self, p: float, basis: str = "scheduled",
                   predicate=None) -> float:
        lat = self.latencies(basis, predicate)
        if not lat:
            return 0.0
        return lat[min(int(p / 100.0 * len(lat)), len(lat) - 1)]

    def to_jsonl(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(json.dumps({
                "started_wall": self.started_wall,
                "started_monotonic": self.started_monotonic,
                "finished_monotonic": self.finished_monotonic,
            }) + "\n")
            for r in self.records:
                f.write(json.dumps(r.to_dict()) + "\n")


def _classify_error_result(text: str) -> str:
    """Map a server error-result string onto a terminal status: the
    serve path writes ``shed: ...`` for admission drops, ``poison:
    quarantined ...`` for quarantines, and ``ShedError: ...`` for
    engine-level generative sheds."""
    low = (text or "").lower()
    if "shed" in low:
        return "shed"
    if "quarantin" in low:
        return "quarantined"
    return "error"


class LoadGenerator:
    """Arrival-schedule-driven request injection.

    * ``broker_factory`` — zero-arg callable returning a broker
      connection (one per internal thread: RESP sockets are not
      thread-safe).  Pass ``lambda: broker`` for an embedded broker.
    * ``http_url`` — base URL of the HTTP fast path (required when the
      schedule contains ``http``/``generate`` requests).
    * ``senders`` — sender-pool size.  Redis sends are non-blocking
      (enqueue only), so a small pool keeps up; HTTP/generate hold a
      sender per in-flight request.  ``senders=1`` deliberately
      recreates a coordinated (blocking) client — the configuration
      the coordinated-omission test uses to show what the scheduled
      basis catches and the sent basis hides.
    * ``events`` — ``[(offset_s, callable)]`` merged into the dispatch
      timeline: chaos windows, broker outages, replica kills fire in
      deterministic order relative to the traffic around them.
    """

    def __init__(self, schedule: Sequence[ScheduledRequest], *,
                 broker_factory: Optional[Callable[[], Any]] = None,
                 http_url: Optional[str] = None,
                 payloads: Optional[PayloadFactory] = None,
                 result_timeout_s: float = 30.0,
                 senders: int = 16,
                 send_retry_s: float = 5.0,
                 poll_interval_s: float = 0.02,
                 http_retries: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        self.schedule = sorted(schedule, key=lambda s: s.offset_s)
        self.broker_factory = broker_factory
        self.http_url = http_url
        self.payloads = payloads or PayloadFactory()
        self.result_timeout_s = float(result_timeout_s)
        self.senders = max(int(senders), 1)
        self.send_retry_s = float(send_retry_s)
        self.poll_interval_s = float(poll_interval_s)
        self.http_retries = int(http_retries)
        self._clock = clock
        self._send_q: "_queue.Queue" = _queue.Queue()
        self._outstanding: Dict[str, RequestRecord] = {}   # uri -> rec
        self._outstanding_lock = threading.Lock()
        self._stop = threading.Event()
        from analytics_zoo_tpu.observability import get_registry
        reg = get_registry()
        self._m_sched = reg.histogram(
            "loadgen_latency_from_scheduled_seconds",
            "request latency measured from the SCHEDULED fire time "
            "(coordinated-omission-safe; the SLO basis)")
        self._m_sent = reg.histogram(
            "loadgen_latency_from_sent_seconds",
            "request latency measured from the actual send (the "
            "closed-loop number, recorded for the CO gap)")
        self._m_requests = reg.counter(
            "loadgen_requests_total",
            "loadgen requests by terminal status",
            labels=("status",))

    # ------------------------------------------------------------- lifecycle
    def run(self, events: Sequence[Tuple[float, Callable[[], None]]]
            = ()) -> LoadgenRun:
        """Fire the whole schedule; block until every record is
        terminal (or its per-request timeout passes).  Returns the
        structured run log."""
        records = [RequestRecord(spec=s) for s in self.schedule]
        started_wall = time.time()
        t0 = self._clock()
        for rec in records:
            rec.scheduled = t0 + rec.spec.offset_s
        # merge request dispatches and scenario events into ONE
        # ordered timeline (events sort before requests at equal
        # offsets so an outage window opens before the traffic
        # scheduled inside it)
        timeline: List[Tuple[float, int, Any]] = \
            [(off, 0, fn) for off, fn in events] + \
            [(rec.spec.offset_s, 1, rec) for rec in records]
        timeline.sort(key=lambda x: (x[0], x[1]))

        sender_threads = [
            threading.Thread(target=self._sender_loop, daemon=True,
                             name=f"loadgen-sender-{i}")
            for i in range(self.senders)]
        for t in sender_threads:
            t.start()
        poller = None
        if any(s.transport == "redis" for s in self.schedule):
            poller = threading.Thread(target=self._poller_loop,
                                      daemon=True,
                                      name="loadgen-poller")
            poller.start()

        try:
            for off, _prio, item in timeline:
                due = t0 + off
                while True:
                    delay = due - self._clock()
                    if delay <= 0:
                        break
                    time.sleep(min(delay, 0.05))
                if callable(item):
                    try:
                        item()
                    except Exception:   # noqa: BLE001 — an event hook
                        log.exception("scenario event hook failed")
                else:
                    self._send_q.put(item)
            # drain: wait out every record's own timeout window
            deadline = t0 + (self.schedule[-1].offset_s
                             if self.schedule else 0.0) \
                + self.result_timeout_s + 5.0
            while self._clock() < deadline:
                if all(r.terminal for r in records):
                    break
                time.sleep(0.05)
            # anything still pending is LOST: the system consumed the
            # request (or never did) and no terminal outcome arrived
            for r in records:
                if not r.terminal:
                    self._finish(r, "lost",
                                 error="no result before the loadgen "
                                       "drain deadline")
        finally:
            self._stop.set()
            for _ in sender_threads:
                self._send_q.put(None)
        return LoadgenRun(records, t0, started_wall, self._clock())

    # ---------------------------------------------------------------- common
    def _finish(self, rec: RequestRecord, status: str,
                error: str = "") -> None:
        if rec.terminal:
            return
        rec.done = self._clock() if rec.done is None else rec.done
        rec.status = status
        rec.error = error
        self._m_requests.labels(status).inc()
        lat = rec.latency_from_scheduled_s
        if lat is not None:
            self._m_sched.observe(lat, exemplar=rec.trace_id)
        lat = rec.latency_from_sent_s
        if lat is not None:
            self._m_sent.observe(lat, exemplar=rec.trace_id)

    # --------------------------------------------------------------- senders
    def _sender_loop(self) -> None:
        tl = threading.local()
        while True:
            rec = self._send_q.get()
            if rec is None:
                return
            try:
                if rec.spec.transport == "redis":
                    self._send_redis(tl, rec)
                elif rec.spec.transport == "generate":
                    self._send_generate(rec)
                else:
                    self._send_http(rec)
            except Exception as e:   # noqa: BLE001 — log, never die
                log.exception("sender failed for %s", rec.spec.uri)
                self._finish(rec, "error",
                             f"{type(e).__name__}: {e}")

    def _send_redis(self, tl, rec: RequestRecord) -> None:
        """Enqueue onto the stream with a bounded retry/reconnect
        budget (a broker outage mid-scenario must not crash the
        sender: the retry time is charged to the scheduled-basis
        latency, which is the honest accounting)."""
        fields = self.payloads.redis_fields(rec.spec)
        deadline = self._clock() + self.send_retry_s
        delay = 0.05
        while True:
            try:
                conn = getattr(tl, "conn", None)
                if conn is None:
                    conn = tl.conn = self.broker_factory()
                conn.xadd("serving_stream", fields)
                rec.sent = self._clock()
                break
            except (OSError, RuntimeError) as e:
                try:
                    if getattr(tl, "conn", None) is not None:
                        tl.conn.close()
                except Exception:   # noqa: BLE001 — already broken
                    pass
                tl.conn = None
                if self._clock() >= deadline:
                    self._finish(rec, "send_failed",
                                 f"{type(e).__name__}: {e}")
                    return
                time.sleep(delay)
                delay = min(delay * 2.0, 0.5)
        with self._outstanding_lock:
            self._outstanding[rec.spec.uri] = rec

    def _http_client(self):
        from analytics_zoo_tpu.serving.client import ServingHttpClient
        return ServingHttpClient(self.http_url,
                                 retries=self.http_retries,
                                 timeout_s=self.result_timeout_s)

    def _send_http(self, rec: RequestRecord) -> None:
        from analytics_zoo_tpu.serving.client import ServingHttpError
        from urllib import request as urlrequest
        client = self._http_client()
        body = self.payloads.http_body(rec.spec)
        req = urlrequest.Request(
            f"{client.base_url}/predict/{rec.spec.endpoint}",
            data=body, headers={
                "Content-Type": "application/json",
                # Request object is built once: every retry re-sends
                # the byte-identical traceparent
                TRACE_HEADER: TraceContext.new(
                    rec.spec.request_id).to_wire(),
            })
        rec.sent = self._clock()
        try:
            ts: Dict[str, float] = {}
            doc = client._open_with_retries(
                req, self.result_timeout_s, self.http_retries,
                consume=lambda r: json.loads(r.read().decode()),
                ts=ts)
            # prefer the client's own monotonic stamps (satellite:
            # measured at the socket, not around the retry ladder)
            if "sent_monotonic" in ts:
                rec.sent = ts["sent_monotonic"]
            rec.first_byte = ts.get("first_byte_monotonic")
            rec.done = ts.get("received_monotonic", self._clock())
            if doc.get("error"):
                self._finish(rec,
                             _classify_error_result(doc["error"]),
                             doc["error"])
            else:
                self._finish(rec, "ok")
        except ServingHttpError as e:
            rec.done = self._clock()
            self._finish(rec, _classify_error_result(str(e)), str(e))
        except Exception as e:   # noqa: BLE001 — connection-class
            rec.done = self._clock()
            self._finish(rec, "error", f"{type(e).__name__}: {e}")

    def _send_generate(self, rec: RequestRecord) -> None:
        from analytics_zoo_tpu.serving.client import ServingHttpError
        client = self._http_client()
        arr = self.payloads.array(rec.spec)

        def on_token(_i, _tok):
            now = self._clock()
            if rec.first_byte is None:
                rec.first_byte = now
            rec.tokens += 1

        rec.sent = self._clock()
        try:
            doc = client.generate(
                rec.spec.endpoint, arr,
                max_tokens=rec.spec.max_tokens, uri=rec.spec.uri,
                request_id=rec.spec.request_id, on_token=on_token,
                timeout_s=self.result_timeout_s,
                retries=self.http_retries)
            rec.done = self._clock()
            rec.tokens = len(doc.get("tokens", ())) or rec.tokens
            self._finish(rec, "ok")
        except ServingHttpError as e:
            rec.done = self._clock()
            self._finish(rec, _classify_error_result(str(e)), str(e))
        except Exception as e:   # noqa: BLE001 — connection-class
            rec.done = self._clock()
            self._finish(rec, "error", f"{type(e).__name__}: {e}")

    # ---------------------------------------------------------------- poller
    def _poller_loop(self) -> None:
        """ONE thread resolves every outstanding redis request: scan
        the result hashes round-robin on a single connection.  Senders
        never wait on results — this is what keeps the redis path
        open-loop at any outstanding depth."""
        conn = None
        # the in-body empty+stopped check below is the real exit
        # condition, and it reads _outstanding under its lock — a
        # `while ... or self._outstanding` header would re-read it
        # unlocked for no extra information
        while True:
            with self._outstanding_lock:
                uris = list(self._outstanding)
            if not uris:
                if self._stop.is_set():
                    return
                time.sleep(self.poll_interval_s)
                continue
            for uri in uris:
                with self._outstanding_lock:
                    rec = self._outstanding.get(uri)
                if rec is None:
                    continue
                if rec.terminal:        # timed out by the drain pass
                    with self._outstanding_lock:
                        self._outstanding.pop(uri, None)
                    continue
                try:
                    if conn is None:
                        conn = self.broker_factory()
                    fields = conn.hgetall("result:" + uri)
                except (OSError, RuntimeError):
                    try:
                        if conn is not None:
                            conn.close()
                    except Exception:   # noqa: BLE001
                        pass
                    conn = None
                    time.sleep(0.1)
                    break               # restart the scan
                if fields:
                    raw = fields.get("value", fields.get(b"value"))
                    if isinstance(raw, bytes):
                        raw = raw.decode()
                    rec.done = self._clock()
                    try:
                        doc = json.loads(raw) if raw else None
                    except (TypeError, json.JSONDecodeError):
                        doc = None
                    if isinstance(doc, dict) and doc.get("error"):
                        self._finish(
                            rec, _classify_error_result(doc["error"]),
                            doc["error"])
                    else:
                        self._finish(rec, "ok")
                    with self._outstanding_lock:
                        self._outstanding.pop(uri, None)
                elif self._clock() - rec.scheduled \
                        > self.result_timeout_s:
                    self._finish(rec, "lost",
                                 "no result within "
                                 f"{self.result_timeout_s:.1f}s of "
                                 "the scheduled time")
                    with self._outstanding_lock:
                        self._outstanding.pop(uri, None)
            time.sleep(self.poll_interval_s)
