"""Adversarial traffic simulation harness (ROADMAP item 5).

``loadgen``   — open-loop, coordinated-omission-safe request
                injection over the Redis bulk path, the HTTP fast
                path, and streaming ``/generate``.
``scenarios`` — the declarative phase/event DSL + canned storms
                (``diurnal``, ``flash_burst_with_outage``,
                ``poison_flood_drain``).
``verdict``   — end-of-run SLO assertions joined across the loadgen
                log, the dead-letter stream, and the supervisor's
                trajectory, plus the capacity-planning report.
"""

from analytics_zoo_tpu.serving.loadgen.loadgen import (  # noqa: F401
    LoadGenerator, LoadgenRun, PayloadFactory, RequestRecord,
    ScheduledRequest)
from analytics_zoo_tpu.serving.loadgen.scenarios import (  # noqa: F401
    SCENARIOS, Phase, PinnedRequest, Scenario, ScenarioEvent,
    default_hooks, diurnal, flash_burst_with_outage,
    poison_flood_drain, run_scenario)
from analytics_zoo_tpu.serving.loadgen.verdict import (  # noqa: F401
    CheckResult, SloSpec, Verdict, capacity_report, evaluate,
    fleet_snapshot, pending_count, read_dead_letters,
    report_document, run_series_store, write_report)
