"""Continuous (in-flight) batcher — the middle layer of the serving
engine.

The PR 9 loop predicted one full ``batch_size`` read at a time: a
lone request waited for the stream read to time out, and a burst
arriving mid-predict waited a whole predict before even being read.
Here the executor is never idle while work is queued: the moment it
frees, a batch is formed from whatever is queued for one endpoint and
padded UP to the nearest warmed bucket size (see
``executor.default_buckets``) — partial batches dispatch immediately
under backlog, so tail latency tracks the device, not the batch
knob.

Requests arrive in *groups* (a Redis bulk read is one group, an HTTP
request is a group of one).  Groups are atomic: a group is never
split across device batches, so the Redis path's batch-scoped
semantics (ack-after-serve, poison-batch error results) survive the
decomposition unchanged, while separate groups DO co-ride one device
batch — the continuous-batching win.

The ``max_wait_ms`` knob applies only on the empty→non-empty edge
(the executor was idle with nothing queued): the first arrivals may
wait up to ``max_wait_ms`` (from the oldest arrival) for co-riders to
fill toward the largest bucket, and are dispatched the moment either
the bucket fills or the deadline passes — a lone request is always
served within ``max_wait_ms`` of arrival plus one predict.  When work
was already queued as the executor freed (the loaded case), dispatch
is immediate and the knob never adds latency.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, List, Optional, Sequence

log = logging.getLogger("analytics_zoo_tpu.serving.engine")


class ShedError(TimeoutError):
    """Admission control dropped the request before it burned device
    capacity (deadline passed while queued).  A ``TimeoutError``
    subclass on purpose: the HTTP transport's status mapping answers
    504 for the timeout class, and the message carries the ``shed:``
    marker clients and the loadgen verdict key on.  ``age_ms`` /
    ``deadline_ms`` carry the justification so the Redis transport
    can dead-letter the shed with the same evidence fields the
    stream-path shed records (the verdict proves every shed was
    deadline-earned from exactly these)."""

    def __init__(self, message: str, age_ms: float = 0.0,
                 deadline_ms: float = 0.0):
        super().__init__(message)
        self.age_ms = float(age_ms)
        self.deadline_ms = float(deadline_ms)


@dataclasses.dataclass
class Request:
    """One record flowing through the engine, transport-agnostic.

    The transport that created it blocks on :meth:`wait` (HTTP
    handler thread, or the Redis loop waiting for a submitted bulk
    group) and reads ``result`` / ``error`` after completion."""
    endpoint: str
    uri: str
    data: Any                       # per-record ndarray (no batch dim)
    request_id: Optional[str] = None
    arrival: float = 0.0            # time.perf_counter() at ingress
    result: Any = None
    error: Optional[BaseException] = None
    #: generative-only: per-sequence token budget (clamped to the
    #: endpoint's max_seq_len; None = the endpoint default)
    max_tokens: Optional[int] = None
    #: generative-only: called (index, token) from the scheduler
    #: thread the moment each token is emitted — the per-token
    #: streaming hook.  Must be fast and never raise (it runs between
    #: decode iterations); errors are swallowed.
    on_token: Optional[Any] = None
    #: request-scoped tracing context (observability.reqtrace): a
    #: TraceContext (or bare trace_id) the transport decoded from the
    #: wire; None = untraced.  The batcher/executor/decode layers mark
    #: their lifecycle stations against it.
    trace: Optional[Any] = None
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False)

    @property
    def trace_id(self) -> Optional[str]:
        if self.trace is None:
            return None
        if isinstance(self.trace, str):
            return self.trace
        return getattr(self.trace, "trace_id", None)

    def complete(self, result: Any) -> None:
        self.result = result
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        self.error = exc
        self._done.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        """Block until completed; False on timeout (the request may
        still complete later — the caller decides whether to treat
        that as an error)."""
        return self._done.wait(timeout_s)


class ContinuousBatcher:
    """One executor thread draining per-endpoint group queues.

    All queue state is guarded by one condition variable; predict runs
    OUTSIDE the lock (XLA dispatch releases the GIL, so transports
    keep submitting while the device works).  A failure inside an
    execution fails that batch's requests and never kills the thread —
    the engine twin of the serving loop's poison contract."""

    def __init__(self, registry, executor,
                 max_wait_ms: float = 0.0,
                 clock=time.perf_counter):
        from analytics_zoo_tpu.observability import get_registry
        self.registry = registry          # EndpointRegistry
        self.executor = executor          # ModelExecutor
        self.max_wait_ms = max(float(max_wait_ms), 0.0)
        self._clock = clock
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # deterministic weighted scheduling state: endpoint -> credit
        self._credit = {}
        self.batches_dispatched = 0
        reg = get_registry()
        self._m_inflight = reg.gauge(
            "serving_inflight_batches",
            "batches currently executing on the device")
        self._m_wait = reg.histogram(
            "serving_batch_wait_seconds",
            "oldest-request queue wait at batch dispatch")
        self._m_requests = reg.counter(
            "serving_endpoint_requests_total",
            "requests submitted per serving endpoint",
            labels=("endpoint",))

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ContinuousBatcher":
        """Idempotent: a live thread is reused, a stopped batcher
        restarts (``ClusterServing.close()`` + a later ``run()`` is a
        supported sequence)."""
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="zoo-serving-batcher")
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout_s)

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -------------------------------------------------------------- ingress
    def submit(self, requests: Sequence[Request],
               _now: Optional[float] = None) -> List[Request]:
        """Enqueue one atomic group (all requests must share one
        endpoint).  Unknown endpoints fail the whole group immediately
        — the transport writes the error result, nothing is silently
        dropped.  Returns the requests for wait-all convenience."""
        requests = list(requests)
        if not requests:
            return requests
        name = requests[0].endpoint
        now = self._clock() if _now is None else _now
        for r in requests:
            if not r.arrival:
                r.arrival = now
        ep = self.registry.get(name)
        if ep is None or any(r.endpoint != name for r in requests):
            exc = KeyError(
                f"unknown serving endpoint {name!r} (registered: "
                f"{sorted(self.registry.names())})")
            for r in requests:
                r.fail(exc if r.endpoint == name else KeyError(
                    "mixed endpoints in one submitted group"))
            return requests
        self._m_requests.labels(name).inc(len(requests))
        if any(r.trace is not None for r in requests):
            from analytics_zoo_tpu.observability.reqtrace import (
                get_request_log)
            from analytics_zoo_tpu.observability.tracing import (
                get_tracer)
            reqlog = get_request_log()
            tracer = get_tracer()
            for r in requests:
                tid = r.trace_id
                if not tid:
                    continue
                reqlog.mark(tid, "batch_queue_enter", t=now,
                            endpoint=name)
                # flow OUT of the transport thread's slice; the
                # executor thread closes it at batch compose, giving
                # Perfetto its causal arrow across the two lanes
                tracer.flow_start("serving_request", tid)
        # groups larger than the endpoint's largest bucket are split
        # into bucket-sized atomic chunks (each chunk still serves
        # together; the transport's wait-all covers all chunks).
        # Generative sequences queue individually: slot-pool admission
        # is per sequence (a half-free pool admits half a group and
        # keeps the rest queued), and completion is per sequence too —
        # the transport's wait-all, not co-location, carries the
        # group's ack semantics.
        cap = 1 if ep.generative else ep.buckets[-1]
        with self._cv:
            for lo in range(0, len(requests), cap):
                ep.queue.append(requests[lo:lo + cap])
            self._cv.notify_all()
        return requests

    def submit_one(self, request: Request) -> Request:
        self.submit([request])
        return request

    # ----------------------------------------------------------- scheduling
    def _pick_endpoint(self):
        """Deterministic weighted round-robin over endpoints with
        queued work: every pick debits one credit; when every pending
        endpoint is out of credit, all credits refill to the weights.
        An endpoint with weight 2 gets two batches for every one of a
        weight-1 peer under contention, and never starves anyone."""
        pending = [ep for ep in self.registry if ep.has_work]
        if not pending:
            return None
        for ep in pending:
            self._credit.setdefault(ep.name, ep.weight)
        funded = [ep for ep in pending if self._credit[ep.name] > 0]
        if not funded:
            for ep in pending:
                self._credit[ep.name] = ep.weight
            funded = pending
        ep = funded[0]
        self._credit[ep.name] -= 1
        return ep

    def _compose(self, ep) -> List[Request]:
        """Pop whole groups for ``ep`` into one device batch: groups
        are taken in arrival order while they fit under the largest
        bucket AND share the first group's per-record shape/dtype (a
        mismatched group cannot np.stack with the rest — it waits for
        its own batch instead of poisoning this one).  Requests that
        already completed while queued — a transport timed them out
        and answered their client with an error — are dropped here:
        predicting them would amplify load exactly when the executor
        is already behind."""
        batch: List[Request] = []
        cap = ep.buckets[-1]
        key = None
        while ep.queue:
            group = [r for r in ep.queue[0] if not r.done]
            if not group:
                ep.queue.popleft()
                continue
            gkey = self._shape_key(group)
            if key is None:
                key = gkey
            elif gkey != key:
                break
            if batch and len(batch) + len(group) > cap:
                break
            ep.queue.popleft()
            batch.extend(group)
        return batch

    @staticmethod
    def _shape_key(group):
        try:
            a = group[0].data
            return (tuple(getattr(a, "shape", ())),
                    str(getattr(a, "dtype", "")))
        except Exception:   # noqa: BLE001 — exotic payloads still batch
            return ("?",)

    def _queued_for(self, ep) -> int:
        return sum(len(g) for g in ep.queue)

    def _any_bucket_full(self) -> bool:
        """Does ANY endpoint have a largest-bucket's worth queued?
        Ends the idle-edge fill-wait: a full bucket anywhere beats
        waiting out one endpoint's co-rider timer."""
        return any(self._queued_for(e) >= e.buckets[-1]
                   for e in self.registry if e.queue)

    def _generative_pending(self) -> bool:
        """Any generative endpoint with work ALSO ends the fill-wait:
        a sequence's first token must never sit behind a stateless
        peer's co-rider timer (generative endpoints themselves never
        fill-wait, and that guarantee has to hold when a stateless
        endpoint grabbed the idle edge first)."""
        return any(e.generative and e.has_work for e in self.registry)

    # ------------------------------------------------------------ main loop
    def _loop(self) -> None:
        # whether the previous iteration dispatched a batch: work
        # found right after an execution accumulated WHILE the device
        # was busy and dispatches immediately (the continuous-batching
        # property); work found any other way — batcher just started,
        # or woke from an empty-queue idle — is on the idle edge,
        # where max_wait gives co-riders a chance to fill a bucket
        just_executed = False
        while not self._stop.is_set():
            with self._cv:
                ep = self._pick_endpoint()
                if ep is None:
                    # executor idle, nothing queued: sleep until a
                    # submit notifies
                    just_executed = False
                    self._cv.wait(0.5)
                    ep = self._pick_endpoint()
                    if ep is None:
                        continue
                if ep.generative:
                    # generative endpoints never fill-wait: between
                    # decode iterations every queued sequence is a
                    # backfill candidate anyway, and a timer here
                    # would tax inter-token latency, the metric the
                    # decode scheduler exists to protect
                    pass
                elif not just_executed and self.max_wait_ms > 0.0:
                    # the idle edge: the first arrivals may wait
                    # (from the OLDEST queued arrival) for co-riders
                    # toward the largest bucket — ending the moment
                    # ANY endpoint has a full bucket queued, so a
                    # burst for a peer endpoint never idles the
                    # executor behind one endpoint's lone-request
                    # timer
                    deadline = (min(r.arrival for g in ep.queue
                                    for r in g)
                                + self.max_wait_ms / 1000.0)
                    while not self._stop.is_set() \
                            and not self._any_bucket_full() \
                            and not self._generative_pending():
                        remaining = deadline - self._clock()
                        if remaining <= 0:
                            break
                        self._cv.wait(min(remaining, 0.05))
                if self._stop.is_set():
                    break
                # dispatch NOW, partial or not
                batch = [] if ep.generative else self._compose(ep)
            if ep.generative:
                # one decode ITERATION per scheduling credit: step
                # the active slots, retire finished sequences,
                # backfill from the queue — then fall back into the
                # scheduler so stateless peers interleave per
                # iteration, not per sequence
                self._execute_decode(ep)
                just_executed = True
                continue
            if not batch:
                continue
            self._m_wait.observe(
                max(self._clock() - min(r.arrival for r in batch),
                    0.0))
            self._execute(ep, batch)
            just_executed = True

    def _execute_decode(self, ep) -> None:
        """One generative scheduler iteration under the same
        thread-survival guard as :meth:`_execute`: the executor
        already failed the active sequences on any escape (and reset
        the pool), so this only has to keep the batcher alive."""
        self._m_inflight.set(1)
        try:
            self.executor.execute_decode(ep)
        except BaseException:   # noqa: BLE001 — poison contract
            log.exception("decode iteration escaped for endpoint %s; "
                          "failed sequences carry the error to their "
                          "transports", ep.name)
        finally:
            self._m_inflight.set(0)
            self.batches_dispatched += 1

    def _execute(self, ep, batch: List[Request]) -> None:
        self._m_inflight.set(1)
        try:
            self.executor.execute(ep, batch)
        except BaseException as e:   # noqa: BLE001 — poison contract
            # the executor already fails requests on model errors;
            # this catches executor-level surprises — INCLUDING the
            # non-Exception process-death class — so the batcher
            # thread survives.  The failed requests carry the
            # exception to their transports, and the Redis transport
            # re-raises non-Exception escapes so its loop dies with
            # the batch un-acked (the PEL-reclaim contract); actual
            # process kills (os._exit, signals) never reach here.
            for r in batch:
                if not r.done:
                    r.fail(e)
            log.exception("batch execution failed (%d records, "
                          "endpoint %s)", len(batch), ep.name)
        finally:
            self._m_inflight.set(0)
            self.batches_dispatched += 1
