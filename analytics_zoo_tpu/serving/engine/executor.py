"""Executor layer: multi-model endpoint registry + bucket-padded
predict.

An :class:`Endpoint` maps a name to an ``InferenceModel`` (anything
with ``predict``; ``warm`` optional), its bucket ladder, top-N
config, and a per-endpoint group queue the batcher schedules across
with weighted round-robin.  :class:`ModelExecutor` runs one composed
batch: stack → pad to the smallest bucket that fits → predict →
top-N softmax postprocess → complete each request.

Buckets are the core of the latency story: instead of ONE padded
shape (always ``batch_size``, PR 9), each endpoint keeps a small
ladder of batch sizes, every rung AOT-warmed at model load (the PR 8
``compile/`` cache makes that a deserialize, not a compile), so a
partial batch pays a partial predict — a lone request on a bucket-1
program, not a 31/32-padding full batch.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.stages import pad_to_batch

log = logging.getLogger("analytics_zoo_tpu.serving.engine")


def default_buckets(batch_size: int) -> Tuple[int, ...]:
    """The default ladder: powers of two up to ``batch_size``, plus
    ``batch_size`` itself — ≤ log2(bs)+1 warmed programs, every fill
    level within 2x of its bucket."""
    bs = max(int(batch_size), 1)
    out = []
    b = 1
    while b < bs:
        out.append(b)
        b *= 2
    out.append(bs)
    return tuple(out)


def bucket_for(buckets: Sequence[int], n: int) -> int:
    """Smallest ladder rung that fits ``n`` records — the one
    bucket-selection rule (shared by stateless endpoints and the
    decode slot pool, whose ladders come from the same
    ``parse_buckets``)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


def parse_buckets(spec, batch_size: int) -> Tuple[int, ...]:
    """Normalize a bucket spec (``"1,4,16"`` / iterable / None):
    sorted, deduped, capped at ``batch_size``, and always containing
    ``batch_size`` so every composed batch has a rung that fits."""
    if spec in (None, "", ()):
        return default_buckets(batch_size)
    if isinstance(spec, str):
        spec = [s for s in spec.replace("x", ",").split(",")
                if s.strip()]
    buckets = sorted({int(b) for b in spec if int(b) > 0})
    buckets = [b for b in buckets if b <= batch_size]
    if not buckets or buckets[-1] != batch_size:
        buckets.append(int(batch_size))
    return tuple(buckets)


class Endpoint:
    """One served model and its engine-side state."""

    def __init__(self, name: str, model, *, top_n: int = 1,
                 buckets: Sequence[int] = (),
                 batch_size: Optional[int] = None,
                 input_shape=None, weight: int = 1):
        if batch_size is None:
            batch_size = max(buckets) if buckets else 4
        self.name = name
        self.model = model
        self.top_n = int(top_n)
        self.buckets = parse_buckets(buckets, int(batch_size))
        self.input_shape = (tuple(input_shape) if input_shape
                            else None)
        self.weight = max(int(weight), 1)
        #: FIFO of atomic request groups (the batcher owns the lock)
        self.queue: deque = deque()
        self.records_total = 0

    #: generative endpoints override (decode.GenerativeEndpoint) —
    #: the batcher routes on it without importing the decode module
    generative = False

    @property
    def has_work(self) -> bool:
        """Whether the scheduler should hand this endpoint a credit
        (generative endpoints also count active decode slots)."""
        return bool(self.queue)

    def bucket_for(self, n: int) -> int:
        """Smallest warmed bucket that fits ``n`` records."""
        return bucket_for(self.buckets, n)

    def warm(self) -> int:
        """AOT warm-start every bucket (no-op without a model ``warm``
        or a configured ``input_shape``).  Returns #buckets warmed —
        after a full warm, no fill level recompiles."""
        warm = getattr(self.model, "warm", None)
        if warm is None or self.input_shape is None:
            return 0
        warmed = 0
        for b in self.buckets:
            try:
                warmed += bool(warm(self.input_shape, b))
            except Exception:   # noqa: BLE001 — warm is best-effort
                log.exception("warm-up failed for endpoint %s "
                              "bucket %d", self.name, b)
        return warmed


class EndpointRegistry:
    """Name → :class:`Endpoint`; iteration order = registration order
    (the batcher's weighted round-robin is deterministic over it)."""

    def __init__(self):
        self._endpoints: Dict[str, Endpoint] = {}
        self._lock = threading.Lock()

    def register(self, name: str, model, **kwargs) -> Endpoint:
        return self.add(Endpoint(name, model, **kwargs))

    def add(self, ep: Endpoint) -> Endpoint:
        """Register a pre-built endpoint (how generative endpoints,
        which carry a decode slot pool, enter the registry)."""
        with self._lock:
            if ep.name in self._endpoints:
                raise ValueError(
                    f"serving endpoint {ep.name!r} already registered")
            self._endpoints[ep.name] = ep
        return ep

    def get(self, name: str) -> Optional[Endpoint]:
        with self._lock:
            return self._endpoints.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._endpoints)

    def __iter__(self) -> Iterator[Endpoint]:
        with self._lock:
            return iter(list(self._endpoints.values()))

    def __len__(self) -> int:
        with self._lock:
            return len(self._endpoints)

    def warm_all(self) -> Dict[str, int]:
        """Warm every endpoint's full bucket ladder; returns
        {endpoint: buckets warmed}."""
        out = {}
        for ep in self:
            t0 = time.perf_counter()
            n = ep.warm()
            out[ep.name] = n
            if n:
                log.info(
                    "endpoint %s: %d/%d buckets AOT-warm in %.2fs "
                    "(buckets=%s)", ep.name, n, len(ep.buckets),
                    time.perf_counter() - t0, ep.buckets)
        return out


class ModelExecutor:
    """Runs one composed batch for one endpoint and completes its
    requests.  Model/stack failures fail the batch's requests (the
    transports turn those into explicit error results) and never
    propagate — except process-fatal BaseExceptions, which the
    batcher re-raises after failing the requests."""

    def __init__(self):
        from analytics_zoo_tpu.observability import (
            get_registry, get_tracer)
        self._tracer = get_tracer()
        reg = get_registry()
        # the SAME fill-ratio gauge PR 1 introduced: real records over
        # the endpoint's full batch capacity (its largest bucket) —
        # the saturation signal the fleet autoscaler reads.  Bucket
        # padding waste is visible separately: bucket/records ride the
        # serving_execute span args.
        self._m_fill = reg.gauge(
            "serving_batch_fill_ratio",
            "real records / batch capacity of the last served batch")
        # monotonic batch id stamped on every request's batch_compose
        # station, so a waterfall can group co-riders of one device
        # batch across timelines
        self._batch_seq = 0

    def _mark_batch(self, requests: List, bucket: int,
                    real: int) -> None:
        """Station marks for a composed batch (no-op for untraced
        requests): ``batch_compose`` with batch id + fill ratio +
        co-rider count on the executor thread, closing the flow the
        transport thread opened at submit."""
        if not any(r.trace is not None for r in requests):
            return
        from analytics_zoo_tpu.observability.reqtrace import (
            get_request_log)
        self._batch_seq += 1
        reqlog = get_request_log()
        for r in requests:
            tid = r.trace_id
            if not tid:
                continue
            self._tracer.flow_end("serving_request", tid)
            reqlog.mark(tid, "batch_compose", batch=self._batch_seq,
                        fill=round(real / bucket, 4),
                        co_riders=real - 1)

    def execute(self, ep: Endpoint, requests: List) -> int:
        real = len(requests)
        if real == 0:
            return 0
        try:
            bucket = ep.bucket_for(real)
            self._mark_batch(requests, bucket, real)
            x = pad_to_batch(np.stack([r.data for r in requests]),
                             bucket)
            self._m_fill.set(real / ep.buckets[-1])
            traced = [r for r in requests if r.trace_id]
            if traced:
                from analytics_zoo_tpu.observability.reqtrace import (
                    get_request_log)
                reqlog = get_request_log()
                now = time.perf_counter()
                for r in traced:
                    reqlog.mark(r.trace_id, "dispatch", t=now,
                                bucket=bucket)
            with self._tracer.span(
                    "serving_execute", endpoint=ep.name, records=real,
                    bucket=bucket,
                    request_ids=[r.request_id for r in requests
                                 if r.request_id][:16]):
                out = np.asarray(ep.model.predict(x))[:real]
            if traced:
                now = time.perf_counter()
                for r in traced:
                    reqlog.mark(r.trace_id, "device_done", t=now)
            values = self.postprocess(out, ep.top_n)
        except Exception as e:
            log.exception("predict failed for endpoint %s "
                          "(%d records)", ep.name, real)
            for r in requests:
                r.fail(e)
            return 0
        for r, v in zip(requests, values):
            r.complete(v)
        ep.records_total += real
        return real

    def execute_decode(self, ep) -> int:
        """One decode-step scheduler iteration for a generative
        endpoint: step the active slots, retire EOS/budget-finished
        sequences, backfill freed slots from the queue — the stateful
        twin of :meth:`execute`.  Failure contract mirrors the
        stateless path: a model ``Exception`` fails exactly the
        sequences whose state shared the fused step program (the pool
        resets, the thread survives); a non-``Exception`` escape
        re-raises after failing them, so the Redis transport's loop
        dies with its batch un-acked — the PEL-reclaim trigger."""
        self._m_fill.set(ep.pool.active_count / ep.pool.capacity)
        try:
            with self._tracer.span(
                    "serving_decode_step", endpoint=ep.name,
                    active=ep.pool.active_count,
                    queued=len(ep.queue)):
                return ep.run_iteration()
        except Exception as e:
            log.exception("decode iteration failed for endpoint %s "
                          "(%d active)", ep.name,
                          ep.pool.active_count)
            ep.pool.fail_all(e)
            return 0
        except BaseException as e:   # noqa: BLE001 — process-death class
            ep.pool.fail_all(e)
            raise

    @staticmethod
    def postprocess(out: np.ndarray, top_n: int) -> List[List]:
        """Top-N softmax (the reference's PostProcessing.scala role):
        per record, ``[[class, prob], ...]`` descending."""
        exp = np.exp(out - out.max(axis=-1, keepdims=True))
        probs = exp / exp.sum(axis=-1, keepdims=True)
        top = np.argsort(-probs, axis=-1)[:, :top_n]
        return [[[int(i), float(p[i])] for i in t]
                for t, p in zip(top, probs)]
