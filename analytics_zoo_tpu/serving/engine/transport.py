"""HTTP/JSON fast-path transport.

The Redis stream is the bulk path: durable, exactly-once, replayable —
and a round trip costs an enqueue poll plus a result poll.  This
transport is the low-latency path for interactive callers: one POST
carries one record straight into the SAME engine queue the Redis loop
feeds, rides a continuously-batched device predict, and the response
returns on the same connection — no broker hop at all.  It keeps
working during a broker outage (the breaker only guards broker IO),
which is exactly when an orchestrator probing the fleet needs a live
predict path.

Contract (stdlib-only, JSON over ``ThreadingHTTPServer``):

* ``POST /predict/<endpoint>`` — body ``{"data": <nested list>,
  "dtype": "float32"?, "uri": str?, "request_id": str?}`` or
  ``{"npy_b64": <base64 .npy bytes>, ...}``.  200 →
  ``{"value": [[class, prob], ...], "request_id": ..., "endpoint":
  ...}``; 404 unknown endpoint, 400 undecodable payload, 500 predict
  error, 504 deadline.  (A stopped engine restarts on submit, so
  there is deliberately no "engine down" status.)
* ``GET /endpoints`` — the registry listing (name → buckets, top_n,
  weight, records served).

Each handler thread blocks on its own request's completion — HTTP
concurrency is the transport's in-flight window, the batcher decides
the device batching.
"""

from __future__ import annotations

import base64
import io
import json
import logging
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from analytics_zoo_tpu.serving.engine.batcher import Request
from analytics_zoo_tpu.serving.engine.core import DEFAULT_ENDPOINT

log = logging.getLogger("analytics_zoo_tpu.serving.engine")


def decode_payload(body: bytes):
    """JSON body → (ndarray, uri, request_id).  Raises ValueError on
    anything undecodable (the handler answers 400)."""
    try:
        doc = json.loads(body or b"{}")
    except json.JSONDecodeError as e:
        raise ValueError(f"bad JSON: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError("payload must be a JSON object")
    uri = str(doc.get("uri") or "")
    rid = doc.get("request_id") or uuid.uuid4().hex
    if "npy_b64" in doc:
        raw = base64.b64decode(doc["npy_b64"])
        arr = np.load(io.BytesIO(raw), allow_pickle=False)
    elif "data" in doc:
        arr = np.asarray(doc["data"],
                         dtype=np.dtype(doc.get("dtype") or "float32"))
    else:
        raise ValueError("payload needs 'data' or 'npy_b64'")
    return arr, uri, str(rid)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # noqa: A003 — stdlib API
        log.debug("http transport: " + fmt, *args)

    def _respond(self, code: int, doc: dict) -> None:
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:   # noqa: N802 — stdlib API
        path = self.path.split("?", 1)[0]
        engine = self.server.engine
        if path in ("/endpoints", "/"):
            out = {}
            for ep in engine.registry:
                out[ep.name] = {
                    "buckets": list(ep.buckets),
                    "top_n": ep.top_n,
                    "weight": ep.weight,
                    "records_total": ep.records_total,
                }
            self._respond(200, {"endpoints": out})
        else:
            self._respond(404, {"error": f"no route {path!r}"})

    def do_POST(self) -> None:   # noqa: N802 — stdlib API
        path = self.path.split("?", 1)[0]
        transport = self.server.transport
        if path != "/predict" and not path.startswith("/predict/"):
            self._respond(404, {"error": f"no route {path!r}"})
            return
        endpoint = path[len("/predict"):].strip("/") or DEFAULT_ENDPOINT
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        code, doc = transport.handle_predict(endpoint, body)
        self._respond(code, doc)


class HttpTransport:
    """The fast-path listener over one :class:`ServingEngine`."""

    def __init__(self, engine, port: int = 0,
                 host: str = "127.0.0.1",
                 timeout_s: float = 30.0):
        from analytics_zoo_tpu.observability import (
            get_registry, get_tracer)
        self.engine = engine
        self._host = host
        self._requested_port = int(port)
        self.timeout_s = float(timeout_s)
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._tracer = get_tracer()
        reg = get_registry()
        self._m_requests = reg.counter(
            "serving_http_requests_total",
            "HTTP fast-path requests by response class",
            labels=("status",))
        self._m_latency = reg.histogram(
            "serving_request_latency_seconds",
            "stream-arrival to result-write latency per record")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HttpTransport":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.engine = self.engine
        self._httpd.transport = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"zoo-serving-http:{self.port}")
        self._thread.start()
        log.info("serving HTTP fast path listening on %s:%d/predict",
                 self._host, self.port)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        self.port = None

    @property
    def url(self) -> Optional[str]:
        return (f"http://{self._host}:{self.port}"
                if self.port else None)

    # --------------------------------------------------------------- serve
    def handle_predict(self, endpoint: str, body: bytes):
        """One fast-path request → (http status, response doc).
        Separated from the handler class so tests can drive the full
        path without a socket."""
        import time
        t0 = time.perf_counter()
        try:
            arr, uri, rid = decode_payload(body)
        except ValueError as e:
            self._m_requests.labels("bad_request").inc()
            return 400, {"error": str(e)}
        if self.engine.registry.get(endpoint) is None:
            self._m_requests.labels("unknown_endpoint").inc()
            return 404, {
                "error": f"unknown endpoint {endpoint!r}",
                "endpoints": self.engine.endpoints()}
        req = Request(endpoint=endpoint, uri=uri, data=arr,
                      request_id=rid)
        with self._tracer.span("serving_http_predict",
                               endpoint=endpoint, request_id=rid):
            self.engine.submit_wait([req], timeout_s=self.timeout_s)
        if req.error is not None:
            timed_out = isinstance(req.error, TimeoutError)
            self._m_requests.labels(
                "timeout" if timed_out else "error").inc()
            return (504 if timed_out else 500), {
                "error": f"{type(req.error).__name__}: {req.error}",
                "request_id": rid, "endpoint": endpoint}
        self._m_latency.observe(time.perf_counter() - t0)
        self._m_requests.labels("ok").inc()
        return 200, {"value": req.result, "request_id": rid,
                     "endpoint": endpoint}
