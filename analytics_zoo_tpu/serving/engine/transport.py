"""HTTP/JSON fast-path transport.

The Redis stream is the bulk path: durable, exactly-once, replayable —
and a round trip costs an enqueue poll plus a result poll.  This
transport is the low-latency path for interactive callers: one POST
carries one record straight into the SAME engine queue the Redis loop
feeds, rides a continuously-batched device predict, and the response
returns on the same connection — no broker hop at all.  It keeps
working during a broker outage (the breaker only guards broker IO),
which is exactly when an orchestrator probing the fleet needs a live
predict path.

Contract (stdlib-only, JSON over ``ThreadingHTTPServer``):

* ``POST /predict/<endpoint>`` — body ``{"data": <nested list>,
  "dtype": "float32"?, "uri": str?, "request_id": str?}`` or
  ``{"npy_b64": <base64 .npy bytes>, ...}``.  200 →
  ``{"value": [[class, prob], ...], "request_id": ..., "endpoint":
  ...}``; 404 unknown endpoint, 400 undecodable payload, 500 predict
  error, 504 deadline.  (A stopped engine restarts on submit, so
  there is deliberately no "engine down" status.)
* ``POST /generate/<endpoint>`` — generative endpoints only: body as
  above (``data`` = the int token sequence) plus optional
  ``max_tokens``.  The response STREAMS (chunked transfer): one JSON
  line per token, ``{"token": t, "index": i}``, the moment the decode
  scheduler emits it, then a final line ``{"done": true, "tokens":
  [...], "request_id": ..., "endpoint": ...}`` (or ``{"error": ...}``
  if decode failed mid-stream).  Pre-stream failures use the predict
  status contract (400/404/504; 400 also for a non-generative
  endpoint).
* ``GET /endpoints`` — the registry listing (name → buckets, top_n,
  weight, records served; generative endpoints add slots/max_seq_len).

Each handler thread blocks on its own request's completion — HTTP
concurrency is the transport's in-flight window, the batcher decides
the device batching.
"""

from __future__ import annotations

import base64
import io
import itertools
import json
import logging
import socket
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from analytics_zoo_tpu.observability.reqtrace import (
    TRACE_HEADER, TraceContext, get_request_log)
from analytics_zoo_tpu.resilience.chaos import (
    SITE_SERVING_HTTP, InjectedFault, active_chaos)
from analytics_zoo_tpu.serving.engine.batcher import (Request,
                                                      ShedError)
from analytics_zoo_tpu.serving.engine.core import DEFAULT_ENDPOINT

log = logging.getLogger("analytics_zoo_tpu.serving.engine")


def decode_payload(body: bytes, default_dtype: str = "float32"):
    """JSON body → (ndarray, uri, request_id, doc).  Raises ValueError
    on anything undecodable (the handler answers 400)."""
    try:
        doc = json.loads(body or b"{}")
    except json.JSONDecodeError as e:
        raise ValueError(f"bad JSON: {e}") from e
    if not isinstance(doc, dict):
        raise ValueError("payload must be a JSON object")
    uri = str(doc.get("uri") or "")
    rid = doc.get("request_id") or uuid.uuid4().hex
    if "npy_b64" in doc:
        raw = base64.b64decode(doc["npy_b64"])
        arr = np.load(io.BytesIO(raw), allow_pickle=False)
    elif "data" in doc:
        arr = np.asarray(doc["data"], dtype=np.dtype(
            doc.get("dtype") or default_dtype))
    else:
        raise ValueError("payload needs 'data' or 'npy_b64'")
    return arr, uri, str(rid), doc


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):   # noqa: A003 — stdlib API
        log.debug("http transport: " + fmt, *args)

    def _respond(self, code: int, doc: dict) -> None:
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:   # noqa: N802 — stdlib API
        path = self.path.split("?", 1)[0]
        engine = self.server.engine
        if path in ("/endpoints", "/"):
            out = {}
            for ep in engine.registry:
                entry = {
                    "buckets": list(ep.buckets),
                    "top_n": ep.top_n,
                    "weight": ep.weight,
                    "records_total": ep.records_total,
                }
                if ep.generative:
                    entry.update(generative=True,
                                 slots=ep.pool.capacity,
                                 enc_len=ep.pool.enc_len,
                                 max_seq_len=ep.max_seq_len)
                out[ep.name] = entry
            self._respond(200, {"endpoints": out})
        else:
            self._respond(404, {"error": f"no route {path!r}"})

    def do_POST(self) -> None:   # noqa: N802 — stdlib API
        path = self.path.split("?", 1)[0]
        transport = self.server.transport
        # chaos site ``serving.http``: transport-layer faults, fired
        # BEFORE the request is even read.  A raising kind drops the
        # connection with no HTTP response (the network-disconnect
        # class the client's retry ladder must absorb); ``slow``
        # already slept inside trip — the straggling-proxy class.
        try:
            transport._trip_chaos()
        except InjectedFault:
            transport._m_requests.labels("chaos_dropped").inc()
            self.close_connection = True
            try:
                self.connection.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return
        for route in ("/predict", "/generate"):
            if path == route or path.startswith(route + "/"):
                break
        else:
            self._respond(404, {"error": f"no route {path!r}"})
            return
        endpoint = path[len(route):].strip("/") or DEFAULT_ENDPOINT
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length else b""
        trace_header = self.headers.get(TRACE_HEADER)
        if route == "/generate":
            transport.handle_generate(endpoint, body, self,
                                      trace_header=trace_header)
            return
        code, doc = transport.handle_predict(
            endpoint, body, trace_header=trace_header)
        self._respond(code, doc)

    # --------------------------------------------------- chunked streaming
    def start_stream(self, code: int = 200) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

    def stream_line(self, doc: dict) -> None:
        data = json.dumps(doc).encode() + b"\n"
        self.wfile.write(f"{len(data):X}\r\n".encode() + data
                         + b"\r\n")
        self.wfile.flush()

    def end_stream(self) -> None:
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()


class HttpTransport:
    """The fast-path listener over one :class:`ServingEngine`."""

    def __init__(self, engine, port: int = 0,
                 host: str = "127.0.0.1",
                 timeout_s: float = 30.0):
        from analytics_zoo_tpu.observability import (
            get_registry, get_tracer)
        self.engine = engine
        self._host = host
        self._requested_port = int(port)
        self.timeout_s = float(timeout_s)
        self.port: Optional[int] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # chaos-site step counter (``serving.http``): POSTs arrive on
        # handler threads — itertools.count.__next__ is GIL-atomic.
        # Steps reset per installed plan (the serving.redis
        # convention), so ``at_step=0, times=k`` always means "the
        # next k POSTs" no matter how much traffic ran before a
        # scenario armed its plan.
        self._chaos_seq = itertools.count()
        self._chaos_plan = None
        self._tracer = get_tracer()
        reg = get_registry()
        self._m_requests = reg.counter(
            "serving_http_requests_total",
            "HTTP fast-path requests by response class",
            labels=("status",))
        self._m_latency = reg.histogram(
            "serving_request_latency_seconds",
            "stream-arrival to result-write latency per record")

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HttpTransport":
        if self._httpd is not None:
            return self
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.engine = self.engine
        self._httpd.transport = self
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"zoo-serving-http:{self.port}")
        self._thread.start()
        log.info("serving HTTP fast path listening on %s:%d/predict",
                 self._host, self.port)
        return self

    def stop(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        self.port = None

    @property
    def url(self) -> Optional[str]:
        return (f"http://{self._host}:{self.port}"
                if self.port else None)

    def _trip_chaos(self) -> None:
        """Fire the ``serving.http`` site for one POST.  Step counts
        attempted POSTs since the CURRENT plan was installed (each new
        plan sees steps 0, 1, 2, … — mirroring
        ``BreakerClient._trip_chaos``)."""
        plan = active_chaos()
        if plan is None:
            self._chaos_plan = None
            return
        if plan is not self._chaos_plan:
            self._chaos_plan = plan
            self._chaos_seq = itertools.count()
        plan.trip(SITE_SERVING_HTTP, next(self._chaos_seq))

    # --------------------------------------------------------------- serve
    @staticmethod
    def _trace_begin(trace_header, rid: str, endpoint: str,
                     t0: float):
        """Build this request's TraceContext (the client's via
        :data:`TRACE_HEADER`, else a server-stamped one) and open its
        timeline with the HTTP arrival stations.  None when tracing is
        off or the header is malformed AND no context can be minted."""
        reqlog = get_request_log()
        if not reqlog.enabled:
            return None
        ctx = (TraceContext.from_wire(trace_header, request_id=rid)
               if trace_header else TraceContext.new(rid))
        if ctx is not None:
            reqlog.begin(ctx, transport="http", endpoint=endpoint,
                         station="transport_receive", t=t0)
            reqlog.mark(ctx, "decode")
        return ctx

    @staticmethod
    def _outcome_of(error) -> str:
        if error is None:
            return "ok"
        if isinstance(error, ShedError):
            return "shed"
        if isinstance(error, TimeoutError):
            return "timeout"
        return "error"

    def handle_predict(self, endpoint: str, body: bytes,
                       trace_header: Optional[str] = None):
        """One fast-path request → (http status, response doc).
        Separated from the handler class so tests can drive the full
        path without a socket (``trace_header`` stands in for the
        :data:`TRACE_HEADER` value ``do_POST`` forwards)."""
        import time
        t0 = time.perf_counter()
        try:
            arr, uri, rid, _doc = decode_payload(body)
        except ValueError as e:
            self._m_requests.labels("bad_request").inc()
            return 400, {"error": str(e)}
        ctx = self._trace_begin(trace_header, rid, endpoint, t0)
        reqlog = get_request_log()
        if self.engine.registry.get(endpoint) is None:
            self._m_requests.labels("unknown_endpoint").inc()
            reqlog.finish(ctx, "error", station="respond")
            return 404, {
                "error": f"unknown endpoint {endpoint!r}",
                "endpoints": self.engine.endpoints()}
        req = Request(endpoint=endpoint, uri=uri, data=arr,
                      request_id=rid, trace=ctx)
        with self._tracer.span("serving_http_predict",
                               endpoint=endpoint, request_id=rid):
            self.engine.submit_wait([req], timeout_s=self.timeout_s)
        if req.error is not None:
            timed_out = isinstance(req.error, TimeoutError)
            self._m_requests.labels(
                "timeout" if timed_out else "error").inc()
            reqlog.finish(ctx, self._outcome_of(req.error),
                          station="respond")
            return (504 if timed_out else 500), {
                "error": f"{type(req.error).__name__}: {req.error}",
                "request_id": rid, "endpoint": endpoint}
        self._m_latency.observe(
            time.perf_counter() - t0,
            exemplar=ctx.trace_id if ctx else None)
        self._m_requests.labels("ok").inc()
        reqlog.finish(ctx, "ok", station="respond")
        out = {"value": req.result, "request_id": rid,
               "endpoint": endpoint}
        if ctx is not None:
            out["trace_id"] = ctx.trace_id
        return 200, out

    def handle_generate(self, endpoint: str, body: bytes,
                        handler,
                        trace_header: Optional[str] = None) -> None:
        """One streaming generate request: validate, submit to the
        decode scheduler, and relay each emitted token onto the
        connection as a chunked JSON line the moment it arrives —
        inter-token latency on the wire tracks the device decode
        step, not the sequence.  ``handler`` is the live request
        handler (chunked writes need the socket)."""
        import queue as _queue
        import time
        t0 = time.perf_counter()
        try:
            arr, uri, rid, doc = decode_payload(body,
                                                default_dtype="int32")
        except ValueError as e:
            self._m_requests.labels("bad_request").inc()
            handler._respond(400, {"error": str(e)})
            return
        ctx = self._trace_begin(trace_header, rid, endpoint, t0)
        reqlog = get_request_log()
        ep = self.engine.registry.get(endpoint)
        if ep is None:
            self._m_requests.labels("unknown_endpoint").inc()
            reqlog.finish(ctx, "error", station="respond")
            handler._respond(404, {
                "error": f"unknown endpoint {endpoint!r}",
                "endpoints": self.engine.endpoints()})
            return
        if not ep.generative:
            self._m_requests.labels("bad_request").inc()
            reqlog.finish(ctx, "error", station="respond")
            handler._respond(400, {
                "error": f"endpoint {endpoint!r} is not generative; "
                         f"POST /predict/{endpoint} instead"})
            return
        try:
            max_tokens = int(doc["max_tokens"]) \
                if doc.get("max_tokens") else None
        except (TypeError, ValueError):
            self._m_requests.labels("bad_request").inc()
            reqlog.finish(ctx, "error", station="respond")
            handler._respond(400, {"error": "bad max_tokens"})
            return
        emitted: _queue.Queue = _queue.Queue()
        req = Request(endpoint=endpoint, uri=uri,
                      data=np.asarray(arr, np.int32).reshape(-1),
                      request_id=rid, max_tokens=max_tokens,
                      trace=ctx,
                      on_token=lambda i, t: emitted.put((i, t)))
        with self._tracer.span("serving_http_generate",
                               endpoint=endpoint, request_id=rid):
            self.engine.submit([req])
            # INACTIVITY deadline, reset on every token: a healthy
            # stream still emitting must never be killed for total
            # duration — only a stall of timeout_s with no tokens is
            # a timeout (and a pre-stream stall still gets a clean
            # 504 status line)
            deadline = time.monotonic() + self.timeout_s
            streaming = False
            try:
                while True:
                    try:
                        i, tok = emitted.get(timeout=0.05)
                    except _queue.Empty:
                        if req.done:
                            break
                        if time.monotonic() >= deadline:
                            req.fail(TimeoutError(
                                f"no tokens within "
                                f"{self.timeout_s:.1f}s"))
                            break
                        continue
                    deadline = time.monotonic() + self.timeout_s
                    if not streaming:
                        handler.start_stream()
                        streaming = True
                    handler.stream_line({"token": tok, "index": i})
                # drain stragglers emitted between the last get and
                # completion so the final token count matches
                while True:
                    try:
                        i, tok = emitted.get_nowait()
                    except _queue.Empty:
                        break
                    if streaming:
                        handler.stream_line({"token": tok,
                                             "index": i})
                if req.error is not None:
                    timed_out = isinstance(req.error, TimeoutError)
                    self._m_requests.labels(
                        "timeout" if timed_out else "error").inc()
                    reqlog.finish(ctx, self._outcome_of(req.error),
                                  station="respond")
                    err = {"error": f"{type(req.error).__name__}: "
                                    f"{req.error}",
                           "request_id": rid, "endpoint": endpoint}
                    if streaming:
                        handler.stream_line(err)
                        handler.end_stream()
                    else:
                        handler._respond(504 if timed_out else 500,
                                         err)
                    return
                if not streaming:
                    handler.start_stream()
                done_line = {"done": True,
                             "tokens": req.result,
                             "request_id": rid,
                             "endpoint": endpoint}
                if ctx is not None:
                    done_line["trace_id"] = ctx.trace_id
                handler.stream_line(done_line)
                handler.end_stream()
                self._m_latency.observe(
                    time.perf_counter() - t0,
                    exemplar=ctx.trace_id if ctx else None)
                self._m_requests.labels("ok").inc()
                reqlog.finish(ctx, "ok", station="respond")
            except (BrokenPipeError, ConnectionError, OSError):
                # the client hung up mid-stream: mark the request done
                # so the scheduler's abandoned-sweep retires its slot
                # instead of decoding tokens nobody reads — a burst of
                # disconnects must not pin the pool full of dead
                # sequences until max_seq_len
                if not req.done:
                    req.fail(ConnectionError(
                        "generate client disconnected mid-stream"))
                log.debug("generate stream client disconnect "
                          "(endpoint %s, request %s)", endpoint, rid)
                self._m_requests.labels("client_gone").inc()
                reqlog.finish(ctx, "error", station="respond",
                              cause="client_gone")
